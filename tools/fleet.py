"""Fleet supervisor: launch, watch, and survivor-elastic-relaunch a
multi-process fit.

The reference inherited this from YARN — a lost executor was re-requested
and Spark's lineage replayed its partitions. The TPU port's fleets are N
long-lived jax.distributed processes whose collectives WEDGE when a
member dies mid-program, so supervision is explicit:

1. **launch** — N worker processes join a gloo/grpc rendezvous
   (``parallel.multihost.initialize`` with bounded retry) and run the
   streamed entity-sharded fit with COORDINATED checkpoints
   (``game.checkpoint`` quorum manifests) at every chunk boundary;
2. **watch** — exit codes plus the heartbeat-file liveness protocol
   (``proc-<i>.alive`` touched on a cadence; staleness beyond a deadline
   = dead). A member exiting with the injection code 113 (or losing its
   heartbeat) marks its host LOST;
3. **stop the survivors** — SIGTERM requests the boundary stop
   (``GracefulStop`` + the ``fleet_any`` collective agreement make every
   member stop at the SAME boundary); members wedged in a collective
   against a dead partner cannot reach the boundary, so after a grace
   period the supervisor escalates to SIGKILL — their progress since the
   last certified checkpoint is lost, and that is fine, because chunks
   replay deterministically;
4. **relaunch on the survivors** — a new, smaller fleet restores the
   newest CERTIFIED checkpoint via ``restore_placed()`` (the entity axis
   re-sliced onto the shrunken mesh) and recomputes its per-host splits
   deterministically (``ingest.planner.plans_for_host`` /
   ``multihost.process_slice``) — the dead host's work lands on
   survivors with no coordination state.

An external SIGTERM to ONE member (preemption) propagates through the
same boundary agreement: every member writes the coordinated final
checkpoint and exits 75 — interrupted, not relaunched.

Fleet observability (ISSUE 13): workers are launched with
``PHOTON_PROC_ID``/``PHOTON_TRACE_OUT``/``PHOTON_TELEMETRY_OUT`` so each
member writes its OWN suffixed artifact stream, one directory per
generation (``<workdir>/telemetry/gen<g>/trace.proc-<i>.jsonl``, … —
relaunches renumber members, so generations must not share files) plus
progress heartbeats — the input of ``cli report --fleet``; ``--status-file`` /
``--status-port`` publish the live supervisor snapshot an operator polls
(member liveness from heartbeat mtimes, last heartbeat fields per
member, deaths/relaunches, generation —
``photon_ml_tpu.parallel.fleet_status``).

CLI::

    python -m tools.fleet --workdir /tmp/fleet                # supervise
    python -m tools.fleet --workdir /tmp/fleet \
        --status-file /tmp/fleet/status.json --status-port 0  # + live status
    python -m tools.fleet --worker --proc 0 --nproc 2 ...     # (internal)

tools/chaos.py drives this harness for the DISTRIBUTED crash matrix:
one member hard-killed at each fleet fault seam, the survivor-resumed
fit's final loss checked against the uninterrupted fleet reference.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

#: worker fit shape — shared with tools.chaos so the fleet reference and
#: the single-process matrix solve the same problem
N_ENTITIES = 16
N_ROWS = 8
DIM = 4
N_CHUNKS = 4
DATA_SEED = 20260803

#: exit code of a graceful boundary stop (cli train's "interrupted,
#: restart me" convention)
GRACEFUL_EXIT_CODE = 75

#: a worker that NOTICED the fleet break (a collective failed against a
#: dead peer) exits with this code via ``os._exit`` — unwinding normally
#: would wedge in jax's atexit distributed-shutdown barrier against the
#: very peer that died. The supervisor reads it as "host fine, fleet
#: broken": the member relaunches in the next generation.
FLEET_ABORT_EXIT_CODE = 76


def make_problem():
    """The deterministic worker problem ``(X, y)``: every fleet member —
    and the chaos matrix's reference scorer — generates the SAME data
    from DATA_SEED, so there is exactly one definition to drift."""
    import numpy as np

    rng = np.random.default_rng(DATA_SEED)
    X = rng.normal(size=(N_ENTITIES, N_ROWS, DIM))
    W = rng.normal(size=(N_ENTITIES, DIM))
    z = np.einsum("erk,ek->er", X, W)
    y = (rng.random((N_ENTITIES, N_ROWS)) < 1 / (1 + np.exp(-z))).astype(
        np.float32
    )
    return X.astype(np.float32), y


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class FleetSpec:
    """One supervised fleet run (including any survivor relaunches)."""

    workdir: str
    num_processes: int = 2
    devices_per_process: int = 2
    heartbeat_every_s: float = 0.25
    #: staleness beyond which a member with no exit code counts dead
    heartbeat_deadline_s: float = 5.0
    #: how long survivors get to reach their boundary stop after SIGTERM
    #: before the supervisor escalates to SIGKILL
    grace_s: float = 12.0
    #: coordinated-checkpoint quorum wait inside the workers (kept well
    #: under grace_s so an abandoned save resolves before escalation)
    quorum_timeout_s: float = 4.0
    max_relaunches: int = 2
    timeout_s: float = 600.0
    #: fault plan armed onto EXACTLY ONE member (the victim) of the
    #: first generation — the chaos harness's kill switch
    victim_plan: Optional[dict] = None
    victim_process: int = 1
    #: deliver SIGTERM to this member this many seconds after its FIRST
    #: heartbeat (external preemption of one host; None = never).
    #: Anchoring on the heartbeat — not launch — keeps the signal inside
    #: the fit whatever jax import/compile latency the box has
    sigterm_after_s: Optional[float] = None
    sigterm_process: int = 0
    #: test-only: stretch each chunk boundary so mid-fit signals land
    chunk_sleep_s: float = 0.0
    #: which member the chunk sleep applies to (-1 = all) — sleeping ONE
    #: member makes it arrive last at every fleet_any barrier, i.e. a
    #: deterministic straggler for the collective-wait attribution tests
    chunk_sleep_proc: int = -1
    #: how a lost host is recognized: "exit_code" marks a member lost the
    #: moment it exits with the injection code 113; "heartbeat" ignores
    #: that fast path and waits for the member's ``proc-<i>.alive`` file
    #: to go stale — the pure liveness-protocol detection (the matrix's
    #: ``fleet.heartbeat`` row runs this mode so staleness detection is
    #: itself crash-proven)
    detect_by: str = "exit_code"
    #: per-member telemetry artifact streams (fleet observability): when
    #: True, every worker gets PHOTON_PROC_ID/PHOTON_TRACE_OUT/
    #: PHOTON_TELEMETRY_OUT pointed into ``telemetry_dir`` (default
    #: <workdir>/telemetry), so the run leaves trace.proc-<i>.jsonl +
    #: telemetry.proc-<i>.jsonl behind — the input of
    #: ``cli report --fleet``
    telemetry: bool = True
    telemetry_dir: Optional[str] = None
    #: worker-side progress-heartbeat cadence (the telemetry JSONL lines
    #: the live status tail-parses; distinct from the liveness-file touch)
    progress_heartbeat_every_s: float = 1.0
    #: live supervisor status (photon_ml_tpu.parallel.fleet_status): a
    #: JSON snapshot written atomically to status_file and/or served on
    #: http://127.0.0.1:<status_port>/statusz every status_interval_s
    status_file: Optional[str] = None
    status_port: Optional[int] = None
    status_interval_s: float = 1.0

    def resolved_telemetry_dir(self) -> Optional[str]:
        if not self.telemetry:
            return None
        return self.telemetry_dir or os.path.join(self.workdir, "telemetry")

    def generation_telemetry_dir(self, generation: int) -> Optional[str]:
        """One artifact directory PER GENERATION (``telemetry/gen0``, …):
        a relaunched fleet renumbers its members, so an unqualified path
        would let the new proc 0 truncate the DEAD member's stream and
        FleetReport would read the killed member as complete. One
        directory = one generation's fleet is the aggregation contract
        (``cli report --fleet <dir>/gen<g>``)."""
        d = self.resolved_telemetry_dir()
        return None if d is None else os.path.join(d, f"gen{generation}")

    def telemetry_out_base(self, generation: int) -> Optional[str]:
        """The UNSUFFIXED telemetry JSONL path generation ``g``'s workers
        point PHOTON_TELEMETRY_OUT at (identity suffixes it per member);
        also what the status writer tail-parses."""
        d = self.generation_telemetry_dir(generation)
        return None if d is None else os.path.join(d, "telemetry.jsonl")


def _worker_env(
    spec: FleetSpec, proc: int, nproc: int, armed: bool, generation: int
) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(
        f"--xla_force_host_platform_device_count={spec.devices_per_process}"
    )
    env["XLA_FLAGS"] = " ".join(flags)
    env.pop("PHOTON_FAULT_PLAN", None)
    if armed and spec.victim_plan is not None:
        env["PHOTON_FAULT_PLAN"] = json.dumps(spec.victim_plan)
    # fleet identity + per-member artifact streams: identity BEFORE jax
    # imports (telemetry.identity reads PHOTON_PROC_ID), artifact env
    # suffixed per member by telemetry.configure_from_env in the worker
    env["PHOTON_PROC_ID"] = str(proc)
    env["PHOTON_PROC_COUNT"] = str(nproc)
    telemetry_dir = spec.generation_telemetry_dir(generation)
    if telemetry_dir is not None:
        env["PHOTON_TRACE_OUT"] = os.path.join(telemetry_dir, "trace.jsonl")
        env["PHOTON_TELEMETRY_OUT"] = spec.telemetry_out_base(generation)
    else:
        env.pop("PHOTON_TRACE_OUT", None)
        env.pop("PHOTON_TELEMETRY_OUT", None)
    return env


@dataclasses.dataclass
class _Member:
    proc: subprocess.Popen
    process_id: int
    out_path: str
    err_path: str
    rc: Optional[int] = None
    lost_host: bool = False  # exited 113 / heartbeat-stale-killed


def _launch_generation(
    spec: FleetSpec, generation: int, nproc: int, arm_victim: bool
) -> list[_Member]:
    fleet_dir = os.path.join(spec.workdir, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    telemetry_dir = spec.generation_telemetry_dir(generation)
    if telemetry_dir is not None:
        os.makedirs(telemetry_dir, exist_ok=True)
    # stale liveness files from the previous generation must not mask a
    # new member's death (mtime staleness is the signal)
    for name in os.listdir(fleet_dir):
        if name.endswith(".alive"):
            try:
                os.unlink(os.path.join(fleet_dir, name))
            except OSError:
                pass
    port = _free_port() if nproc > 1 else 0
    members = []
    for pid in range(nproc):
        out_path = os.path.join(
            spec.workdir, f"gen{generation}-proc{pid}.out"
        )
        err_path = os.path.join(
            spec.workdir, f"gen{generation}-proc{pid}.err"
        )
        armed = arm_victim and pid == spec.victim_process
        argv = [
            sys.executable, "-m", "tools.fleet", "--worker",
            "--proc", str(pid), "--nproc", str(nproc),
            "--port", str(port), "--dir", spec.workdir,
            "--quorum-timeout", str(spec.quorum_timeout_s),
            "--heartbeat-every", str(spec.heartbeat_every_s),
            "--progress-heartbeat-every",
            str(spec.progress_heartbeat_every_s),
            "--chunk-sleep", str(spec.chunk_sleep_s),
            "--chunk-sleep-proc", str(spec.chunk_sleep_proc),
        ]
        with open(out_path, "wb") as out, open(err_path, "wb") as err:
            proc = subprocess.Popen(
                argv,
                env=_worker_env(spec, pid, nproc, armed, generation),
                cwd=_repo_root(),
                stdout=out,
                stderr=err,
            )
        members.append(_Member(proc, pid, out_path, err_path))
    return members


def _signal_all(members: list[_Member], sig) -> None:
    for m in members:
        if m.proc.poll() is None:
            try:
                m.proc.send_signal(sig)
            except OSError:
                pass


def _supervise_generation(
    spec: FleetSpec, generation: int, nproc: int, deadline: float,
    status=None,
) -> dict:
    """Run one fleet generation to completion; the per-generation record
    (exit codes, detected deaths, whether escalation was needed)."""
    from photon_ml_tpu.parallel import multihost

    fleet_dir = os.path.join(spec.workdir, "fleet")
    members = _launch_generation(
        spec, generation, nproc, arm_victim=generation == 0
    )
    if status is not None:
        # per-generation state resets; the cumulative death_history is
        # run_fleet's to maintain (it survives relaunches)
        status.update(generation=generation, num_processes=nproc,
                      rcs={}, deaths=[], outcome=None,
                      telemetry_out=spec.telemetry_out_base(generation))
    started = time.monotonic()
    sigterm_sent = False
    sigterm_anchor: Optional[float] = None
    stopping = False
    stop_started = 0.0
    escalated: list[int] = []
    try:
        while True:
            now = time.monotonic()
            if now > deadline:
                _signal_all(members, signal.SIGKILL)
                for m in members:
                    m.proc.wait()
                    m.rc = m.proc.returncode
                return {
                    "generation": generation,
                    "num_processes": nproc,
                    "rcs": {m.process_id: m.rc for m in members},
                    "outcome": "timeout",
                    "escalated": escalated,
                }
            # external-preemption injection: SIGTERM one member mid-fit,
            # anchored on its first heartbeat so the signal lands inside
            # the fit regardless of jax import/compile latency
            if spec.sigterm_after_s is not None and not sigterm_sent:
                if sigterm_anchor is None and os.path.exists(
                    multihost.heartbeat_path(
                        fleet_dir, spec.sigterm_process
                    )
                ):
                    sigterm_anchor = now
                if (
                    sigterm_anchor is not None
                    and now - sigterm_anchor >= spec.sigterm_after_s
                ):
                    for m in members:
                        if (
                            m.process_id == spec.sigterm_process
                            and m.proc.poll() is None
                        ):
                            m.proc.send_signal(signal.SIGTERM)
                    sigterm_sent = True
            # collect exits. Exit-code classification: 113 (the injected
            # preemption/OOM-kill code) = host LOST; 76 = this member
            # noticed the fleet break and bailed (host retained); other
            # unexpected codes are crashes on a retained host.
            for m in members:
                if m.rc is None and m.proc.poll() is not None:
                    m.rc = m.proc.returncode
                    if m.rc == 113 and spec.detect_by == "exit_code":
                        m.lost_host = True
            # heartbeat staleness: the liveness-protocol detection. A
            # stale member that never delivered an exit code is a dead
            # or wedged HOST — reclaim (SIGKILL) and mark it lost.
            if now - started > spec.heartbeat_deadline_s:
                for pid in multihost.dead_peers(
                    fleet_dir, nproc, spec.heartbeat_deadline_s
                ):
                    m = members[pid]
                    if m.lost_host:
                        continue
                    if m.rc is None and m.proc.poll() is None:
                        m.proc.send_signal(signal.SIGKILL)
                        m.proc.wait()
                        m.rc = m.proc.returncode
                        m.lost_host = True
                        escalated.append(pid)
                    elif spec.detect_by == "heartbeat" and m.rc == 113:
                        # heartbeat-mode: the lost-host verdict waited
                        # for the file to go stale, not the exit code
                        m.lost_host = True
            lost = [m for m in members if m.lost_host]
            broken = [
                m for m in members
                if m.rc is not None
                and m.rc not in (0, GRACEFUL_EXIT_CODE)
                and m.process_id not in escalated
            ]
            alive = [m for m in members if m.rc is None]
            if (lost or broken) and not stopping:
                # member death (or a broken-fleet bail): stop the
                # survivors at their next boundary. Death COUNTING
                # happens in run_fleet over the generation's final
                # verdict — a broken-only stop is not a member death.
                stopping = True
                stop_started = now
                _signal_all(members, signal.SIGTERM)
            if (
                stopping
                and alive
                and now - stop_started > spec.grace_s
                and not any(m.process_id in escalated for m in alive)
            ):
                # survivors wedged in a collective against the dead
                # member can never reach the boundary — reclaim them;
                # the certified-checkpoint replay makes this lossless
                for m in alive:
                    escalated.append(m.process_id)
                _signal_all(members, signal.SIGKILL)
            if status is not None:
                # keep the live snapshot truthful mid-generation: exit
                # codes and detected deaths as they land (liveness itself
                # is pulled from heartbeat mtimes by the status thread)
                status.update(
                    rcs={m.process_id: m.rc for m in members
                         if m.rc is not None},
                    deaths=[m.process_id for m in members if m.lost_host],
                )
            if not alive:
                break
            time.sleep(0.05)
    finally:
        for m in members:
            if m.proc.poll() is None:
                m.proc.kill()
            m.proc.wait()
            if m.rc is None:
                m.rc = m.proc.returncode
    if spec.detect_by == "heartbeat":
        # pure liveness-protocol mode: the lost-host verdict comes ONLY
        # from proc-<i>.alive staleness. A fast fleet can finish (every
        # member exited) before the victim's file ever goes stale, so
        # resolve pending verdicts here — the victim is dead, its file
        # WILL stale out within one deadline
        pending = [m for m in members if m.rc == 113 and not m.lost_host]
        resolve_by = time.monotonic() + spec.heartbeat_deadline_s * 2
        while pending and time.monotonic() < resolve_by:
            stale = multihost.dead_peers(
                fleet_dir, nproc, spec.heartbeat_deadline_s
            )
            for m in pending:
                if m.process_id in stale:
                    m.lost_host = True
            pending = [m for m in pending if not m.lost_host]
            if pending:
                time.sleep(0.1)
    rcs = {m.process_id: m.rc for m in members}
    deaths = [m.process_id for m in members if m.lost_host]
    if deaths:
        outcome = "member_death"
    elif all(r == 0 for r in rcs.values()):
        outcome = "complete"
    elif all(r in (0, GRACEFUL_EXIT_CODE) for r in rcs.values()):
        outcome = "interrupted"
    else:
        outcome = "failed"
    return {
        "generation": generation,
        "num_processes": nproc,
        "rcs": rcs,
        "deaths": deaths,
        "outcome": outcome,
        "escalated": escalated,
    }


def run_fleet(spec: FleetSpec) -> dict:
    """Supervise a fit to completion across member loss: launch, watch,
    boundary-stop, relaunch on survivors. JSON-safe report; ``ok`` means
    the fit COMPLETED (survivor resume counts; a graceful external
    interruption reports ``interrupted`` instead)."""
    from photon_ml_tpu import telemetry

    os.makedirs(spec.workdir, exist_ok=True)
    deadline = time.monotonic() + spec.timeout_s
    nproc = spec.num_processes
    generations = []
    relaunches = 0
    report: dict = {"workdir": spec.workdir, "generations": generations}
    status = None
    if spec.status_file is not None or spec.status_port is not None:
        from photon_ml_tpu.parallel.fleet_status import FleetStatusWriter

        status = FleetStatusWriter(
            fleet_dir=os.path.join(spec.workdir, "fleet"),
            num_processes=nproc,
            heartbeat_deadline_s=spec.heartbeat_deadline_s,
            status_file=spec.status_file,
            port=spec.status_port,
            telemetry_out=spec.telemetry_out_base(0),
            interval_s=spec.status_interval_s,
        ).start()
        report["status_port"] = status.port
        report["status_file"] = spec.status_file
    death_history: list = []
    try:
        while True:
            gen = _supervise_generation(
                spec, len(generations), nproc, deadline, status=status
            )
            generations.append(gen)
            death_history.extend(
                {"generation": gen["generation"], "process_id": pid}
                for pid in gen.get("deaths") or ()
            )
            if status is not None:
                status.update(
                    rcs=gen["rcs"], deaths=gen.get("deaths") or [],
                    death_history=list(death_history),
                    outcome=gen["outcome"],
                )
            if gen.get("deaths"):
                telemetry.counter("recovery.fleet_member_deaths").inc(
                    len(gen["deaths"])
                )
            if gen["outcome"] == "complete":
                report.update(ok=True, interrupted=False)
                break
            if gen["outcome"] == "interrupted":
                report.update(ok=False, interrupted=True)
                break
            if gen["outcome"] in ("timeout", "failed") and not gen.get(
                "deaths"
            ):
                report.update(ok=False, interrupted=False)
                break
            survivors = nproc - len(gen["deaths"])
            if survivors < 1 or relaunches >= spec.max_relaunches:
                report.update(ok=False, interrupted=False)
                break
            relaunches += 1
            telemetry.counter("recovery.fleet_relaunches").inc()
            if status is not None:
                status.update(relaunches=relaunches)
            nproc = survivors
    finally:
        if status is not None:
            status.stop()
    report["relaunches"] = relaunches
    report["deaths_total"] = sum(
        len(g.get("deaths") or ()) for g in generations
    )
    report["final_path"] = os.path.join(spec.workdir, "final.npy")
    if spec.resolved_telemetry_dir() is not None:
        # one artifact dir PER GENERATION (relaunches renumber members);
        # `telemetry_dir` points at the newest generation's — the one a
        # completed run's fleet report reads
        dirs = [
            spec.generation_telemetry_dir(g)
            for g in range(len(generations))
        ]
        report["telemetry_dirs"] = dirs
        report["telemetry_dir"] = dirs[-1]
    return report


# ---------------------------------------------------------------------------
# serving-fleet supervision (shard-owning members + in-process router)
# ---------------------------------------------------------------------------


def make_serving_model(
    registry_dir: str,
    n_entities: int = 48,
    fe_dim: int = 4,
    re_dim: int = 3,
    n_buckets: int = 2,
    task: str = "logistic",
    seed: int = 20260807,
) -> str:
    """Build and publish one small deterministic GAME model (FE
    ``global`` + per-``userId`` RE over ``n_entities`` entities) into
    ``registry_dir``; returns the published version directory. The
    serving chaos matrix, bench, and the e2e fleet test all share this
    builder so their subprocess members score the same coefficients."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.game.models import (
        FixedEffectModel,
        GameModel,
        RandomEffectBucketModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.serving import publish_version

    rng = np.random.default_rng(seed)
    fe = FixedEffectModel(
        coefficients=jnp.asarray(rng.normal(size=fe_dim), jnp.float32),
        shard_name="global",
    )
    w_users = rng.normal(size=(n_entities, re_dim))
    entity_bucket = (np.arange(n_entities) % n_buckets).astype(np.int64)
    entity_pos = np.zeros(n_entities, np.int64)
    buckets = []
    for b in range(n_buckets):
        codes_b = np.nonzero(entity_bucket == b)[0]
        entity_pos[codes_b] = np.arange(len(codes_b))
        proj = np.tile(np.arange(re_dim, dtype=np.int32), (len(codes_b), 1))
        buckets.append(
            RandomEffectBucketModel(
                coefficients=jnp.asarray(w_users[codes_b], jnp.float32),
                projection=jnp.asarray(proj),
                entity_codes=jnp.asarray(codes_b, jnp.int32),
            )
        )
    re_model = RandomEffectModel(
        id_name="userId",
        shard_name="user",
        buckets=tuple(buckets),
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        vocab=np.arange(n_entities),
    )
    model = GameModel(task=task, models={"fixed": fe, "perUser": re_model})
    index_maps = {
        "global": [f"g{j}" for j in range(fe_dim)],
        "user": [f"u{j}" for j in range(re_dim)],
    }
    return publish_version(registry_dir, model, index_maps)


@dataclasses.dataclass
class ServingFleetSpec:
    """One supervised SERVING fleet run: N shard-owning ``cli serve
    --member`` processes, an in-process :class:`FleetRouter` driving
    sustained traffic, and the same heartbeat/relaunch supervision the
    training fleet uses — plus live elastic resizes through the
    stage/commit barrier."""

    workdir: str
    #: published model directory (feature-indexes/ + model-metadata.json)
    model_dir: str
    fleet_size: int = 3
    max_batch: int = 64
    #: per-member slice HBM budget (the fleet's reason to exist); None
    #: skips enforcement
    hbm_budget_mb: Optional[float] = None
    heartbeat_every_s: float = 0.25
    #: staleness beyond which a member with no exit code counts dead
    heartbeat_deadline_s: float = 3.0
    #: how long one member gets to load + warm + announce
    warm_timeout_s: float = 180.0
    timeout_s: float = 600.0
    #: router fan-out timeout per member call
    member_timeout_s: float = 3.0
    router_refresh_s: float = 0.15
    # -- sustained traffic the supervisor drives through the router
    traffic_seconds: float = 6.0
    traffic_rows: int = 8
    traffic_hz: float = 20.0
    #: dense feature noise synthesized onto traffic rows as
    #: ``((shard_name, n_cols), ...)`` — each row gets ``[col, value]``
    #: pairs for cols [0, n_cols) on that shard (the bench/test owns the
    #: model, so it knows the feature space; empty = ids-only rows)
    traffic_features: tuple = ()
    rng_seed: int = 20260807
    # -- hard-kill one member mid-traffic (None = no kill)
    kill_member: Optional[int] = None
    kill_after_s: float = 1.5
    relaunch: bool = True
    # -- live resize schedule: [(after_s, new_fleet_size), ...]
    resizes: tuple = ()
    # -- fault plan armed onto exactly one member's environment
    victim_plan: Optional[dict] = None
    victim_member: int = 1
    # -- live status surface (parallel.fleet_status)
    status_file: Optional[str] = None
    status_port: Optional[int] = None
    status_interval_s: float = 0.5
    #: router-side head sampling: mint a sampled trace context every Nth
    #: routed batch (0 = never; slow/error/degraded requests still
    #: persist via tail sampling)
    trace_sample_every: int = 0

    def announce_dir(self) -> str:
        return os.path.join(self.workdir, "announce")

    def fleet_dir(self) -> str:
        return os.path.join(self.workdir, "fleet")

    def telemetry_base(self) -> str:
        return os.path.join(self.workdir, "telemetry", "serving.jsonl")

    def trace_base(self) -> str:
        return os.path.join(self.workdir, "telemetry", "trace.jsonl")


@dataclasses.dataclass
class _ServingMember:
    proc: subprocess.Popen
    member: int
    fleet_size: int
    epoch: int
    out_path: str
    err_path: str
    rc: Optional[int] = None


def _serving_member_env(spec: ServingFleetSpec, member: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PHOTON_PROC_ID"] = str(member)
    env.pop("PHOTON_FAULT_PLAN", None)
    if spec.victim_plan is not None and member == spec.victim_member:
        env["PHOTON_FAULT_PLAN"] = json.dumps(spec.victim_plan)
    return env


def _launch_serving_member(
    spec: ServingFleetSpec, member: int, fleet_size: int, epoch: int
) -> _ServingMember:
    from photon_ml_tpu.telemetry import identity

    os.makedirs(spec.workdir, exist_ok=True)
    os.makedirs(os.path.dirname(spec.telemetry_base()), exist_ok=True)
    out_path = os.path.join(spec.workdir, f"member{member}-e{epoch}.out")
    err_path = os.path.join(spec.workdir, f"member{member}-e{epoch}.err")
    argv = [
        sys.executable, "-m", "photon_ml_tpu.cli", "serve",
        "--model-dir", spec.model_dir,
        "--member", str(member),
        "--fleet-size", str(fleet_size),
        "--announce-dir", spec.announce_dir(),
        "--epoch", str(epoch),
        "--host", "127.0.0.1", "--port", "0",
        "--max-batch", str(spec.max_batch),
        "--heartbeat-dir", spec.fleet_dir(),
        "--telemetry-out",
        identity.member_artifact_path(spec.telemetry_base(), member),
        # kill-safe span stream: PHOTON_PROC_ID in the member env makes
        # cli serve suffix this to trace.proc-<member>.jsonl, and the
        # supervisor harvests it into flight-proc-<member>.json when the
        # member dies without draining
        "--trace-out", spec.trace_base(),
    ]
    if spec.hbm_budget_mb is not None:
        argv += ["--hbm-budget-mb", str(spec.hbm_budget_mb)]
    with open(out_path, "wb") as out, open(err_path, "wb") as err:
        proc = subprocess.Popen(
            argv,
            env=_serving_member_env(spec, member),
            cwd=_repo_root(),
            stdout=out,
            stderr=err,
        )
    return _ServingMember(
        proc, member, fleet_size, epoch, out_path, err_path
    )


def _admin_post(url: str, op: str, payload: dict, timeout_s: float) -> dict:
    import urllib.request

    req = urllib.request.Request(
        f"{url}/v1/admin/{op}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def _wait_for_epoch(
    spec: ServingFleetSpec, epoch: int, fleet_size: int, deadline: float
) -> dict:
    """Block until every member of ``(epoch, fleet_size)`` has announced
    ready; returns {member: record}."""
    from photon_ml_tpu.serving import scan_announce

    want = set(range(fleet_size))
    records: dict[int, dict] = {}
    while time.monotonic() < deadline:
        records = {
            int(r["member"]): r
            for r in scan_announce(spec.announce_dir())
            if int(r.get("epoch", -1)) == epoch
            and int(r.get("fleet_size", -1)) == fleet_size
            and r.get("ready")
        }
        if set(records) == want:
            return records
        time.sleep(0.1)
    raise TimeoutError(
        f"serving fleet epoch {epoch} (size {fleet_size}) incomplete "
        f"after warm timeout; have {sorted(records)}"
    )


class _TrafficDriver:
    """Sustained closed-loop traffic through the router on a thread:
    per-request wall latency samples with timestamps, so disturbance
    windows (kill, resize) can be cut out and compared afterward."""

    def __init__(self, router, rows_fn, hz: float):
        import threading

        self.router = router
        self.rows_fn = rows_fn
        self.period_s = 1.0 / max(hz, 0.1)
        self.samples: list = []  # (t_rel, latency_ms, rows)
        self.failures: list = []  # (t_rel, error string)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serving-traffic", daemon=True
        )
        self.t0 = 0.0

    def start(self) -> "_TrafficDriver":
        self.t0 = time.monotonic()
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            rows = self.rows_fn()
            t_start = time.monotonic()
            try:
                self.router.score_rows(rows)
                self.samples.append(
                    (
                        round(t_start - self.t0, 4),
                        round((time.monotonic() - t_start) * 1000.0, 3),
                        len(rows),
                    )
                )
            except Exception as e:  # noqa: BLE001 — a non-shed failure IS the finding
                self.failures.append(
                    (round(t_start - self.t0, 4), f"{type(e).__name__}: {e}")
                )
            rest = self.period_s - (time.monotonic() - t_start)
            if rest > 0:
                self._stop.wait(rest)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)

    def p99_between(self, t_lo: float, t_hi: float) -> Optional[float]:
        import numpy as np

        lat = [s[1] for s in self.samples if t_lo <= s[0] < t_hi]
        if not lat:
            return None
        return float(np.percentile(np.asarray(lat), 99))


def _traffic_rows_fn(spec: ServingFleetSpec, lookups: dict):
    """Deterministic traffic generator: every request sprays ids across
    the full vocab of every coordinate (so every member owns some of
    every batch) plus optional dense feature noise."""
    import numpy as np

    rng = np.random.default_rng(spec.rng_seed)
    values = {
        id_name: list(table) for id_name, table in lookups.items()
    }

    def rows_fn():
        rows = []
        for _ in range(spec.traffic_rows):
            row: dict = {
                "features": {
                    shard: [
                        [j, float(rng.normal())] for j in range(n_cols)
                    ]
                    for shard, n_cols in spec.traffic_features
                },
                "ids": {
                    id_name: str(vals[int(rng.integers(len(vals)))])
                    for id_name, vals in values.items()
                    if vals
                },
            }
            rows.append(row)
        return rows

    return rows_fn


def run_serving_fleet(spec: ServingFleetSpec) -> dict:
    """Supervise a shard-owning serving fleet end to end: launch N
    members, route sustained traffic, survive a hard kill (heartbeat
    detection -> same-slot relaunch -> degraded window closes), execute
    live resizes through the stage/commit barrier, and drain everyone at
    the end. JSON-safe report with latency samples, shed accounting, and
    per-event timings."""
    import numpy as np  # noqa: F401 — percentile in the driver

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.parallel import multihost
    from photon_ml_tpu.serving import (
        FleetRouter,
        fleet_lookups_from_version_dir,
    )
    from photon_ml_tpu.telemetry import identity
    from photon_ml_tpu.telemetry.progress import tail_heartbeat_fields

    os.makedirs(spec.workdir, exist_ok=True)
    os.makedirs(spec.announce_dir(), exist_ok=True)
    os.makedirs(spec.fleet_dir(), exist_ok=True)
    deadline = time.monotonic() + spec.timeout_s
    report: dict = {"workdir": spec.workdir, "events": []}
    task, link, lookups = fleet_lookups_from_version_dir(spec.model_dir)
    fleet_size = spec.fleet_size
    epoch = 0
    members: dict[int, _ServingMember] = {}
    retired: list[_ServingMember] = []
    router = None
    traffic = None
    status = None
    degraded0 = telemetry.counter("serving.degraded_scores").value
    routed0 = telemetry.counter("serving.routed_rows").value
    member_failures0 = telemetry.counter("serving.member_failures").value

    def _push_status(records: dict) -> None:
        if status is None:
            return
        extras = {}
        down = router.members_status() if router is not None else {}
        for m, rec in records.items():
            d = down.get(m, {})
            entry = {
                "url": rec.get("url"),
                "model_version": rec.get("version"),
                "owned": rec.get("owned") or {},
                "degraded": bool(
                    d.get("degraded", d.get("cooling_down", False))
                ),
                "cooldown_remaining_s": d.get("cooldown_remaining_s", 0.0),
            }
            if d.get("fanout_rtt_ms"):
                entry["fanout_rtt_ms"] = d["fanout_rtt_ms"]
            tail = tail_heartbeat_fields(
                identity.member_artifact_path(spec.telemetry_base(), m),
                expect_proc=m,
            )
            if tail is not None:
                last_t, last_total = _req_cursor.get(m, (None, None))
                total = tail.get("serving_requests_total")
                now = time.monotonic()
                if (
                    total is not None
                    and last_total is not None
                    and now > last_t
                ):
                    entry["requests_per_s"] = round(
                        max(total - last_total, 0) / (now - last_t), 2
                    )
                if total is not None:
                    _req_cursor[m] = (now, total)
            extras[m] = entry
        status.update(
            num_processes=fleet_size, generation=epoch,
            member_extras=extras,
        )

    _req_cursor: dict[int, tuple] = {}
    try:
        if spec.status_file is not None or spec.status_port is not None:
            from photon_ml_tpu.parallel.fleet_status import FleetStatusWriter

            status = FleetStatusWriter(
                fleet_dir=spec.fleet_dir(),
                num_processes=fleet_size,
                heartbeat_deadline_s=spec.heartbeat_deadline_s,
                status_file=spec.status_file,
                port=spec.status_port,
                telemetry_out=spec.telemetry_base(),
                interval_s=spec.status_interval_s,
            ).start()
            report["status_port"] = status.port
            report["status_file"] = spec.status_file
        for m in range(fleet_size):
            members[m] = _launch_serving_member(spec, m, fleet_size, epoch)
        records = _wait_for_epoch(
            spec, epoch, fleet_size,
            min(deadline, time.monotonic() + spec.warm_timeout_s),
        )
        version = str(records[0]["version"])
        # router-side span stream: the supervisor process persists its
        # request:route spans next to the members' per-proc streams so
        # `cli report --fleet` can join one trace_id across the fan-out
        telemetry.configure(
            trace_out=os.path.join(
                os.path.dirname(spec.telemetry_base()), "trace.router.jsonl"
            )
        )
        router = FleetRouter(
            spec.announce_dir(),
            lookups,
            task=task,
            link=link,
            member_timeout_s=spec.member_timeout_s,
            refresh_interval_s=spec.router_refresh_s,
            retries=1,
            backoff_s=0.05,
            cooldown_s=0.4,
            sample_every=spec.trace_sample_every,
        )
        router.refresh()
        _push_status(records)
        traffic = _TrafficDriver(
            router, _traffic_rows_fn(spec, lookups), spec.traffic_hz
        ).start()
        t0 = traffic.t0

        def _rel() -> float:
            return round(time.monotonic() - t0, 4)

        # -- event schedule: kill + resizes interleave on the timeline --
        kill_at = (
            None if spec.kill_member is None
            else t0 + spec.kill_after_s
        )
        resize_plan = [
            (t0 + after_s, int(new_size)) for after_s, new_size in spec.resizes
        ]
        traffic_end = t0 + spec.traffic_seconds
        killed: Optional[dict] = None
        # a resize that slipped past traffic_end (slow warms on small
        # hosts) still completes before teardown: the headline is that
        # EVERY scheduled swap lands under live traffic, not that it
        # lands on a wall-clock mark — so traffic keeps flowing while
        # the plan has entries left
        while time.monotonic() < deadline and (
            time.monotonic() < traffic_end or resize_plan
        ):
            now = time.monotonic()
            if kill_at is not None and now >= kill_at:
                kill_at = None
                victim = members[spec.kill_member]
                t_kill = _rel()
                victim.proc.kill()
                victim.proc.wait()
                victim.rc = victim.proc.returncode
                killed = {"member": spec.kill_member, "t_kill": t_kill}
                report["events"].append({"kill": dict(killed)})
                # heartbeat-staleness detection, then same-slot relaunch
                # (same epoch: the announce refresh is an endpoint update,
                # not an ownership change — serving.resize_swap must NOT
                # fire for it)
                while time.monotonic() < deadline:
                    if spec.kill_member in multihost.dead_peers(
                        spec.fleet_dir(), fleet_size,
                        spec.heartbeat_deadline_s,
                    ):
                        break
                    time.sleep(0.05)
                killed["detect_s"] = round(_rel() - t_kill, 3)
                # flight-recorder harvest: the victim died without its
                # drain-path dump, so recover its last words from the
                # kill-safe trace stream (bounded tail read; a torn last
                # line is dropped, never adopted)
                from photon_ml_tpu.telemetry import requests as rq

                flight = rq.harvest_flight(
                    identity.member_artifact_path(
                        spec.trace_base(), spec.kill_member
                    ),
                    rq.flight_path(
                        os.path.dirname(spec.telemetry_base()),
                        spec.kill_member,
                    ),
                )
                if flight is not None:
                    killed["flight_spans"] = flight
                if spec.relaunch:
                    members[spec.kill_member] = _launch_serving_member(
                        spec, spec.kill_member, fleet_size, epoch
                    )
                    old_pid = records[spec.kill_member].get("pid")
                    while time.monotonic() < deadline:
                        recs = {
                            int(r["member"]): r
                            for r in _scan_ready(spec, epoch, fleet_size)
                        }
                        fresh = recs.get(spec.kill_member)
                        if fresh is not None and fresh.get("pid") != old_pid:
                            records = recs
                            break
                        time.sleep(0.05)
                    router.refresh()
                    killed["recovery_s"] = round(_rel() - t_kill, 3)
                continue
            if resize_plan and now >= resize_plan[0][0]:
                _t, new_size = resize_plan.pop(0)
                event = {
                    "resize": {
                        "from": fleet_size,
                        "to": new_size,
                        "t_start": _rel(),
                        "epoch": epoch + 1,
                    }
                }
                survivors = list(range(min(fleet_size, new_size)))
                # 1) growth: launch the new slots straight into epoch+1
                #    FIRST — their load+warm overlaps the survivors'
                #    staging below instead of serializing after it
                for m in range(fleet_size, new_size):
                    members[m] = _launch_serving_member(
                        spec, m, new_size, epoch + 1
                    )
                # 2) stage the new slice on every surviving member while
                #    the old one keeps serving (concurrently: staging is
                #    member-local work in N separate processes)
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                    max_workers=max(len(survivors), 1)
                ) as stage_pool:
                    stage_futs = [
                        stage_pool.submit(
                            _admin_post,
                            records[m]["url"], "stage",
                            {"fleet_size": new_size, "version": version},
                            spec.warm_timeout_s,
                        )
                        for m in survivors
                    ]
                    for fut in stage_futs:
                        fut.result()
                # 3) barrier: commit the survivors (their on_commit hook
                #    re-announces at the new size/epoch)
                for m in survivors:
                    _admin_post(
                        records[m]["url"], "commit",
                        {
                            "fleet_size": new_size,
                            "version": version,
                            "epoch": epoch + 1,
                        },
                        spec.member_timeout_s * 4,
                    )
                old_size, old_records = fleet_size, records
                epoch += 1
                records = _wait_for_epoch(
                    spec, epoch, new_size,
                    min(deadline, time.monotonic() + spec.warm_timeout_s),
                )
                fleet_size = new_size
                router.refresh()
                event["resize"]["t_swap"] = _rel()
                # 4) shrink: retire the now-unowned slots via graceful
                #    drain (SIGTERM -> 503 + Retry-After -> exit 75)
                for m in range(new_size, old_size):
                    gone = members.pop(m)
                    gone.proc.send_signal(signal.SIGTERM)
                    retired.append(gone)
                    try:
                        os.unlink(
                            os.path.join(
                                spec.announce_dir(), f"member-{m}.json"
                            )
                        )
                    except OSError:
                        pass
                report["events"].append(event)
                _push_status(records)
                continue
            _push_status(records)
            time.sleep(0.05)
        traffic.stop()
        if killed is not None:
            report["kill"] = killed
        # -- graceful teardown: every member drains and exits 75 --------
        for m in list(members.values()) + retired:
            if m.proc.poll() is None:
                m.proc.send_signal(signal.SIGTERM)
        for m in list(members.values()) + retired:
            try:
                m.rc = m.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                m.rc = m.proc.wait()
        report["rcs"] = {
            m.member: m.rc for m in list(members.values()) + retired
        }
        report["samples"] = traffic.samples
        report["failures"] = traffic.failures
        report["routed_rows"] = int(
            telemetry.counter("serving.routed_rows").value - routed0
        )
        report["degraded_scores"] = int(
            telemetry.counter("serving.degraded_scores").value - degraded0
        )
        report["member_failures"] = int(
            telemetry.counter("serving.member_failures").value
            - member_failures0
        )
        report["degraded_fraction"] = (
            report["degraded_scores"] / report["routed_rows"]
            if report["routed_rows"]
            else 0.0
        )
        report["fleet_size"] = fleet_size
        report["epoch"] = epoch
        report["telemetry_dir"] = os.path.dirname(spec.telemetry_base())
        report["ok"] = not traffic.failures
        return report
    finally:
        if traffic is not None and traffic._thread.is_alive():
            traffic.stop()
        if router is not None:
            router.close()
        if status is not None:
            status.stop()
        for m in list(members.values()) + retired:
            if m.proc.poll() is None:
                m.proc.kill()
                m.proc.wait()


def _scan_ready(
    spec: ServingFleetSpec, epoch: int, fleet_size: int
) -> list[dict]:
    from photon_ml_tpu.serving import scan_announce

    return [
        r
        for r in scan_announce(spec.announce_dir())
        if int(r.get("epoch", -1)) == epoch
        and int(r.get("fleet_size", -1)) == fleet_size
        and r.get("ready")
    ]


def verify_certified_checkpoints(
    checkpoint_dir: str, num_entities: int, dim: int
) -> list[str]:
    """Audit every CERTIFIED checkpoint under ``checkpoint_dir``: each
    ``chunk-*`` directory must carry a quorum/complete manifest whose
    shards contiguously cover [0, num_entities) with readable payloads.
    Returns a list of violation strings (empty = no partial checkpoint
    was ever certified — the distributed matrix's third assertion)."""
    from photon_ml_tpu.game.checkpoint import (
        CheckpointError,
        CheckpointSpec,
        StreamingCheckpointManager,
    )

    if not os.path.isdir(checkpoint_dir):
        return []
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=checkpoint_dir, every=1)
    )
    problems = []
    for _c, path in mgr._chunk_dirs():
        try:
            manifest = mgr._read_manifest(path)
            if int(manifest["num_entities"]) != num_entities:
                raise CheckpointError(
                    f"{path}: wrong entity count "
                    f"{manifest['num_entities']}"
                )
            if int(manifest["dim"]) != dim:
                raise CheckpointError(f"{path}: wrong dim {manifest['dim']}")
            reader = mgr._row_reader(path, manifest, "coefficients")
            reader(0, num_entities)  # every payload byte readable
        except (CheckpointError, ValueError, OSError, KeyError) as e:
            problems.append(f"{path}: certified but partial/corrupt: {e}")
    return problems


# ---------------------------------------------------------------------------
# the worker fit (one fleet member)
# ---------------------------------------------------------------------------


def _worker_main(args) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from photon_ml_tpu import faults, telemetry
    from photon_ml_tpu.parallel import multihost

    faults.warn_if_armed()
    # per-member artifact streams: PHOTON_PROC_ID is already in this
    # worker's env (set by the supervisor BEFORE jax existed), so the
    # trace/telemetry sinks open per-member suffixed files and the trace
    # header records this member's identity + epoch anchor
    telemetry.configure_from_env()
    if args.nproc > 1:
        multihost.initialize(
            multihost.DistributedConfig(
                coordinator_address=f"127.0.0.1:{args.port}",
                num_processes=args.nproc,
                process_id=args.proc,
                init_retries=2,
                init_backoff_s=0.2,
            )
        )
        assert jax.process_count() == args.nproc
    # the progress heartbeat starts only AFTER the distributed client is
    # up: a beat probes memory.hbm_stats() -> jax.devices(), and
    # initializing the backend while jax.distributed.initialize is still
    # rendezvousing would wedge the fleet on local-only devices
    progress_heartbeat = None
    telemetry_out = os.environ.get("PHOTON_TELEMETRY_OUT")
    if telemetry_out and args.progress_heartbeat_every > 0:
        progress_heartbeat = telemetry.Heartbeat(
            interval=args.progress_heartbeat_every,
            jsonl_path=telemetry.member_artifact_path(telemetry_out),
        ).start()
    heartbeat = multihost.HeartbeatWriter(
        os.path.join(args.dir, "fleet"),
        args.proc,
        interval_s=args.heartbeat_every,
    ).start()
    try:
        return _worker_fit(args, np)
    finally:
        heartbeat.stop()
        if progress_heartbeat is not None:
            progress_heartbeat.stop()


def _worker_fit(args, np) -> int:
    import jax
    import jax.numpy as jnp  # noqa: F401 — jax must be live before mesh use

    from photon_ml_tpu.game.checkpoint import (
        CheckpointSpec,
        GracefulStop,
        StreamingCheckpointManager,
        TrainingInterrupted,
    )
    from photon_ml_tpu.game.streaming import (
        LocalChunk,
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.parallel import multihost

    stop = GracefulStop().install()
    n_dev = jax.device_count()
    mesh = multihost.global_mesh({"entity": n_dev})
    # shared deterministic problem: every member generates the same data
    X, y = make_problem()
    per = N_ENTITIES // N_CHUNKS

    def local_chunk(start: int) -> LocalChunk:
        # this process's slice of the chunk's global [start, start+per)
        # rows — recomputed from the CURRENT mesh, so a survivor fleet's
        # members absorb the dead host's rows deterministically
        lo, hi = multihost.process_slice(per, mesh, "entity")
        glo, ghi = start + lo, start + hi
        return LocalChunk(
            DenseBatch(
                x=X[glo:ghi],
                labels=y[glo:ghi],
                offsets=np.zeros((ghi - glo, N_ROWS), np.float32),
                weights=np.ones((ghi - glo, N_ROWS), np.float32),
            ),
            global_size=per,
        )

    chunks = [(i * per, local_chunk(i * per)) for i in range(N_CHUNKS)]
    cfg = OptimizerConfig(
        max_iterations=60,
        tolerance=1e-9,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.3,
    )
    mgr = StreamingCheckpointManager(
        CheckpointSpec(
            directory=os.path.join(args.dir, "ckpt"),
            every=1,
            quorum_timeout_s=args.quorum_timeout,
        )
    )
    restored = mgr.restore_placed(mesh=mesh)
    if restored is not None:
        table = ShardedCoefficientTable.from_coefficients(
            restored.coefficients, mesh=mesh
        )
        start_chunk = restored.next_chunk
    else:
        table = ShardedCoefficientTable(N_ENTITIES, DIM, mesh=mesh)
        start_chunk = 0

    def should_stop() -> bool:
        if args.chunk_sleep > 0 and args.chunk_sleep_proc in (-1, args.proc):
            time.sleep(args.chunk_sleep)
        # fleet-consistent agreement: every member sees the same verdict
        # at the same boundary, so nobody sails alone into a collective
        return multihost.fleet_any(stop.requested, mesh)

    trainer = StreamingRandomEffectTrainer(
        "logistic", cfg, mesh=mesh, prefetch=False
    )
    try:
        trainer.train(
            table,
            chunks,
            checkpointer=mgr,
            start_chunk=start_chunk,
            should_stop=should_stop,
        )
        final = table.to_numpy()  # every member runs the gather collective
    except TrainingInterrupted as e:
        print(json.dumps({
            "interrupted": True,
            "at_chunk": e.step,
            "checkpoint": e.checkpoint_path,
            "start_chunk": start_chunk,
            "process_id": args.proc,
        }))
        return GRACEFUL_EXIT_CODE
    except Exception as e:  # noqa: BLE001 — any failure in a degraded fleet
        if jax.process_count() > 1:
            # a collective failed (gloo "connection closed by peer" et
            # al): the fleet is broken and this process cannot help it.
            # Exit through os._exit — normal unwinding would WEDGE in
            # jax's atexit distributed-shutdown barrier against the dead
            # peer, turning one lost host into a hung survivor.
            print(json.dumps({
                "fleet_abort": True,
                "process_id": args.proc,
                "error": f"{type(e).__name__}: {e}"[:500],
            }))
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(FLEET_ABORT_EXIT_CODE)
        raise
    if jax.process_index() == 0:
        np.save(os.path.join(args.dir, "final.npy"), final)
    print(json.dumps({
        "interrupted": False,
        "resumed": restored is not None,
        "start_chunk": start_chunk,
        "process_id": args.proc,
        "num_processes": args.nproc,
    }))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.fleet", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--worker", action="store_true",
                        help="run as ONE fleet member (internal)")
    parser.add_argument("--proc", type=int, default=0)
    parser.add_argument("--nproc", type=int, default=1)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--dir", help="fleet working directory")
    parser.add_argument("--quorum-timeout", type=float, default=4.0)
    parser.add_argument("--heartbeat-every", type=float, default=0.25)
    parser.add_argument("--progress-heartbeat-every", type=float,
                        default=1.0,
                        help="worker progress-heartbeat cadence into the "
                        "per-member telemetry JSONL (0 disables)")
    parser.add_argument("--chunk-sleep", type=float, default=0.0)
    parser.add_argument("--chunk-sleep-proc", type=int, default=-1)
    parser.add_argument("--workdir", help="supervisor working directory")
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--devices-per-process", type=int, default=2)
    parser.add_argument("--max-relaunches", type=int, default=2)
    parser.add_argument("--json", dest="json_out",
                        help="write the supervisor report to this path")
    parser.add_argument("--status-file",
                        help="write an atomic live-status JSON snapshot "
                        "here on a cadence (member liveness, last "
                        "heartbeat fields, deaths/relaunches, generation)")
    parser.add_argument("--status-port", type=int,
                        help="serve the live-status snapshot on "
                        "http://127.0.0.1:PORT/statusz (0 = ephemeral "
                        "port, reported in the supervisor JSON)")
    parser.add_argument("--status-interval", type=float, default=1.0,
                        help="seconds between status snapshots")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="disable the per-member trace/telemetry "
                        "artifact streams (on by default under "
                        "<workdir>/telemetry)")
    parser.add_argument("--serve-model-dir",
                        help="supervise a SERVING fleet of shard-owning "
                        "cli-serve members over this published model "
                        "directory instead of a training fit")
    parser.add_argument("--serve-fleet-size", type=int, default=3,
                        help="serving fleet size (entity counts must "
                        "divide by it)")
    parser.add_argument("--serve-seconds", type=float, default=6.0,
                        help="how long to drive router traffic")
    args = parser.parse_args(argv)
    if args.serve_model_dir:
        if not args.workdir:
            parser.error("--serve-model-dir requires --workdir")
        report = run_serving_fleet(ServingFleetSpec(
            workdir=args.workdir,
            model_dir=args.serve_model_dir,
            fleet_size=args.serve_fleet_size,
            traffic_seconds=args.serve_seconds,
            status_file=args.status_file,
            status_port=args.status_port,
            status_interval_s=args.status_interval,
        ))
        if args.json_out:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                json.dump(report, fh, indent=2, sort_keys=True)
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if report.get("ok") else 1
    if args.worker:
        if not args.dir:
            parser.error("--worker requires --dir")
        return _worker_main(args)
    if not args.workdir:
        parser.error("--workdir is required (or --worker --dir)")
    # the supervisor owns recovery.fleet_* — export them like bench.py
    # does (PHOTON_TELEMETRY_OUT / PHOTON_TRACE_OUT opt-in) so a real
    # fleet run's member deaths/relaunches reach the RunReport Recovery
    # section, not just this process's memory
    from photon_ml_tpu import telemetry

    telemetry.configure_from_env()
    report = run_fleet(FleetSpec(
        workdir=args.workdir,
        num_processes=args.num_processes,
        devices_per_process=args.devices_per_process,
        max_relaunches=args.max_relaunches,
        telemetry=not args.no_telemetry,
        progress_heartbeat_every_s=args.progress_heartbeat_every,
        status_file=args.status_file,
        status_port=args.status_port,
        status_interval_s=args.status_interval,
    ))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
