"""Pass 5 — L016 fault-point test coverage.

A fault-injection seam that no test arms is dead weight that LOOKS like
coverage: the crash matrix claims "every registered point is proven
recoverable", but a point added in a refactor and never wired into a
test would rot silently — the exact failure mode the injection registry
exists to prevent. This pass closes the loop statically:

- **registration sites** are found by AST: every
  ``register_point("<id>", ...)`` call with a literal first argument
  inside ``photon_ml_tpu/`` (the repo convention — module-level
  constants bound at import; a non-literal id is itself flagged, since
  neither this pass nor a reader can know what it registers);
- **coverage** means the id appears inside at least one string literal
  under ``tests/`` — an exact plan rule (``FaultRule("my.seam", ...)``),
  an env-transported JSON plan, or the crash-matrix enumeration test's
  explicit expected-points list all count. Substring matching over
  literals keeps JSON blobs covered without executing anything.
- **classification** must be statically enumerable too: the
  ``write_path=``/``distributed=`` kwargs select which matrix
  (single-process write-path vs distributed fleet rows) proves a seam
  recoverable, so a non-literal value there is flagged the same as a
  non-literal id — the distributed enumeration test
  (``faults.distributed_points()`` in tests/test_chaos.py) and this
  pass both key on it.

Scope: like the other interprocedural passes this runs over the real
tree only — reduced test trees (``require_seeds=False`` in the driver)
skip it, as does a tree that carries no tests at all.
"""

from __future__ import annotations

import ast
import os
from typing import Sequence

from tools.analysis.core import Finding, SourceFile

_TESTS_PREFIX = "tests" + os.sep
_PACKAGE_PREFIX = "photon_ml_tpu" + os.sep


def _registration_sites(
    package_files: Sequence[SourceFile],
) -> tuple[list[tuple[str, int, str]], list[Finding]]:
    """(rel, line, point_id) per literal ``register_point`` call, plus
    findings for non-literal registrations (unverifiable ids)."""
    sites: list[tuple[str, int, str]] = []
    findings: list[Finding] = []
    for sf in package_files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None
            )
            if name != "register_point" or not node.args:
                continue
            first = node.args[0]
            for kw in node.keywords:
                if kw.arg in ("write_path", "distributed") and not (
                    isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, bool)
                ):
                    findings.append(
                        Finding(
                            path=sf.rel,
                            line=node.lineno,
                            code="L016",
                            message=(
                                f"register_point() with a non-literal "
                                f"{kw.arg}= — matrix membership "
                                "(write_path_points/distributed_points) "
                                "must be statically enumerable"
                            ),
                        )
                    )
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                sites.append((sf.rel, node.lineno, first.value))
            else:
                findings.append(
                    Finding(
                        path=sf.rel,
                        line=node.lineno,
                        code="L016",
                        message=(
                            "register_point() with a non-literal id — "
                            "the fault-point registry must be statically "
                            "enumerable (tests and this pass key on the "
                            "literal id)"
                        ),
                    )
                )
    return sites, findings


def _test_string_literals(files: Sequence[SourceFile]) -> list[str]:
    out: list[str] = []
    for sf in files:
        if sf.tree is None or not sf.rel.startswith(_TESTS_PREFIX):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                out.append(node.value)
    return out


def run(files: Sequence[SourceFile]) -> list[Finding]:
    package_files = [
        sf for sf in files if sf.rel.startswith(_PACKAGE_PREFIX)
    ]
    sites, findings = _registration_sites(package_files)
    if not sites:
        return findings
    literals = _test_string_literals(files)
    if not literals:
        return findings  # no tests in this tree (reduced fixture)
    for rel, line, point in sites:
        if any(point in lit for lit in literals):
            continue
        findings.append(
            Finding(
                path=rel,
                line=line,
                code="L016",
                message=(
                    f"fault point '{point}' is not exercised by any "
                    "test — no string literal under tests/ mentions it "
                    "(arm it in a plan, or add it to the crash-matrix "
                    "expected-points list)"
                ),
            )
        )
    return findings
