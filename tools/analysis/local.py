"""Per-file AST lint: rules L001-L012 (the former ``_Lint`` monolith of
tools/check.py, now emitting structured :class:`~tools.analysis.core.Finding`
objects so suppressions/baselines/JSON work uniformly).

Rule summary (rationale lives with each check):

- L001 unused module-scope import
- L002 bare ``except:``
- L003 mutable default argument
- L004 ``== None`` / ``!= None``
- L005 f-string without placeholders
- L006 wall-clock ``time.time()`` in library code (ANY spelling: the
  module-alias table now catches ``import time as t; t.time()`` — the
  blind spot the literal matcher had)
- L007 bare ``block_until_ready()`` statement in library code
- L008 non-atomic persistence outside the blessed atomic writers
- L009 bare ``print()`` in library code (CLI modules exempt)
- L010 device->host syncs in serving hot-path modules
- L011 bare ``jax.jit`` in hot-path library modules
- L012 placement-free ``device_put`` / any ``pmap`` in sharding modules

The L010/L011/L012 path lists below are ALSO the seeds of the
interprocedural hot-path pass (:mod:`tools.analysis.hotpath`): per-file
rules catch syncs written directly in a hot module, L013 catches the same
syncs one or more calls away.
"""

from __future__ import annotations

import ast
import os

from tools.analysis.core import Finding

# Files allowed to call np.savez/json.dump directly: the atomic-write
# primitives and the persistence layers built immediately on top of them.
L008_BLESSED = {
    os.path.join("photon_ml_tpu", "utils", "atomic.py"),
    os.path.join("photon_ml_tpu", "data", "model_store.py"),
    os.path.join("photon_ml_tpu", "game", "checkpoint.py"),
}

# Serving hot-path modules: every score request flows through these, so a
# stray device->host sync (jax.device_get, float() on an array, np.asarray
# on a jax array) costs the full tunnel round trip PER REQUEST. The one
# sanctioned crossing is telemetry.sync_fetch (device.py accounts it).
L010_HOT_PATH = {
    os.path.join("photon_ml_tpu", "serving", "engine.py"),
    os.path.join("photon_ml_tpu", "serving", "batcher.py"),
    # the asyncio front end: one blocked event loop stalls EVERY
    # connection, so a stray sync here is worse than in the threading
    # server
    os.path.join("photon_ml_tpu", "serving", "aio.py"),
}

# Hot-path library modules where every jit-compiled program must go
# through telemetry.xla.instrumented_jit (L011): a bare jax.jit hides its
# compile time, cost analysis, and recompile attribution from the
# executable registry — exactly the blind spot that made BENCH_r05
# unexplainable. Cold paths (one-off summaries, diagnostics) may stay on
# bare jax.jit via the allowlist.
L011_HOT_DIRS = (
    os.path.join("photon_ml_tpu", "parallel") + os.sep,
    os.path.join("photon_ml_tpu", "game") + os.sep,
    os.path.join("photon_ml_tpu", "ops") + os.sep,
    # the sweep runner batches G solver configs into single executables;
    # a bare jax.jit there hides exactly the multi-config warmup the
    # recompile-storm gate needs multi_shape attribution for
    os.path.join("photon_ml_tpu", "sweep") + os.sep,
    # the ingest pipeline's assembler writes every chunk through donated
    # device programs, and its uploader feeds every training batch — a
    # bare jax.jit there (and any sync reachable from it, L013) would be
    # invisible on exactly the path the overlap benches gate
    os.path.join("photon_ml_tpu", "ingest") + os.sep,
    # incremental warm-start retrains: the masked-lane re-solves and the
    # vocabulary-growth row expansion run on the training hot path — a
    # bare jax.jit there would hide exactly the solve-count structure
    # bench_freshness gates the ≥10× time-to-fresh claim on
    os.path.join("photon_ml_tpu", "incremental") + os.sep,
    # the freshness conductor re-runs masked solves (and escalated full
    # fits) every cycle of a long-lived daemon: a bare jax.jit there
    # would hide recompiles that accumulate directly into the
    # event→served staleness p99 the pipeline tier gates on
    os.path.join("photon_ml_tpu", "pipeline") + os.sep,
    # the quality layer runs inside every gated publish (gate stats on
    # the candidate model) and inside every score_rows chunk (drift
    # sketches): a bare jax.jit or stray device sync there would tax
    # exactly the serving and publish paths the quality benches gate
    os.path.join("photon_ml_tpu", "quality") + os.sep,
)
L011_HOT_FILES = {
    os.path.join("photon_ml_tpu", "serving", "engine.py"),
    # the nearline updater re-solves entity rows on a live-serving
    # cadence: a bare jax.jit there would hide exactly the executables
    # whose recompiles the SLO bench gates p99 flatness over
    os.path.join("photon_ml_tpu", "serving", "nearline.py"),
    # GLMix bootstrap: B resample lanes ride the sweep solver family on
    # the publish path (and the masked incremental variant); a bare
    # jax.jit there would hide exactly the lane-composition executables
    # bench_diagnostics gates the <=2x overhead claim on
    os.path.join("photon_ml_tpu", "diagnostics", "bootstrap.py"),
    os.path.join("photon_ml_tpu", "training.py"),
    # the executable profiler wraps EVERY instrumented dispatch: a bare
    # jax.jit inside it would both escape its own accounting and put an
    # uninstrumented program on the hottest path in the process; its
    # functions are also L013 jit-walk seeds, so a device sync it
    # introduces is caught on the real dispatch path
    os.path.join("photon_ml_tpu", "telemetry", "profile.py"),
    # the request tracer runs inside every serving request (batcher
    # dispatch, router fan-out, engine folds) — pure-stdlib by contract:
    # a device touch in trace bookkeeping would wedge the event loop
    os.path.join("photon_ml_tpu", "telemetry", "requests.py"),
}
L011_COLD_ALLOWLIST = {
    # gather_to_host: a once-per-summary replicating identity, not a
    # training/serving hot path
    os.path.join("photon_ml_tpu", "parallel", "multihost.py"),
}

# Sharding-discipline modules (L012): in these hot paths every
# `jax.device_put` must name an explicit placement (a Sharding/
# NamedSharding/device second argument or device=/sharding= keyword) — a
# bare `device_put(x)` lands on the default device and is then silently
# replicated/resharded at the next jit boundary, exactly the bug class
# the GSPMD scale-out removed. Bare `pmap` is rejected outright (the
# legacy per-device API; use NamedSharding + jit, parallel/sharding.py).
L012_HOT_DIRS = (
    os.path.join("photon_ml_tpu", "parallel") + os.sep,
)
L012_HOT_FILES = {
    os.path.join("photon_ml_tpu", "game", "coordinates.py"),
    os.path.join("photon_ml_tpu", "game", "streaming.py"),
    os.path.join("photon_ml_tpu", "game", "factored.py"),
    os.path.join("photon_ml_tpu", "serving", "engine.py"),
    os.path.join("photon_ml_tpu", "serving", "registry.py"),
}


def is_l011_hot(rel: str) -> bool:
    return (
        rel in L011_HOT_FILES or rel.startswith(L011_HOT_DIRS)
    ) and rel not in L011_COLD_ALLOWLIST


def is_l012_hot(rel: str) -> bool:
    return rel in L012_HOT_FILES or rel.startswith(L012_HOT_DIRS)


class LocalLint(ast.NodeVisitor):
    """One file's L001-L012 findings (``findings`` after construction)."""

    def __init__(self, path: str, tree: ast.Module, library: bool = False):
        self.path = path
        # library code (photon_ml_tpu/) additionally gets the fake-timing
        # rules L006/L007; benches and tests may time however they like
        self.library = library
        self._l008_exempt = path in L008_BLESSED
        self._l010_hot = path in L010_HOT_PATH
        self._l011_hot = is_l011_hot(path)
        self._l012_hot = is_l012_hot(path)
        # CLI modules own stdout: bare print() is their user interface
        self._l009_exempt = path.startswith(
            os.path.join("photon_ml_tpu", "cli") + os.sep
        )
        self.findings: list[Finding] = []
        self.imported: dict[str, int] = {}  # name -> lineno (module scope)
        self.used: set[str] = set()
        # local name -> imported module (`import time as t` => t -> time):
        # the L006 blind-spot fix — wall-clock detection resolves through
        # this table instead of matching the literal `time.time()` spelling
        self._module_aliases: dict[str, str] = {}
        # names bound to the wall clock by `from time import time [as x]`
        self._time_aliases: set[str] = set()
        # names bound to the jit transform by `from jax import jit [as x]`
        self._jit_aliases: set[str] = set()
        self._collect(tree)

    def _report(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(
            Finding(path=self.path, line=node.lineno, code=code, message=msg)
        )

    def _collect(self, tree: ast.Module) -> None:
        for node in tree.body:  # module scope only: re-export surfaces stay
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    self.imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__" or any(
                    a.name == "*" for a in node.names
                ):
                    continue
                for a in node.names:
                    self.imported[a.asname or a.name] = node.lineno
        # alias tables come from EVERY import in the file (function-local
        # `import time as t` must not dodge L006), unlike the module-scope
        # unused-import bookkeeping above
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname is not None:
                        self._module_aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self._module_aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                for a in node.names:
                    if node.module == "time" and a.name == "time":
                        self._time_aliases.add(a.asname or a.name)
                    if node.module == "jax" and a.name == "jit":
                        self._jit_aliases.add(a.asname or a.name)
        self.visit(tree)

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._report(node, "L002", "bare `except:` (catch something)")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self._report(
                    d, "L003", "mutable default argument (use None sentinel)"
                )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        if self._l011_hot:
            # `@jax.jit` decorators without a call are Attribute/Name
            # nodes, invisible to visit_Call
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call) and self._is_bare_jit(dec):
                    self._report_l011(dec)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and (
                isinstance(comp, ast.Constant) and comp.value is None
            ):
                self._report(node, "L004", "use `is None` / `is not None`")
        self.generic_visit(node)

    def _is_wall_clock_call(self, node: ast.Call) -> bool:
        # `<module-bound-to-time>.time()` (import time / import time as t)
        # or a bare `time()` bound by `from time import time [as x]`
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "time"
            and isinstance(f.value, ast.Name)
            and self._module_aliases.get(f.value.id) == "time"
        ):
            return True
        return isinstance(f, ast.Name) and f.id in self._time_aliases

    def _is_non_atomic_persist_call(self, node: ast.Call) -> bool:
        # `<anything>.savez(...)` / `<anything>.savez_compressed(...)` and
        # `json.dump(...)` (json.dumps returns a string and is fine)
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in (
            "savez", "savez_compressed",
        ):
            return True
        return (
            isinstance(f, ast.Attribute)
            and f.attr == "dump"
            and isinstance(f.value, ast.Name)
            and f.value.id == "json"
        )

    def _is_bare_jit(self, node: ast.AST) -> bool:
        # `jax.jit(...)` / `@jax.jit` / from-imported `jit(...)`
        f = node.func if isinstance(node, ast.Call) else node
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "jit"
            and isinstance(f.value, ast.Name)
            and f.value.id == "jax"
        ):
            return True
        return isinstance(f, ast.Name) and f.id in self._jit_aliases

    def _report_l011(self, node: ast.AST) -> None:
        self._report(
            node,
            "L011",
            "bare jax.jit in a hot-path library module — compiles escape "
            "the executable registry (no cost analysis, no recompile "
            "attribution); use telemetry.xla.instrumented_jit(fn, "
            "name=...), or add a cold path to L011_COLD_ALLOWLIST",
        )

    def _is_serving_sync_call(self, node: ast.Call) -> bool:
        # device->host crossings in serving hot paths: `jax.device_get`
        # (any spelling), `np.asarray`/`numpy.asarray` (a jax-array arg
        # forces a fetch), and `float(x)` on anything but a literal
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "device_get":
            return True
        if isinstance(f, ast.Name) and f.id == "device_get":
            return True
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "asarray"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy")
        ):
            return True
        return (
            isinstance(f, ast.Name)
            and f.id == "float"
            and not all(isinstance(a, ast.Constant) for a in node.args)
        )

    def _check_l012(self, node: ast.Call) -> None:
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if attr == "pmap":
            self._report(
                node,
                "L012",
                "bare pmap in a sharding-discipline module — the legacy "
                "per-device API replicates state and bypasses GSPMD; use "
                "NamedSharding + jit (parallel/sharding.py)",
            )
        if attr == "device_put":
            explicit = len(node.args) >= 2 or any(
                k.arg in ("device", "sharding")
                for k in node.keywords
                if k.arg is not None
            )
            if not explicit:
                self._report(
                    node,
                    "L012",
                    "jax.device_put without an explicit Sharding — an "
                    "unsharded upload lands on the default device and "
                    "silently replicates/reshards at the next jit "
                    "boundary; pass a NamedSharding (parallel/sharding.py "
                    "placement helpers)",
                )

    def visit_Call(self, node: ast.Call) -> None:
        if self._l012_hot:
            self._check_l012(node)
        if self.library and self._is_wall_clock_call(node):
            self._report(
                node,
                "L006",
                "time.time() in library code — wall-clock steps corrupt "
                "phase durations; use time.monotonic() / utils.timing.Timer",
            )
        if (
            self.library
            and not self._l008_exempt
            and self._is_non_atomic_persist_call(node)
        ):
            self._report(
                node,
                "L008",
                "non-atomic persistence (np.savez/json.dump to a final "
                "path) in library code — a crash mid-write leaves a "
                "truncated file; route through utils.atomic / the "
                "model_store//checkpoint writers",
            )
        if self._l011_hot and self._is_bare_jit(node):
            self._report_l011(node)
        if self._l010_hot and self._is_serving_sync_call(node):
            self._report(
                node,
                "L010",
                "device->host sync in a serving hot-path module — every "
                "request pays the tunnel round trip; fetch results through "
                "telemetry.sync_fetch only",
            )
        if (
            self.library
            and not self._l009_exempt
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            self._report(
                node,
                "L009",
                "bare print() in library code — stdout belongs to CLI "
                "drivers; route output through logging or telemetry",
            )
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        # a bare `x.block_until_ready()` / `jax.block_until_ready(x)` /
        # from-imported `block_until_ready(x)` STATEMENT is a timing sync —
        # which is a no-op through the tunnel (PERF_NOTES.md); uses whose
        # result feeds real code are fine
        call = node.value
        if (
            self.library
            and isinstance(call, ast.Call)
            and (
                (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "block_until_ready"
                )
                or (
                    isinstance(call.func, ast.Name)
                    and call.func.id == "block_until_ready"
                )
            )
        ):
            self._report(
                node,
                "L007",
                "bare block_until_ready() for timing is a no-op sync on the "
                "tunnel TPU; fetch via telemetry.sync_fetch instead",
            )
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self._report(node, "L005", "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # format specs parse as nested JoinedStrs of constants (e.g. ':.3g');
        # visiting them would false-positive L005 on every formatted field
        self.visit(node.value)

    def unused_imports(self, tree: ast.Module) -> None:
        exported = set()
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                exported |= {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant)
                }
        for name, lineno in sorted(self.imported.items(), key=lambda kv: kv[1]):
            if name not in self.used and name not in exported:
                self.findings.append(
                    Finding(
                        path=self.path,
                        line=lineno,
                        code="L001",
                        message=f"unused import `{name}`",
                    )
                )


def lint_file(rel: str, tree: ast.Module, library: bool) -> list[Finding]:
    lint = LocalLint(rel, tree, library=library)
    lint.unused_imports(tree)
    return lint.findings
