"""Shared plumbing for the analysis passes: parsed sources, findings,
inline suppressions, and the baseline diff.

Parse-once is a deliberate perf fix: the old gate compiled every file in
``check_syntax`` and then re-parsed the survivors in ``check_lint`` — two
full passes over a 130-file tree. Here every file is parsed exactly once;
a failed parse becomes a ``SYNTAX`` finding and the file simply carries no
tree for the later passes.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from typing import Iterable, Optional

#: Inline suppression comment on the exact line the finding is reported
#: at; the form is `photon: noqa` followed by the bracketed code list
#: (one code, or comma-separated). Matched against real COMMENT tokens
#: only — the same text inside a string literal (test fixtures, docs)
#: neither suppresses nor counts as a stale suppression.
NOQA_RE = re.compile(r"#\s*photon:\s*noqa\[([A-Za-z0-9_,\s]+)\]")

#: Code of the unused-suppression warning (itself not suppressible:
#: a noqa that silences the warning about itself would always be "used").
UNUSED_SUPPRESSION = "W001"

#: Code of a pass-configuration error (e.g. a hot-path seed that no longer
#: resolves after a rename — the pass would silently stop guarding).
BAD_SEED = "W002"


@dataclasses.dataclass
class Finding:
    """One gate finding. ``chain`` carries the call path for the
    interprocedural passes (L013/L014/L017/L019), seed first, offending
    function last. ``alternates`` counts other call chains that reached
    the same finding — the driver dedupes to the shortest chain so the
    report stays readable as the graph grows."""

    path: str
    line: int
    code: str
    message: str
    chain: Optional[tuple[str, ...]] = None
    alternates: int = 0
    # stable identity of the offending SITE (rule-specific, e.g. the sync
    # description), independent of which chain reached it — the dedupe
    # key; None opts a finding out of chain-dedupe entirely
    site: Optional[str] = None

    def render(self) -> str:
        text = f"{self.path}:{self.line}: {self.code} {self.message}"
        if self.chain:
            text += f" [via {' -> '.join(self.chain)}]"
        if self.alternates:
            text += (
                f" (+{self.alternates} alternate call "
                f"chain{'s' if self.alternates > 1 else ''})"
            )
        return text

    def key(self) -> tuple[str, str, str]:
        # baseline identity deliberately excludes the line number — pure
        # line drift (code added above a grandfathered finding) must not
        # resurrect it. Messages themselves may embed line numbers (L014
        # cites the jit registration site, L015 lists write lines), so
        # digits are normalized out of the key for the same reason.
        return (self.path, self.code, re.sub(r"\d+", "#", self.message))

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "code": self.code,
            "message": self.message,
            "chain": list(self.chain) if self.chain else None,
            "alternates": self.alternates,
        }


@dataclasses.dataclass
class SourceFile:
    """One parsed source file: the single AST shared by every pass."""

    rel: str  # repo-relative path (the path findings report)
    abspath: str
    text: str
    lines: list[str]
    tree: Optional[ast.Module]
    error: Optional[SyntaxError]


def load_source(root_rel: str, abspath: str) -> SourceFile:
    with open(abspath, encoding="utf-8") as fh:
        text = fh.read()
    tree: Optional[ast.Module] = None
    error: Optional[SyntaxError] = None
    try:
        tree = ast.parse(text, filename=abspath)
    except SyntaxError as e:
        error = e
    return SourceFile(
        rel=root_rel,
        abspath=abspath,
        text=text,
        lines=text.splitlines(),
        tree=tree,
        error=error,
    )


def syntax_findings(files: Iterable[SourceFile]) -> list[Finding]:
    out = []
    for sf in files:
        if sf.error is not None:
            out.append(
                Finding(
                    path=sf.rel,
                    line=sf.error.lineno or 0,
                    code="SYNTAX",
                    message=sf.error.msg or "invalid syntax",
                )
            )
    return out


def dedupe_chain_findings(findings: list[Finding]) -> list[Finding]:
    """Collapse identical findings reached through multiple call chains.

    Interprocedural passes can reach one offending site from several
    seeds/roots; reporting each chain separately buries the signal as the
    graph grows. Findings sharing ``(path, line, code, site)`` collapse
    to ONE report carrying the SHORTEST chain (ties: first wins), with
    the others counted in ``alternates``. Findings without a ``site`` or
    ``chain`` pass through untouched.
    """
    by_key: dict[tuple, Finding] = {}
    out: list[Finding] = []
    for f in findings:
        if f.chain is None or f.site is None:
            out.append(f)
            continue
        key = (f.path, f.line, f.code, f.site)
        cur = by_key.get(key)
        if cur is None:
            by_key[key] = f
            out.append(f)
        else:
            if len(f.chain) < len(cur.chain):
                cur.message, cur.chain = f.message, f.chain
            cur.alternates += 1
    return out


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def collect_suppressions(sf: SourceFile) -> dict[int, set[str]]:
    """1-based line -> set of codes suppressed on that line.

    Tokenizes the file so only REAL comments count: a noqa-shaped string
    inside a docstring or a test fixture literal is inert."""
    out: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(sf.text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = NOQA_RE.search(tok.string)
            if m:
                codes = {
                    c.strip() for c in m.group(1).split(",") if c.strip()
                }
                if codes:
                    out.setdefault(tok.start[0], set()).update(codes)
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass  # unparseable files are already SYNTAX findings
    return out


def apply_suppressions(
    findings: list[Finding],
    suppressions: dict[str, dict[int, set[str]]],
) -> tuple[list[Finding], list[Finding]]:
    """-> (kept findings, unused-suppression warnings).

    A finding is suppressed when its exact reported line carries a
    ``# photon: noqa[<its code>]`` comment. Every suppression entry that
    silenced nothing becomes a W001 warning, so stale noqa comments are
    flushed out instead of rotting into false confidence.
    """
    used: set[tuple[str, int, str]] = set()
    kept: list[Finding] = []
    for f in findings:
        codes = suppressions.get(f.path, {}).get(f.line, set())
        if f.code in codes:
            used.add((f.path, f.line, f.code))
        else:
            kept.append(f)
    warnings = []
    for path, per_line in sorted(suppressions.items()):
        for line, codes in sorted(per_line.items()):
            for code in sorted(codes):
                if (path, line, code) not in used:
                    warnings.append(
                        Finding(
                            path=path,
                            line=line,
                            code=UNUSED_SUPPRESSION,
                            message=(
                                f"unused suppression `# photon: "
                                f"noqa[{code}]` — nothing on this line "
                                f"triggers {code}; delete the comment"
                            ),
                        )
                    )
    return kept, warnings


# ---------------------------------------------------------------------------
# Baseline diff
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> dict[tuple[str, str, str], int]:
    """Grandfathered finding keys -> accepted OCCURRENCE COUNT, from a
    ``--baseline`` JSON file (the ``--write-baseline`` / ``--json``
    schema: ``{"findings": [...]}`` with ``path``/``code``/``message``
    per entry; duplicate keys accumulate)."""
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data["findings"] if isinstance(data, dict) else data
    out: dict[tuple[str, str, str], int] = {}
    for e in entries:
        # normalize exactly like Finding.key(): stored messages carry the
        # line numbers of their era, keys must not
        key = (e["path"], e["code"], re.sub(r"\d+", "#", e["message"]))
        out[key] = out.get(key, 0) + 1
    return out


def split_baseline(
    findings: list[Finding], baseline
) -> tuple[list[Finding], list[Finding], list[tuple[str, str, str]]]:
    """-> (new findings that fail CI, grandfathered findings, stale
    baseline keys no current finding consumed — fixed, delete them).

    MULTISET semantics: each baseline entry absorbs exactly ONE matching
    occurrence. Per-file rules have constant messages, so set semantics
    would let one grandfathered ``print()`` green-light every future
    ``print()`` in the same file — the exact "only NEW findings fail"
    contract the baseline exists to keep."""
    if not isinstance(baseline, dict):
        baseline = {k: 1 for k in baseline}
    remaining = dict(baseline)
    new: list[Finding] = []
    grandfathered: list[Finding] = []
    for f in findings:
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
            grandfathered.append(f)
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0)
    return new, grandfathered, stale
