"""Pass 3 — L014 jit-purity.

A function handed to ``instrumented_jit`` / ``jax.jit`` (or used as a
``lax.while_loop`` / ``lax.scan`` / ``lax.fori_loop`` body) executes its
Python exactly ONCE, at trace time. Host side effects inside it —
telemetry counters, log lines, wall-clock reads, file I/O, module-global
mutation — appear to work on the first call and then silently never run
again; the two newest bug classes in the tree both started this way.

This pass resolves every jit registration site through the call graph
(including the repo's dominant idiom: a closure factory returning
``instrumented_jit(run)`` where ``run`` calls shared solver machinery),
walks the transitive callee closure of each traced function, and flags
impure operations with the chain from the traced root.

The detectors are deliberately NARROW (exact resolved names, module-level
``logger`` convention, ``print``/``open``/``global``): a purity pass that
cries wolf gets allowlisted into uselessness. Verifiably pure host-side
helpers that only *construct* traced computations are fine — tracing
double-executes nothing for them; the danger is effects the author
expected to repeat per call.
"""

from __future__ import annotations

import ast

from tools.analysis.callgraph import FunctionInfo, PackageGraph
from tools.analysis.core import Finding
from tools.analysis.hotpath import short_chain

#: Wrappers that register a traced function: positional arg 0 is traced.
JIT_WRAPPERS = {
    "jax.jit",
    "photon_ml_tpu.telemetry.xla.instrumented_jit",
}

#: Control-flow primitives whose function-valued args are traced bodies.
LOOP_WRAPPERS = {
    "jax.lax.while_loop": (0, 1),
    "jax.lax.scan": (0,),
    "jax.lax.fori_loop": (2,),
}

#: Transform wrappers to look through when resolving the traced function:
#: ``instrumented_jit(jax.vmap(solve_one, ...))`` traces ``solve_one``.
TRANSPARENT_WRAPPERS = {"jax.vmap", "jax.pmap", "functools.partial"}

#: Exact resolved call names that are impure inside a trace.
WALL_CLOCK = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.process_time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
TELEMETRY_SINKS = {
    "photon_ml_tpu.telemetry.metrics.counter",
    "photon_ml_tpu.telemetry.metrics.gauge",
    "photon_ml_tpu.telemetry.metrics.histogram",
    "photon_ml_tpu.telemetry.trace.add_event",
    "photon_ml_tpu.telemetry.trace.span",
    "photon_ml_tpu.telemetry.device.sync_fetch",
}
FILE_OPS = {
    "os.remove",
    "os.rename",
    "os.replace",
    "os.makedirs",
    "os.unlink",
    "os.rmdir",
    "shutil.rmtree",
    "shutil.copyfile",
    "shutil.copytree",
}
_LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}


def impure_sites(fn: FunctionInfo) -> list[tuple[int, str]]:
    """(lineno, description) for every impure operation in the body."""
    out: list[tuple[int, str]] = []
    for node in _own_nodes(fn.node):
        if isinstance(node, ast.Global):
            out.append(
                (node.lineno, "mutates module global(s) "
                              f"{', '.join(node.names)}")
            )
    for resolved, call in fn.calls:
        f = call.func
        if resolved in WALL_CLOCK:
            out.append((call.lineno, f"reads the wall clock ({resolved})"))
        elif resolved in TELEMETRY_SINKS:
            out.append(
                (call.lineno,
                 f"records telemetry ({resolved.rsplit('.', 1)[-1]})")
            )
        elif resolved in FILE_OPS:
            out.append((call.lineno, f"filesystem side effect ({resolved})"))
        elif isinstance(f, ast.Name) and f.id == "open":
            out.append((call.lineno, "opens a file"))
        elif isinstance(f, ast.Name) and f.id == "print":
            out.append((call.lineno, "prints to stdout"))
        elif (
            isinstance(f, ast.Attribute)
            and f.attr in _LOG_METHODS
            and isinstance(f.value, ast.Name)
            and (f.value.id in ("logging",) or "log" in f.value.id.lower())
        ):
            out.append((call.lineno, f"logs via {f.value.id}.{f.attr}()"))
    return out


def _own_nodes(fn_node: ast.AST):
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _unwrap(graph: PackageGraph, fn: FunctionInfo, expr: ast.AST) -> ast.AST:
    """Look through vmap/partial wrappers to the traced function expr."""
    while isinstance(expr, ast.Call):
        resolved = graph._resolve_func_expr(fn, expr.func)
        name = None
        if isinstance(expr.func, ast.Name):
            name = expr.func.id
        elif isinstance(expr.func, ast.Attribute):
            name = expr.func.attr
        if resolved in TRANSPARENT_WRAPPERS or name in ("vmap", "partial"):
            if not expr.args:
                break
            expr = expr.args[0]
            continue
        break
    return expr


def trace_roots(graph: PackageGraph) -> list[tuple[str, str, int, str]]:
    """(traced function qname, registration file, line, wrapper name) for
    every jit/loop registration site resolvable through the graph."""
    roots: list[tuple[str, str, int, str]] = []
    for fn in graph.functions.values():
        for resolved, call in fn.calls:
            if resolved in JIT_WRAPPERS and call.args:
                arg_specs = [(0, resolved.rsplit(".", 1)[-1])]
            elif resolved in LOOP_WRAPPERS:
                short = resolved.rsplit(".", 1)[-1]
                arg_specs = [
                    (i, f"lax.{short}") for i in LOOP_WRAPPERS[resolved]
                ]
            else:
                continue
            for idx, wrapper in arg_specs:
                if idx >= len(call.args):
                    continue
                expr = _unwrap(graph, fn, call.args[idx])
                target = graph.resolve_call_target(
                    graph._resolve_func_expr(fn, expr)
                )
                if target is not None:
                    roots.append((target, fn.rel, call.lineno, wrapper))
        # decorator forms: @jax.jit / @instrumented_jit(name=...) /
        # @functools.partial(jax.jit, ...)
        for dec in getattr(fn.node, "decorator_list", []):
            expr = dec.func if isinstance(dec, ast.Call) else dec
            resolved = graph._resolve_func_expr(fn, expr)
            if resolved in JIT_WRAPPERS:
                roots.append(
                    (fn.qname, fn.rel, dec.lineno,
                     resolved.rsplit(".", 1)[-1])
                )
            elif (
                isinstance(dec, ast.Call)
                and resolved == "functools.partial"
                and dec.args
                and graph._resolve_func_expr(fn, dec.args[0]) in JIT_WRAPPERS
            ):
                roots.append((fn.qname, fn.rel, dec.lineno, "partial(jit)"))
    return roots


def run(graph: PackageGraph) -> list[Finding]:
    # one finding may be reachable from SEVERAL traced roots: every
    # occurrence is emitted with its ``site`` set and the driver's
    # chain-dedupe keeps the shortest chain, counting the alternates
    findings: list[Finding] = []
    for root, reg_rel, reg_line, wrapper in trace_roots(graph):
        reach = graph.reachable([root])
        for qname in sorted(reach):
            fn = graph.functions[qname]
            for lineno, desc in impure_sites(fn):
                chain = short_chain(graph.chain_to(reach, qname))
                findings.append(
                    Finding(
                        path=fn.rel,
                        line=lineno,
                        code="L014",
                        message=(
                            f"{desc} inside jit-traced code — this runs "
                            f"ONCE at trace time and silently never "
                            f"again (traced via {wrapper} at "
                            f"{reg_rel}:{reg_line}); hoist the effect to "
                            f"the host side of the jit boundary"
                        ),
                        chain=chain,
                        site=desc,
                    )
                )
    return findings
