"""Pass 4 — L015 lock discipline, and L018 lock-order deadlock cycles.

``serving/`` and ``telemetry/progress.py`` run real daemon threads now.
For every class that spawns one (``threading.Thread(target=self._x)``),
this pass finds instance attributes written BOTH from the thread target's
call closure and from the public API, and requires every such write to
sit under a ``with self._lock:`` / ``with self._cv:`` block. An attribute
written from two threads without a lock is exactly the shared-state race
the GIL papers over until it doesn't (read-modify-write interleavings,
torn multi-field invariants).

Scope decisions, deliberately:

- ``__init__`` writes are exempt — construction happens-before the thread
  exists.
- Attributes written only from public methods (e.g. ``self._thread`` in
  ``start``/``stop``) or only from the thread side are not flagged; the
  pass targets the cross-thread pairs.
- A "lock" context manager is any ``with self.<attr>:`` whose attribute
  name contains lock/cv/cond/mutex — the repo convention (``_lock``,
  ``_cv``). Methods called WHILE holding a lock are not modeled (no
  interprocedural lock state): a write must be lexically inside the
  ``with`` block. That is the repo's existing style and keeps the pass
  exact; a justified exception takes a ``# photon: noqa[L015]``.

**L018 — lock-order cycles** (:func:`run_lock_order`). The threaded
classes now hold locks WHILE calling into each other (engine version
lock, registry lock, nearline buffer condition, fleet status lock), and
two threads acquiring two locks in opposite orders is the classic
deadlock no per-class pass can see. This pass extracts every lock
ACQUISITION ORDER: a ``with self._lock:`` block that (lexically) nests
another lock ``with``, or that calls — through the call graph, plus
instance-type resolution the plain graph lacks (``v = ClassName(...)``
locals, ``self._attr = ClassName(...)`` attributes, annotated returns
like ``_engine_of(...) -> ScoringEngine``) — into a method that
acquires another lock, yields a directed edge ``A -> B`` in the
cross-class lock-order graph. A cycle in that graph (including the
self-edge: re-acquiring a non-reentrant ``threading.Lock`` through a
helper call) is a deadlock waiting for the right interleaving; the
finding names every edge with its acquisition site and call chain.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from tools.analysis.callgraph import ClassInfo, PackageGraph
from tools.analysis.core import Finding
from tools.analysis.hotpath import _short

_LOCKISH = ("lock", "cv", "cond", "mutex")

#: Dunder methods that are public API surface (context-manager protocol).
_PUBLIC_DUNDERS = {"__enter__", "__exit__", "__call__", "__iter__",
                   "__next__"}


@dataclasses.dataclass
class _Write:
    attr: str
    lineno: int
    locked: bool
    method: str  # method qname the write lives in


def _is_lock_cm(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and any(k in expr.attr.lower() for k in _LOCKISH)
    )


def _flatten_targets(target: ast.AST):
    """Unpack tuple/list/starred assignment targets:
    ``self._a, self._b = ...`` writes BOTH attributes."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _flatten_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from _flatten_targets(target.value)
    else:
        yield target


def _self_attr_of_target(target: ast.AST) -> Optional[str]:
    """`self._x = ...` / `self._x[k] = ...` / `self._x += ...` -> `_x`."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return target.attr
    return None


def attr_writes(fn_node: ast.AST, method_qname: str) -> list[_Write]:
    """Every ``self.<attr>`` write in the method body with its lock
    context (lexically enclosing ``with self._lock/_cv:`` blocks)."""
    out: list[_Write] = []

    def rec(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(
                _is_lock_cm(item.context_expr) for item in node.items
            )
            for child in node.body:
                rec(child, inner)
            return
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # nested defs are their own graph nodes
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for leaf in _flatten_targets(t):
                    attr = _self_attr_of_target(leaf)
                    if attr is not None:
                        out.append(
                            _Write(attr, leaf.lineno, locked, method_qname)
                        )
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            attr = _self_attr_of_target(node.target)
            if attr is not None:
                out.append(
                    _Write(attr, node.target.lineno, locked, method_qname)
                )
        for child in ast.iter_child_nodes(node):
            rec(child, locked)

    for stmt in fn_node.body:
        rec(stmt, False)
    return out


def thread_targets(graph: PackageGraph, cls: ClassInfo) -> list[str]:
    """Method qnames this class hands to ``threading.Thread(target=...)``."""
    out = []
    for mname, mq in cls.methods.items():
        fn = graph.functions[mq]
        for resolved, call in fn.calls:
            is_thread = resolved == "threading.Thread" or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "Thread"
            ) or (
                isinstance(call.func, ast.Name)
                and call.func.id == "Thread"
            )
            if not is_thread:
                continue
            for kw in call.keywords:
                if kw.arg != "target":
                    continue
                v = kw.value
                if (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                    and v.attr in cls.methods
                ):
                    out.append(cls.methods[v.attr])
    return out


def _class_closure(
    graph: PackageGraph, cls: ClassInfo, entries: list[str]
) -> set[str]:
    """Methods (and their nested defs) reachable from ``entries`` through
    self-calls, restricted to this class's own functions."""
    own = set()
    for mq in cls.methods.values():
        own.add(mq)
        stack = [mq]
        while stack:
            q = stack.pop()
            for child in graph.functions[q].nested:
                if child not in own:
                    own.add(child)
                    stack.append(child)
    reach = graph.reachable(entries)
    return {q for q in reach if q in own}


# ---------------------------------------------------------------------------
# L018 — lock-order cycles
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Acq:
    """One lock acquisition (`with self.<attr>:`) in a function body."""

    attr: str
    lineno: int


@dataclasses.dataclass
class _HeldCall:
    """A call made while holding one or more locks."""

    held: tuple  # lock attrs held (innermost last)
    call: ast.Call
    lineno: int


def lock_sites(fn_node: ast.AST):
    """-> (acquisitions, lexical nesting edges, calls-under-lock) for one
    function body. Nested defs are separate graph nodes and excluded."""
    acqs: list[_Acq] = []
    lex_edges: list[tuple[str, str, int]] = []  # (held, acquired, line)
    held_calls: list[_HeldCall] = []

    def rec(node: ast.AST, held: tuple) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                if _is_lock_cm(item.context_expr):
                    attr = item.context_expr.attr
                    acqs.append(_Acq(attr, item.context_expr.lineno))
                    for h in inner:
                        lex_edges.append(
                            (h, attr, item.context_expr.lineno)
                        )
                    inner = inner + (attr,)
                else:
                    # a non-lock context expression (`with self._lock,
                    # other.use():`) EXECUTES while the earlier items'
                    # locks are held — its calls are held-calls too
                    rec(item.context_expr, inner)
            for child in node.body:
                rec(child, inner)
            return
        if isinstance(node, ast.Call) and held:
            held_calls.append(_HeldCall(held, node, node.lineno))
        for child in ast.iter_child_nodes(node):
            rec(child, held)

    for stmt in fn_node.body:
        rec(stmt, ())
    return acqs, lex_edges, held_calls


class _TypeResolver:
    """Instance-type inference the plain call graph lacks: maps
    ``obj.method()`` calls to class methods via (a) locals assigned from
    a class constructor, (b) ``self._attr`` fields assigned a
    constructor anywhere in the class, (c) locals assigned from a call
    whose return annotation names a package class, (d) annotated
    parameters. Conservative: a miss resolves to nothing."""

    def __init__(self, graph: PackageGraph):
        self.graph = graph
        self._local_cache: dict[str, dict[str, str]] = {}
        # class qname -> {attr -> class qname}
        self.attr_types: dict[str, dict[str, str]] = {}
        for cls in graph.classes.values():
            table: dict[str, str] = {}
            for mq in cls.methods.values():
                fn = graph.functions[mq]
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    target_cls = self._call_class(fn, node.value)
                    if target_cls is None:
                        continue
                    for t in node.targets:
                        attr = _self_attr_of_target(t)
                        if attr is not None:
                            table.setdefault(attr, target_cls)
            if table:
                self.attr_types[cls.qname] = table

    def _resolve_class(self, module: str, dotted: str) -> Optional[str]:
        mod = self.graph.modules.get(module)
        if mod is None:
            return None
        head, _, _tail = dotted.partition(".")
        base = mod.bindings.get(head)
        cand = (
            self.graph.resolve_export(
                base + dotted[len(head):] if base else dotted
            )
            if base
            else mod.name + "." + dotted
        )
        if cand in self.graph.classes:
            return cand
        # module-local class referenced bare
        cand = mod.name + "." + dotted
        return cand if cand in self.graph.classes else None

    def _annotation_class(
        self, module: str, ann: Optional[ast.AST]
    ) -> Optional[str]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return self._resolve_class(module, ann.value.strip("'\""))
        if isinstance(ann, ast.Name):
            return self._resolve_class(module, ann.id)
        if isinstance(ann, ast.Attribute):
            parts = []
            node: ast.AST = ann
            while isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            if isinstance(node, ast.Name):
                parts.append(node.id)
                return self._resolve_class(module, ".".join(reversed(parts)))
            return None
        if isinstance(ann, ast.Subscript):  # Optional[X] / "Optional[X]"
            return self._annotation_class(module, ann.slice)
        return None

    def _call_class(self, fn, expr: ast.AST) -> Optional[str]:
        """Class qname an assignment RHS constructs or returns."""
        if not isinstance(expr, ast.Call):
            return None
        resolved = self.graph._resolve_func_expr(fn, expr.func)
        if resolved in self.graph.classes:
            return resolved
        target = self.graph.resolve_call_target(resolved)
        if target is not None:
            callee = self.graph.functions[target]
            ret = self._annotation_class(
                callee.module, getattr(callee.node, "returns", None)
            )
            if ret is not None:
                return ret
        return None

    def local_types(self, fn) -> dict[str, str]:
        """var name -> class qname for one function body (cached)."""
        cached = self._local_cache.get(fn.qname)
        if cached is not None:
            return cached
        out: dict[str, str] = {}
        self._local_cache[fn.qname] = out
        args = fn.node.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            cls = self._annotation_class(fn.module, a.annotation)
            if cls is not None:
                out[a.arg] = cls
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                cls = self._call_class(fn, node.value)
                if cls is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, cls)
        return out

    def resolve_call(self, fn, call: ast.Call) -> Optional[str]:
        """Graph resolution first; typed-instance resolution second."""
        resolved = self.graph._resolve_func_expr(fn, call.func)
        target = self.graph.resolve_call_target(resolved)
        if target is not None:
            return target
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        owner: Optional[str] = None
        base = f.value
        if isinstance(base, ast.Name):
            owner = self.local_types(fn).get(base.id)
        elif (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
        ):
            cls_q = _owner_class(self.graph, fn)
            if cls_q is not None:
                owner = self.attr_types.get(cls_q, {}).get(base.attr)
        if owner is None:
            return None
        mq = self.graph.classes[owner].methods.get(f.attr)
        return mq

    def callees(self, fn) -> list[tuple[str, int]]:
        """Graph callees + typed-instance edges + containment edges."""
        out = list(self.graph.callees(fn.qname))
        have = {t for t, _l in out}
        from tools.analysis.callgraph import own_body_nodes

        for node in own_body_nodes(fn.node):
            if isinstance(node, ast.Call):
                t = self.resolve_call(fn, node)
                if t is not None and t not in have:
                    have.add(t)
                    out.append((t, node.lineno))
        return out


def _owner_class(graph: PackageGraph, fn) -> Optional[str]:
    """The class a function's ``self`` refers to: its own class, or the
    enclosing method's class for defs nested inside methods."""
    cur = fn
    while cur is not None:
        if cur.class_qname is not None:
            return cur.class_qname
        cur = graph.functions.get(cur.parent) if cur.parent else None
    return None


def _short_cls(qname: str) -> str:
    return qname.rsplit(".", 1)[-1]


def lock_order_graph(graph: PackageGraph, resolver=None):
    """-> (nodes, edges): the cross-class lock-order graph. Nodes are
    ``(class qname, lock attr)``; ``edges[(A, B)]`` carries the first
    (and shortest-chained) evidence ``(rel, lineno, chain)`` that B was
    acquired while A was held."""
    if resolver is None:
        resolver = _TypeResolver(graph)
    nodes: set = set()
    edges: dict = {}
    site_cache: dict[str, tuple] = {}

    def sites(qname: str):
        got = site_cache.get(qname)
        if got is None:
            got = lock_sites(graph.functions[qname].node)
            site_cache[qname] = got
        return got

    def add_edge(a, b, rel, lineno, chain):
        cur = edges.get((a, b))
        if cur is None or len(chain) < len(cur[2]):
            edges[(a, b)] = (rel, lineno, chain)

    for qname, fn in sorted(graph.functions.items()):
        cls_q = _owner_class(graph, fn)
        if cls_q is None:
            continue
        acqs, lex_edges, held_calls = sites(qname)
        for a in acqs:
            nodes.add((cls_q, a.attr))
        for held_attr, acq_attr, lineno in lex_edges:
            add_edge(
                (cls_q, held_attr), (cls_q, acq_attr), fn.rel, lineno,
                (qname,),
            )
        for hc in held_calls:
            target = resolver.resolve_call(fn, hc.call)
            if target is None:
                continue
            # BFS over the callee closure, collecting acquisitions with
            # the chain from the lock-holding method
            pred: dict[str, Optional[str]] = {target: None}
            frontier = [target]
            while frontier:
                nxt = []
                for q in frontier:
                    g = graph.functions[q]
                    g_cls = _owner_class(graph, g)
                    if g_cls is not None:
                        g_acqs, _lex, _calls = sites(q)
                        for a in g_acqs:
                            nodes.add((g_cls, a.attr))
                            chain = [q]
                            cur = q
                            while pred[cur] is not None:
                                cur = pred[cur]
                                chain.append(cur)
                            chain.append(qname)
                            for held_attr in hc.held:
                                add_edge(
                                    (cls_q, held_attr),
                                    (g_cls, a.attr),
                                    fn.rel,
                                    hc.lineno,
                                    tuple(reversed(chain)),
                                )
                    for callee, _l in resolver.callees(g):
                        if callee not in pred:
                            pred[callee] = q
                            nxt.append(callee)
                frontier = nxt
    return nodes, edges


def _find_cycles(nodes, edges) -> list[list]:
    """Minimal cycle per strongly-connected component (plus self-edges),
    deduped by node set — one finding per distinct deadlock shape."""
    adj: dict = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    cycles: list[list] = []
    seen_sets: set = set()
    for (a, b) in sorted(edges):
        if a == b:
            key = frozenset((a,))
            if key not in seen_sets:
                seen_sets.add(key)
                cycles.append([a, a])
            continue
        # shortest path b -> a (BFS) closes the cycle a -> b -> ... -> a
        pred = {b: None}
        frontier = [b]
        found = False
        while frontier and not found:
            nxt = []
            for n in frontier:
                for m in adj.get(n, ()):
                    if m == a:
                        path = [a, b]
                        cur = n
                        back = []
                        while cur is not None:
                            back.append(cur)
                            cur = pred[cur]
                        path.extend(reversed(back[:-1]))
                        path.append(a)
                        key = frozenset(path)
                        if key not in seen_sets:
                            seen_sets.add(key)
                            cycles.append(path)
                        found = True
                        break
                    if m not in pred:
                        pred[m] = n
                        nxt.append(m)
                if found:
                    break
            frontier = nxt
    return cycles


def run_lock_order(
    graph: PackageGraph, stats: Optional[dict] = None
) -> list[Finding]:
    """L018: flag every distinct cycle in the lock-order graph."""
    resolver = _TypeResolver(graph)
    nodes, edges = lock_order_graph(graph, resolver)
    if stats is not None:
        stats["nodes"] = len(nodes)
        stats["edges"] = len(edges)
    findings: list[Finding] = []
    for cycle in _find_cycles(nodes, edges):
        names = [f"{_short_cls(c)}.{attr}" for c, attr in cycle]
        legs = []
        first_rel, first_line = None, 0
        for a, b in zip(cycle, cycle[1:]):
            rel, lineno, chain = edges[(a, b)]
            if first_rel is None:
                first_rel, first_line = rel, lineno
            via = " -> ".join(_short(q) for q in chain)
            legs.append(
                f"{_short_cls(a[0])}.{a[1]} held while acquiring "
                f"{_short_cls(b[0])}.{b[1]} at {rel}:{lineno} (via {via})"
            )
        if len(cycle) == 2 and cycle[0] == cycle[1]:
            what = (
                f"non-reentrant lock re-acquired while held: "
                f"{names[0]} — threading.Lock/Condition self-deadlocks"
            )
        else:
            what = (
                f"lock-order cycle {' -> '.join(names)} — two threads "
                f"taking these locks in opposite orders deadlock"
            )
        findings.append(
            Finding(
                path=first_rel or "",
                line=first_line,
                code="L018",
                message=f"{what}; acquisition order: " + "; ".join(legs),
            )
        )
    return findings


def run(graph: PackageGraph) -> list[Finding]:
    findings: list[Finding] = []
    for cls in graph.classes.values():
        entries = thread_targets(graph, cls)
        if not entries:
            continue
        init_q = cls.methods.get("__init__")
        thread_side = _class_closure(graph, cls, entries)
        public_entries = [
            mq
            for mname, mq in cls.methods.items()
            if (not mname.startswith("_") or mname in _PUBLIC_DUNDERS)
        ]
        public_side = _class_closure(graph, cls, public_entries)

        writes: dict[str, list[_Write]] = {}
        for mq in sorted(thread_side | public_side):
            if mq == init_q:
                continue  # construction happens-before the thread
            fn = graph.functions[mq]
            for w in attr_writes(fn.node, mq):
                writes.setdefault(w.attr, []).append(w)

        for attr in sorted(writes):
            sites = writes[attr]
            t_sites = [w for w in sites if w.method in thread_side]
            p_sites = [w for w in sites if w.method in public_side]
            if not t_sites or not p_sites:
                continue  # single-sided: not a cross-thread attribute
            unlocked = [w for w in sites if not w.locked]
            if not unlocked:
                continue
            first = min(unlocked, key=lambda w: w.lineno)
            lines = ", ".join(
                str(w.lineno) for w in sorted(unlocked, key=lambda w: w.lineno)
            )
            t_m = graph.functions[t_sites[0].method].name
            p_m = graph.functions[p_sites[0].method].name
            findings.append(
                Finding(
                    path=cls.rel,
                    line=first.lineno,
                    code="L015",
                    message=(
                        f"attribute `self.{attr}` of {cls.name} is "
                        f"written from the thread target path "
                        f"(`{t_m}`) and the public API (`{p_m}`) with "
                        f"unlocked write(s) at line(s) {lines} — guard "
                        f"every shared write with `with self._lock:` / "
                        f"`with self._cv:`"
                    ),
                )
            )
    return findings
