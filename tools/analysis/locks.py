"""Pass 4 — L015 lock discipline.

``serving/`` and ``telemetry/progress.py`` run real daemon threads now.
For every class that spawns one (``threading.Thread(target=self._x)``),
this pass finds instance attributes written BOTH from the thread target's
call closure and from the public API, and requires every such write to
sit under a ``with self._lock:`` / ``with self._cv:`` block. An attribute
written from two threads without a lock is exactly the shared-state race
the GIL papers over until it doesn't (read-modify-write interleavings,
torn multi-field invariants).

Scope decisions, deliberately:

- ``__init__`` writes are exempt — construction happens-before the thread
  exists.
- Attributes written only from public methods (e.g. ``self._thread`` in
  ``start``/``stop``) or only from the thread side are not flagged; the
  pass targets the cross-thread pairs.
- A "lock" context manager is any ``with self.<attr>:`` whose attribute
  name contains lock/cv/cond/mutex — the repo convention (``_lock``,
  ``_cv``). Methods called WHILE holding a lock are not modeled (no
  interprocedural lock state): a write must be lexically inside the
  ``with`` block. That is the repo's existing style and keeps the pass
  exact; a justified exception takes a ``# photon: noqa[L015]``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from tools.analysis.callgraph import ClassInfo, PackageGraph
from tools.analysis.core import Finding

_LOCKISH = ("lock", "cv", "cond", "mutex")

#: Dunder methods that are public API surface (context-manager protocol).
_PUBLIC_DUNDERS = {"__enter__", "__exit__", "__call__", "__iter__",
                   "__next__"}


@dataclasses.dataclass
class _Write:
    attr: str
    lineno: int
    locked: bool
    method: str  # method qname the write lives in


def _is_lock_cm(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and any(k in expr.attr.lower() for k in _LOCKISH)
    )


def _flatten_targets(target: ast.AST):
    """Unpack tuple/list/starred assignment targets:
    ``self._a, self._b = ...`` writes BOTH attributes."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _flatten_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from _flatten_targets(target.value)
    else:
        yield target


def _self_attr_of_target(target: ast.AST) -> Optional[str]:
    """`self._x = ...` / `self._x[k] = ...` / `self._x += ...` -> `_x`."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return target.attr
    return None


def attr_writes(fn_node: ast.AST, method_qname: str) -> list[_Write]:
    """Every ``self.<attr>`` write in the method body with its lock
    context (lexically enclosing ``with self._lock/_cv:`` blocks)."""
    out: list[_Write] = []

    def rec(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(
                _is_lock_cm(item.context_expr) for item in node.items
            )
            for child in node.body:
                rec(child, inner)
            return
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # nested defs are their own graph nodes
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for leaf in _flatten_targets(t):
                    attr = _self_attr_of_target(leaf)
                    if attr is not None:
                        out.append(
                            _Write(attr, leaf.lineno, locked, method_qname)
                        )
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            attr = _self_attr_of_target(node.target)
            if attr is not None:
                out.append(
                    _Write(attr, node.target.lineno, locked, method_qname)
                )
        for child in ast.iter_child_nodes(node):
            rec(child, locked)

    for stmt in fn_node.body:
        rec(stmt, False)
    return out


def thread_targets(graph: PackageGraph, cls: ClassInfo) -> list[str]:
    """Method qnames this class hands to ``threading.Thread(target=...)``."""
    out = []
    for mname, mq in cls.methods.items():
        fn = graph.functions[mq]
        for resolved, call in fn.calls:
            is_thread = resolved == "threading.Thread" or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "Thread"
            ) or (
                isinstance(call.func, ast.Name)
                and call.func.id == "Thread"
            )
            if not is_thread:
                continue
            for kw in call.keywords:
                if kw.arg != "target":
                    continue
                v = kw.value
                if (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                    and v.attr in cls.methods
                ):
                    out.append(cls.methods[v.attr])
    return out


def _class_closure(
    graph: PackageGraph, cls: ClassInfo, entries: list[str]
) -> set[str]:
    """Methods (and their nested defs) reachable from ``entries`` through
    self-calls, restricted to this class's own functions."""
    own = set()
    for mq in cls.methods.values():
        own.add(mq)
        stack = [mq]
        while stack:
            q = stack.pop()
            for child in graph.functions[q].nested:
                if child not in own:
                    own.add(child)
                    stack.append(child)
    reach = graph.reachable(entries)
    return {q for q in reach if q in own}


def run(graph: PackageGraph) -> list[Finding]:
    findings: list[Finding] = []
    for cls in graph.classes.values():
        entries = thread_targets(graph, cls)
        if not entries:
            continue
        init_q = cls.methods.get("__init__")
        thread_side = _class_closure(graph, cls, entries)
        public_entries = [
            mq
            for mname, mq in cls.methods.items()
            if (not mname.startswith("_") or mname in _PUBLIC_DUNDERS)
        ]
        public_side = _class_closure(graph, cls, public_entries)

        writes: dict[str, list[_Write]] = {}
        for mq in sorted(thread_side | public_side):
            if mq == init_q:
                continue  # construction happens-before the thread
            fn = graph.functions[mq]
            for w in attr_writes(fn.node, mq):
                writes.setdefault(w.attr, []).append(w)

        for attr in sorted(writes):
            sites = writes[attr]
            t_sites = [w for w in sites if w.method in thread_side]
            p_sites = [w for w in sites if w.method in public_side]
            if not t_sites or not p_sites:
                continue  # single-sided: not a cross-thread attribute
            unlocked = [w for w in sites if not w.locked]
            if not unlocked:
                continue
            first = min(unlocked, key=lambda w: w.lineno)
            lines = ", ".join(
                str(w.lineno) for w in sorted(unlocked, key=lambda w: w.lineno)
            )
            t_m = graph.functions[t_sites[0].method].name
            p_m = graph.functions[p_sites[0].method].name
            findings.append(
                Finding(
                    path=cls.rel,
                    line=first.lineno,
                    code="L015",
                    message=(
                        f"attribute `self.{attr}` of {cls.name} is "
                        f"written from the thread target path "
                        f"(`{t_m}`) and the public API (`{p_m}`) with "
                        f"unlocked write(s) at line(s) {lines} — guard "
                        f"every shared write with `with self._lock:` / "
                        f"`with self._cv:`"
                    ),
                )
            )
    return findings
