"""Multi-pass, whole-package static analysis behind ``tools/check.py``.

The gate's reference analog is the scalastyle + Apache RAT pair of the
reference build: zero-setup, stdlib-only, every source file must pass
before code lands. The passes, in execution order:

1. :mod:`tools.analysis.core` — parse every file ONCE (syntax errors are
   findings of the single parse, not a separate compile phase) and carry
   the shared ASTs, ``# photon: noqa[Lxxx]`` suppressions, and the
   ``--baseline`` diff machinery.
2. :mod:`tools.analysis.local` — the per-file AST lint (L001-L012),
   formerly the monolithic ``_Lint`` visitor inside check.py.
3. :mod:`tools.analysis.callgraph` — module index + import-resolved
   intra-package call graph over ``photon_ml_tpu/`` (AST-only: the gate
   still runs in hermetic images with no linters installed).
4. :mod:`tools.analysis.hotpath` — L013: the L010/L011 path lists become
   *seeds*; hotness propagates transitively along call edges, and a sync
   or bare jit hiding in a helper module is reported with its full call
   chain.
5. :mod:`tools.analysis.jitpurity` — L014: functions traced by
   ``instrumented_jit`` / ``jax.jit`` / ``lax.while_loop`` / ``lax.scan``
   (resolved through the call graph) must not touch host state — those
   effects run once at trace time and silently never again.
6. :mod:`tools.analysis.locks` — L015: classes that spawn threads must
   guard attributes written from both the thread target and public
   methods with ``with self._lock/_cv``.
7. :mod:`tools.analysis.faultcov` — L016: every registered fault-
   injection point (``photon_ml_tpu.faults``) must be exercised by at
   least one test — an unarmed injection seam is untested recovery code
   wearing a coverage badge.

:mod:`tools.analysis.driver` orchestrates all of it and owns the CLI
surface (``--json``, ``--baseline``, ``--write-baseline``, ``--root``).
"""

from tools.analysis.driver import analyze, Result  # noqa: F401 (re-export)

__all__ = ["analyze", "Result"]
