"""Orchestration: file discovery, the single parse, every pass, then
suppressions and the baseline diff. ``tools/check.py`` is a thin CLI over
:func:`analyze`.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
from typing import Optional

from tools.analysis import faultcov, hotpath, jitpurity, local, locks
from tools.analysis.callgraph import build_graph
from tools.analysis.core import (
    Finding,
    SourceFile,
    apply_suppressions,
    collect_suppressions,
    load_source,
    split_baseline,
    syntax_findings,
)

TARGETS = ("photon_ml_tpu", "tests", "tools", "__graft_entry__.py")
PACKAGE_DIR = "photon_ml_tpu"


def source_files(root: str) -> list[str]:
    # every bench script is gated (a literal list silently missed new ones)
    out = sorted(_glob.glob(os.path.join(root, "bench*.py")))
    for t in TARGETS:
        path = os.path.join(root, t)
        if os.path.isfile(path):
            out.append(path)
            continue
        if not os.path.isdir(path):
            continue  # --root trees (tests) may carry only the package
        for walk_root, _dirs, files in os.walk(path):
            out.extend(
                os.path.join(walk_root, f)
                for f in files
                if f.endswith(".py")
            )
    return sorted(out)


@dataclasses.dataclass
class Result:
    root: str
    files: list[SourceFile]
    findings: list[Finding]  # NEW findings: these fail the gate
    grandfathered: list[Finding]  # matched --baseline entries
    stale_baseline: list[tuple[str, str, str]]  # baseline keys gone stale
    # call-graph coverage (tests assert the interprocedural passes really
    # ran over the whole package, not a silently empty graph)
    graph_stats: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> dict:
        return {
            "version": 1,
            "root": self.root,
            "files": len(self.files),
            "findings": [f.to_json() for f in self.findings],
            "grandfathered": [f.to_json() for f in self.grandfathered],
            "stale_baseline": [list(k) for k in self.stale_baseline],
            "counts": self.counts(),
            "graph": self.graph_stats,
        }


def analyze(
    root: str,
    baseline: Optional[dict] = None,  # key -> count, or a set (count 1)
    require_seeds: bool = True,
) -> Result:
    """Run the whole gate over ``root``. ``require_seeds=False`` relaxes
    the W002 seed check for reduced test trees that intentionally carry
    only a few modules."""
    files = [
        load_source(os.path.relpath(p, root), p) for p in source_files(root)
    ]
    findings = syntax_findings(files)

    pkg_prefix = PACKAGE_DIR + os.sep
    for sf in files:
        if sf.tree is None:
            continue
        if os.path.basename(sf.rel) == "__init__.py":
            continue  # re-export surfaces import without using
        findings.extend(
            local.lint_file(
                sf.rel, sf.tree, library=sf.rel.startswith(pkg_prefix)
            )
        )

    # interprocedural passes over the library package (incl. __init__
    # trees: re-export bindings are what resolution follows)
    package_files = [sf for sf in files if sf.rel.startswith(pkg_prefix)]
    graph = build_graph(package_files)
    findings.extend(hotpath.run(graph, require_seeds=require_seeds))
    findings.extend(jitpurity.run(graph))
    findings.extend(locks.run(graph))
    if require_seeds:
        # L016 fault-point coverage needs the real tests/ tree; reduced
        # fixture trees (require_seeds=False) legitimately carry neither
        findings.extend(faultcov.run(files))
    graph_stats = {
        "modules": len(graph.modules),
        "functions": len(graph.functions),
        "classes": len(graph.classes),
    }

    suppressions = {}
    for sf in files:
        per_file = collect_suppressions(sf)
        if per_file:
            suppressions[sf.rel] = per_file
    kept, unused_warnings = apply_suppressions(findings, suppressions)
    kept.extend(unused_warnings)
    kept.sort(key=lambda f: (f.path, f.line, f.code, f.message))

    if baseline:
        new, grandfathered, stale = split_baseline(kept, baseline)
    else:
        new, grandfathered, stale = kept, [], []
    return Result(
        root=root,
        files=files,
        findings=new,
        grandfathered=grandfathered,
        stale_baseline=stale,
        graph_stats=graph_stats,
    )
