"""Orchestration: file discovery, the single parse, every pass, then
suppressions and the baseline diff. ``tools/check.py`` is a thin CLI over
:func:`analyze`.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
from typing import Optional

from tools.analysis import dataflow, faultcov, hotpath, jitpurity, local, locks
from tools.analysis.callgraph import build_graph
from tools.analysis.core import (
    BAD_SEED,
    Finding,
    SourceFile,
    apply_suppressions,
    collect_suppressions,
    dedupe_chain_findings,
    load_source,
    split_baseline,
    syntax_findings,
)

TARGETS = ("photon_ml_tpu", "tests", "tools", "__graft_entry__.py")
PACKAGE_DIR = "photon_ml_tpu"


def source_files(root: str) -> list[str]:
    # every bench script is gated (a literal list silently missed new ones)
    out = sorted(_glob.glob(os.path.join(root, "bench*.py")))
    for t in TARGETS:
        path = os.path.join(root, t)
        if os.path.isfile(path):
            out.append(path)
            continue
        if not os.path.isdir(path):
            continue  # --root trees (tests) may carry only the package
        for walk_root, _dirs, files in os.walk(path):
            out.extend(
                os.path.join(walk_root, f)
                for f in files
                if f.endswith(".py")
            )
    return sorted(out)


@dataclasses.dataclass
class Result:
    root: str
    files: list[SourceFile]
    findings: list[Finding]  # NEW findings: these fail the gate
    grandfathered: list[Finding]  # matched --baseline entries
    stale_baseline: list[tuple[str, str, str]]  # baseline keys gone stale
    # call-graph + dataflow coverage (tests assert the interprocedural
    # passes really ran over the whole package, not a silently empty
    # graph: modules/functions/classes, dataflow functions/taint edges,
    # lock-order graph size)
    graph_stats: dict = dataclasses.field(default_factory=dict)
    # --changed mode: the analyzed scope (changed files + call-graph
    # dependents), or None for a full-tree run
    changed_scope: Optional[list[str]] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> dict:
        out = {
            "version": 1,
            "root": self.root,
            "files": len(self.files),
            "findings": [f.to_json() for f in self.findings],
            "grandfathered": [f.to_json() for f in self.grandfathered],
            "stale_baseline": [list(k) for k in self.stale_baseline],
            "counts": self.counts(),
            "graph": self.graph_stats,
        }
        if self.changed_scope is not None:
            out["changed_scope"] = self.changed_scope
        return out


def changed_scope(
    graph, files: list[SourceFile], changed: set[str]
) -> set[str]:
    """``changed`` rel paths + their transitive call-graph DEPENDENTS:
    every file holding a function that (transitively) calls into a
    changed file. A changed callee's behavior is visible in its callers,
    so a pre-commit run must re-judge them too; files neither changed
    nor depending on a change are out of scope."""
    # file-level reverse edges: callee rel -> {caller rels}
    rdeps: dict[str, set[str]] = {}
    for fn in graph.functions.values():
        for resolved, _call in fn.calls:
            target = graph.resolve_call_target(resolved)
            if target is not None:
                callee_rel = graph.functions[target].rel
                if callee_rel != fn.rel:
                    rdeps.setdefault(callee_rel, set()).add(fn.rel)
    scope = {sf.rel for sf in files if sf.rel in changed}
    frontier = list(scope)
    while frontier:
        rel = frontier.pop()
        for caller in rdeps.get(rel, ()):
            if caller not in scope:
                scope.add(caller)
                frontier.append(caller)
    return scope


def analyze(
    root: str,
    baseline: Optional[dict] = None,  # key -> count, or a set (count 1)
    require_seeds: bool = True,
    changed: Optional[set[str]] = None,
) -> Result:
    """Run the whole gate over ``root``. ``require_seeds=False`` relaxes
    the W002 seed check for reduced test trees that intentionally carry
    only a few modules.

    ``changed`` (rel paths) switches on the fast pre-commit scope: the
    whole tree is still PARSED and the interprocedural passes still run
    over the full graph (a partial graph would silently weaken them),
    but per-file lint runs only on the changed files + their call-graph
    dependents, and findings are filtered to that scope. Full-tree
    behavior (``changed=None``) is unchanged and remains what tier-1
    runs."""
    files = [
        load_source(os.path.relpath(p, root), p) for p in source_files(root)
    ]
    pkg_prefix = PACKAGE_DIR + os.sep
    package_files = [sf for sf in files if sf.rel.startswith(pkg_prefix)]
    graph = build_graph(package_files)
    scope: Optional[set[str]] = None
    if changed is not None:
        scope = changed_scope(graph, files, changed)

    findings = syntax_findings(files)
    for sf in files:
        if sf.tree is None:
            continue
        if os.path.basename(sf.rel) == "__init__.py":
            continue  # re-export surfaces import without using
        if scope is not None and sf.rel not in scope:
            continue  # --changed: out-of-scope files keep their lint
        findings.extend(
            local.lint_file(
                sf.rel, sf.tree, library=sf.rel.startswith(pkg_prefix)
            )
        )

    # interprocedural passes over the library package (incl. __init__
    # trees: re-export bindings are what resolution follows) — ALWAYS
    # the full graph, even under --changed
    findings.extend(hotpath.run(graph, require_seeds=require_seeds))
    findings.extend(jitpurity.run(graph))
    findings.extend(locks.run(graph))
    lock_stats: dict = {}
    findings.extend(locks.run_lock_order(graph, lock_stats))
    df_stats = dataflow.Stats()
    findings.extend(
        dataflow.run(graph, df_stats, require_seeds=require_seeds)
    )
    if require_seeds:
        # L016 fault-point coverage needs the real tests/ tree; reduced
        # fixture trees (require_seeds=False) legitimately carry neither
        findings.extend(faultcov.run(files))
    graph_stats = {
        "modules": len(graph.modules),
        "functions": len(graph.functions),
        "classes": len(graph.classes),
        "dataflow": {
            "functions": df_stats.functions,
            "taint_edges": df_stats.taint_edges,
            "jit_callables": df_stats.jit_callables,
            "donating_callables": df_stats.donating_callables,
        },
        "locks": lock_stats,
    }

    findings = dedupe_chain_findings(findings)
    if scope is not None:
        # W002 (a configured seed/sanitizer that no longer resolves) is
        # pass-config health, reported against tools/analysis/ paths that
        # are never in a package scope — scoping it out would let the
        # exact pre-commit workflow it guards land the disarming rename
        findings = [
            f for f in findings
            if f.path in scope or f.code == BAD_SEED
        ]

    suppressions = {}
    for sf in files:
        if scope is not None and sf.rel not in scope:
            continue  # out-of-scope W001s would be pre-commit noise
        per_file = collect_suppressions(sf)
        if per_file:
            suppressions[sf.rel] = per_file
    kept, unused_warnings = apply_suppressions(findings, suppressions)
    kept.extend(unused_warnings)
    kept.sort(key=lambda f: (f.path, f.line, f.code, f.message))

    if baseline:
        new, grandfathered, stale = split_baseline(kept, baseline)
    else:
        new, grandfathered, stale = kept, [], []
    return Result(
        root=root,
        files=files,
        findings=new,
        grandfathered=grandfathered,
        stale_baseline=stale,
        graph_stats=graph_stats,
        changed_scope=sorted(scope) if scope is not None else None,
    )
