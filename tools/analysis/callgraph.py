"""Pass 1: module index + import-resolved intra-package call graph.

AST-only and stdlib-only by design — the gate must run in hermetic images
with nothing installed, so resolution is static name-following, not
import execution:

- every module under the package root is indexed: top-level functions,
  class methods, and *nested* functions (the repo's dominant jit idiom is
  a closure factory — ``_fe_solver`` returning ``instrumented_jit(run)`` —
  so nested defs are first-class graph nodes, connected to their enclosing
  function by a containment edge);
- import bindings (``import m as x``, ``from pkg.mod import f as g``,
  relative forms) are recorded per module from the WHOLE file, including
  function-local imports (``ScoringEngine.load`` imports the model store
  inside the method body);
- calls resolve through those bindings, following re-exports one hop at a
  time (``telemetry.instrumented_jit`` -> ``telemetry/__init__`` binding
  -> ``telemetry.xla.instrumented_jit``), ``self.method`` to the defining
  class, and ``ClassName(...)`` to ``ClassName.__init__``;
- unresolvable calls (dynamic attributes, externals) resolve to a dotted
  name when the root is an imported module (``t.time`` with
  ``import time as t`` -> ``time.time`` — exactly what the wall-clock and
  jit detectors need) and to ``None`` otherwise. Inheritance is NOT
  walked: a miss means a silently absent edge, so passes that depend on
  reachability keep their seed lists explicit and verified (W002).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Optional

from tools.analysis.core import SourceFile


@dataclasses.dataclass
class FunctionInfo:
    qname: str
    name: str
    module: str  # module dotted name
    rel: str  # file path (for findings)
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    lineno: int
    class_qname: Optional[str] = None
    parent: Optional[str] = None  # enclosing function qname (nested defs)
    nested: list = dataclasses.field(default_factory=list)  # child qnames
    # (resolved dotted name or None, ast.Call) for every call in the OWN
    # body — nested defs collect their own calls
    calls: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    qname: str
    name: str
    module: str
    rel: str
    node: ast.ClassDef
    methods: dict = dataclasses.field(default_factory=dict)  # name -> qname


@dataclasses.dataclass
class ModuleInfo:
    name: str
    rel: str
    tree: ast.Module
    is_init: bool
    bindings: dict = dataclasses.field(default_factory=dict)  # name -> dotted


def module_name_for(rel: str) -> tuple[str, bool]:
    """repo-relative path -> (dotted module name, is __init__)."""
    parts = rel[: -len(".py")].split(os.sep)
    if parts[-1] == "__init__":
        return ".".join(parts[:-1]), True
    return ".".join(parts), False


def own_body_nodes(fn_node: ast.AST):
    """Yield every AST node of a def's own body, NOT descending into
    nested function/class definitions (those are separate graph nodes);
    lambdas stay inline — their calls belong to the enclosing function."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


class PackageGraph:
    """Whole-package index + call graph (see module docstring)."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}

    # -- resolution ----------------------------------------------------------

    def resolve_export(self, dotted: str) -> str:
        """Follow import re-exports until the name stops moving.

        ``photon_ml_tpu.telemetry.instrumented_jit`` resolves through the
        ``__init__`` binding to ``photon_ml_tpu.telemetry.xla
        .instrumented_jit``; external names (``jax.lax.while_loop``,
        ``time.time``) come back unchanged — detectors match on them."""
        seen = set()
        while dotted not in seen:
            seen.add(dotted)
            if (
                dotted in self.functions
                or dotted in self.classes
                or dotted in self.modules
            ):
                return dotted
            head, _, tail = dotted.rpartition(".")
            if not head:
                return dotted
            if head in self.modules:
                nxt = self.modules[head].bindings.get(tail)
                if nxt is None or nxt == dotted:
                    return dotted
                dotted = nxt
                continue
            resolved_head = self.resolve_export(head)
            if resolved_head == head:
                return dotted
            dotted = resolved_head + "." + tail
        return dotted

    def _resolve_func_expr(
        self, fn: FunctionInfo, expr: ast.AST
    ) -> Optional[str]:
        """Resolve a Call.func expression to a dotted name, or None."""
        if isinstance(expr, ast.Name):
            # enclosing-function scope chain: own nested defs first, then
            # each ancestor function's nested defs
            scope: Optional[FunctionInfo] = fn
            while scope is not None:
                cand = scope.qname + "." + expr.id
                if cand in self.functions:
                    return cand
                scope = (
                    self.functions.get(scope.parent)
                    if scope.parent
                    else None
                )
            mod = self.modules[fn.module]
            cand = mod.name + "." + expr.id
            if cand in self.functions or cand in self.classes:
                return cand
            target = mod.bindings.get(expr.id)
            if target is not None:
                return self.resolve_export(target)
            return None
        if isinstance(expr, ast.Attribute):
            parts: list[str] = []
            root: ast.AST = expr
            while isinstance(root, ast.Attribute):
                parts.append(root.attr)
                root = root.value
            parts.reverse()
            if not isinstance(root, ast.Name):
                return None
            if root.id in ("self", "cls") and fn.class_qname is not None:
                if len(parts) == 1:
                    cand = fn.class_qname + "." + parts[0]
                    if cand in self.functions:
                        return cand
                return None
            mod = self.modules[fn.module]
            base = mod.bindings.get(root.id)
            if base is None:
                # a sibling definition used as a namespace (rare) or an
                # unimported name — give up rather than guess
                cand = mod.name + "." + root.id
                if cand in self.classes:
                    base = cand
                else:
                    return None
            return self.resolve_export(base + "." + ".".join(parts))
        return None

    def resolve_call_target(self, resolved: Optional[str]) -> Optional[str]:
        """Map a resolved dotted name to a graph FUNCTION node, following
        ``ClassName`` to ``ClassName.__init__``; None for externals."""
        if resolved is None:
            return None
        if resolved in self.functions:
            return resolved
        if resolved in self.classes:
            init = self.classes[resolved].methods.get("__init__")
            return init
        return None

    def callees(self, qname: str) -> list[tuple[str, int]]:
        """(callee function qname, call lineno) edges, including the
        containment edges to nested defs (a closure factory's inner
        function runs whenever the factory's product is called — the
        conservative reading that makes hot-path propagation sound for
        the ``return instrumented_jit(run)`` idiom)."""
        fn = self.functions[qname]
        out = []
        for resolved, call in fn.calls:
            target = self.resolve_call_target(resolved)
            if target is not None:
                out.append((target, call.lineno))
        for child in fn.nested:
            out.append((child, self.functions[child].lineno))
        return out

    def reachable(
        self, seeds: list[str]
    ) -> dict[str, tuple[Optional[str], int]]:
        """BFS closure: qname -> (predecessor qname or None for a seed,
        lineno of the edge's call site). Shortest chains by construction."""
        frontier = [q for q in seeds if q in self.functions]
        visited: dict[str, tuple[Optional[str], int]] = {
            q: (None, self.functions[q].lineno) for q in frontier
        }
        while frontier:
            nxt: list[str] = []
            for q in frontier:
                for callee, lineno in self.callees(q):
                    if callee not in visited:
                        visited[callee] = (q, lineno)
                        nxt.append(callee)
            frontier = nxt
        return visited

    def chain_to(
        self, reach: dict[str, tuple[Optional[str], int]], qname: str
    ) -> tuple[str, ...]:
        """Seed-first call chain for a reached function."""
        chain = [qname]
        cur = qname
        while True:
            pred = reach[cur][0]
            if pred is None:
                break
            chain.append(pred)
            cur = pred
        return tuple(reversed(chain))


# ---------------------------------------------------------------------------
# Construction
# ---------------------------------------------------------------------------


def _collect_bindings(mod: ModuleInfo) -> None:
    base_parts = mod.name.split(".")
    if not mod.is_init:
        base_parts = base_parts[:-1]
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname is not None:
                    mod.bindings[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    mod.bindings[root] = root
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            if node.level == 0:
                prefix = node.module or ""
            else:
                up = base_parts[: len(base_parts) - (node.level - 1)]
                prefix = ".".join(up + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                target = f"{prefix}.{a.name}" if prefix else a.name
                mod.bindings[a.asname or a.name] = target


def _direct_defs(body):
    """Function/class statements directly in scope: descends through
    control flow (if/try/with bodies) but never across another def or
    class boundary — those open their own scope."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            yield node
            continue
        stack.extend(ast.iter_child_nodes(node))


class _DefIndexer:
    """Index top-level functions, class methods, and nested defs."""

    def __init__(self, graph: PackageGraph, mod: ModuleInfo):
        self.graph = graph
        self.mod = mod
        self._func_stack: list[str] = []  # enclosing function qnames
        self._class_stack: list[str] = []  # enclosing class qnames

    def _qualify(self, name: str) -> str:
        if self._func_stack:
            return self._func_stack[-1] + "." + name
        if self._class_stack:
            return self._class_stack[-1] + "." + name
        return self.mod.name + "." + name

    def index_module(self) -> None:
        for node in _direct_defs(self.mod.tree.body):
            self._visit(node)

    def _visit(self, node) -> None:
        if isinstance(node, ast.ClassDef):
            if self._func_stack:
                return  # classes defined inside functions: out of scope
            self._visit_class(node)
        else:
            self._visit_def(node)

    def _visit_class(self, node: ast.ClassDef) -> None:
        qname = self._qualify(node.name)
        self.graph.classes[qname] = ClassInfo(
            qname=qname,
            name=node.name,
            module=self.mod.name,
            rel=self.mod.rel,
            node=node,
        )
        self._class_stack.append(qname)
        for child in _direct_defs(node.body):
            self._visit(child)
        self._class_stack.pop()

    def _visit_def(self, node) -> None:
        qname = self._qualify(node.name)
        in_class = bool(self._class_stack) and not self._func_stack
        info = FunctionInfo(
            qname=qname,
            name=node.name,
            module=self.mod.name,
            rel=self.mod.rel,
            node=node,
            lineno=node.lineno,
            class_qname=self._class_stack[-1] if in_class else None,
            parent=self._func_stack[-1] if self._func_stack else None,
        )
        self.graph.functions[qname] = info
        if info.parent:
            self.graph.functions[info.parent].nested.append(qname)
        if in_class:
            self.graph.classes[self._class_stack[-1]].methods[
                node.name
            ] = qname
        self._func_stack.append(qname)
        for child in _direct_defs(node.body):
            self._visit(child)
        self._func_stack.pop()


def build_graph(package_files: list[SourceFile]) -> PackageGraph:
    """Index + resolve the call graph over the package's source files."""
    graph = PackageGraph()
    for sf in package_files:
        if sf.tree is None:
            continue  # syntax errors are already findings
        name, is_init = module_name_for(sf.rel)
        mod = ModuleInfo(name=name, rel=sf.rel, tree=sf.tree, is_init=is_init)
        _collect_bindings(mod)
        graph.modules[name] = mod
    for mod in graph.modules.values():
        _DefIndexer(graph, mod).index_module()
    # resolve calls only after EVERY module is indexed (forward refs,
    # re-exports through __init__ surfaces)
    for fn in graph.functions.values():
        for node in own_body_nodes(fn.node):
            if isinstance(node, ast.Call):
                fn.calls.append(
                    (graph._resolve_func_expr(fn, node.func), node)
                )
    return graph
