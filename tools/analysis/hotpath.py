"""Pass 2 — L013 hot-path propagation.

The per-file L010/L011 path lists only guard code written *inside* the
listed modules; a helper one call away escaped them entirely. Here those
lists become seeds: hotness propagates transitively along the call graph,
and a ``float(x)`` sync or bare ``jax.jit`` hiding in ``utils/`` that is
reachable from ``ScoringEngine.score_rows`` or a solver loop is flagged
with the full call chain in the message.

Two propagation flavors:

- **sync hotness** from the serving request path (the L010 semantics:
  ``jax.device_get`` / ``np.asarray`` / ``float(non-constant)`` /
  ``block_until_ready`` cost a tunnel round trip per request). Seeds are
  the request-path entry points, NOT whole modules — ``ScoringEngine
  .load`` legitimately syncs at model-load time and must not poison the
  walk. The one sanctioned crossing (``telemetry.device.sync_fetch`` —
  its ``np.asarray`` IS the accounted fetch) is excluded by name.
- **jit hotness** from every function defined in the L011 hot scope (the
  training/serving compile surface): any transitively reachable function
  registering a bare ``jax.jit`` escapes the executable registry.
  ``telemetry.xla`` (the instrumented wrapper itself — the one place a
  real ``jax.jit`` must exist) and the L011 cold allowlist are excluded.

A configured seed that no longer resolves (e.g. a rename) is itself a
finding (W002): a silently empty seed list would mean the pass stops
guarding without anyone noticing.
"""

from __future__ import annotations

import ast

from tools.analysis import local
from tools.analysis.callgraph import FunctionInfo, PackageGraph
from tools.analysis.core import BAD_SEED, Finding

#: Serving request-path entry points (qualified names). Keep in sync with
#: photon_ml_tpu/serving/: a rename here surfaces as W002, not silence.
SYNC_SEEDS = (
    "photon_ml_tpu.serving.engine.ScoringEngine.score_rows",
    "photon_ml_tpu.serving.engine.ScoringEngine.warmup",
    "photon_ml_tpu.serving.batcher.MicroBatcher.submit",
    "photon_ml_tpu.serving.batcher.MicroBatcher._loop",
    "photon_ml_tpu.serving.batcher.ContinuousBatcher._collect",
    "photon_ml_tpu.serving.server.ScoringService.score_request",
    "photon_ml_tpu.serving.server.ScoringService.submit_rows",
    # the event-loop request path: a sync here stalls EVERY connection
    "photon_ml_tpu.serving.aio.AsyncScoringServer._route",
    "photon_ml_tpu.serving.aio.AsyncScoringServer._score",
    # fleet observability (ISSUE 13): the supervisor's status thread and
    # its telemetry tail parser are pure-filesystem monitors — a device
    # sync here would couple "is the fleet alive?" to a possibly-wedged
    # device, exactly when the operator needs the answer most
    "photon_ml_tpu.telemetry.progress.tail_heartbeat_fields",
    "photon_ml_tpu.parallel.fleet_status.FleetStatusWriter.snapshot",
    "photon_ml_tpu.parallel.fleet_status.FleetStatusWriter.write_once",
    # executable-level profiler (ISSUE 16): the dispatch sampler wraps
    # EVERY instrumented_jit call — its one honest device sync must stay
    # routed through the sanctioned telemetry.device.sync_fetch crossing
    # (a bare np.asarray/device_get here would re-open the fake-timing
    # trap on the hottest path in the process). A rename surfaces as
    # W002, not silence.
    "photon_ml_tpu.telemetry.profile.profile_dispatch",
    # request-scoped tracing (ISSUE 18): finish() runs on every request
    # (batcher dispatcher thread, router pool threads) and flight_dump()
    # on the SIGTERM drain path — a device sync inside trace bookkeeping
    # would wedge the event loop / block the drain exactly when the
    # process is being told to die
    "photon_ml_tpu.telemetry.requests.RequestTracer.finish",
    "photon_ml_tpu.telemetry.requests.RequestTracer.flight_dump",
)

#: The sanctioned device->host crossing: its body is the accounted fetch.
SANCTIONED_SYNC = {"photon_ml_tpu.telemetry.device.sync_fetch"}

#: Modules whose bare jax.jit is the *implementation* of the instrumented
#: wrapper — the one legitimate jit callsite in the package.
SANCTIONED_JIT_MODULES = {"photon_ml_tpu.telemetry.xla"}


def _short(qname: str) -> str:
    prefix = "photon_ml_tpu."
    return qname[len(prefix):] if qname.startswith(prefix) else qname


def short_chain(chain: tuple[str, ...]) -> tuple[str, ...]:
    return tuple(_short(q) for q in chain)


# ---------------------------------------------------------------------------
# Site detectors (shared with tests; operate on one function's own body)
# ---------------------------------------------------------------------------


def sync_sites(fn: FunctionInfo) -> list[tuple[ast.Call, str]]:
    """(call node, description) for every device->host sync in the body."""
    out = []
    for resolved, call in fn.calls:
        f = call.func
        if resolved == "jax.device_get" or (
            isinstance(f, ast.Attribute) and f.attr == "device_get"
        ) or (isinstance(f, ast.Name) and f.id == "device_get"):
            out.append((call, "jax.device_get"))
        elif resolved == "numpy.asarray" or (
            isinstance(f, ast.Attribute)
            and f.attr == "asarray"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy")
        ):
            out.append((call, "np.asarray (forces a device fetch)"))
        elif isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
            out.append((call, "block_until_ready"))
        elif (
            isinstance(f, ast.Name)
            and f.id == "float"
            and call.args
            and not all(isinstance(a, ast.Constant) for a in call.args)
        ):
            out.append((call, "float() on a non-constant"))
    return out


def jit_sites(fn: FunctionInfo) -> list[tuple[ast.AST, str]]:
    """(node, description) for every bare jax.jit registration."""
    out = []
    for resolved, call in fn.calls:
        if resolved == "jax.jit":
            out.append((call, "jax.jit(...)"))
    for dec in getattr(fn.node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            if (
                isinstance(dec, ast.Attribute)
                and dec.attr == "jit"
                and isinstance(dec.value, ast.Name)
                and dec.value.id == "jax"
            ):
                out.append((dec, "@jax.jit"))
    return out


# ---------------------------------------------------------------------------
# The pass
# ---------------------------------------------------------------------------


def run(
    graph: PackageGraph,
    sync_seeds: tuple[str, ...] = SYNC_SEEDS,
    require_seeds: bool = True,
) -> list[Finding]:
    findings: list[Finding] = []

    # -- sync propagation from the serving request path ---------------------
    present = [q for q in sync_seeds if q in graph.functions]
    if require_seeds:
        for missing in sorted(set(sync_seeds) - set(present)):
            findings.append(
                Finding(
                    path="tools/analysis/hotpath.py",
                    line=0,
                    code=BAD_SEED,
                    message=(
                        f"hot-path seed `{missing}` no longer resolves — "
                        f"the serving sync pass is not guarding it; update "
                        f"SYNC_SEEDS to the renamed entry point"
                    ),
                )
            )
    reach = graph.reachable(present)
    for qname in sorted(reach):
        fn = graph.functions[qname]
        if fn.rel in local.L010_HOT_PATH:
            continue  # already covered line-by-line by per-file L010
        if qname in SANCTIONED_SYNC or any(
            qname.startswith(s + ".") for s in SANCTIONED_SYNC
        ):
            continue
        chain = short_chain(graph.chain_to(reach, qname))
        for node, desc in sync_sites(fn):
            findings.append(
                Finding(
                    path=fn.rel,
                    line=node.lineno,
                    code="L013",
                    message=(
                        f"{desc} is reachable from serving hot path "
                        f"`{chain[0]}` — every request pays the tunnel "
                        f"round trip; fetch through telemetry.sync_fetch "
                        f"or lift the sync out of the request path"
                    ),
                    chain=chain,
                    site=desc,
                )
            )

    # -- jit propagation from the L011 hot scope ----------------------------
    jit_seeds = sorted(
        q
        for q, fn in graph.functions.items()
        if local.is_l011_hot(fn.rel)
    )
    if require_seeds:
        # same guarantee as SYNC_SEEDS: renaming a hot file/dir must not
        # silently disarm both per-file L011 AND the transitive jit pass
        present_rels = {fn.rel for fn in graph.functions.values()}
        for f in sorted(local.L011_HOT_FILES):
            if f not in present_rels:
                findings.append(
                    Finding(
                        path="tools/analysis/hotpath.py",
                        line=0,
                        code=BAD_SEED,
                        message=(
                            f"L011 hot file `{f}` has no functions in the "
                            f"call graph — renamed? update L011_HOT_FILES "
                            f"or the jit pass stops guarding it"
                        ),
                    )
                )
        for d in local.L011_HOT_DIRS:
            if not any(rel.startswith(d) for rel in present_rels):
                findings.append(
                    Finding(
                        path="tools/analysis/hotpath.py",
                        line=0,
                        code=BAD_SEED,
                        message=(
                            f"L011 hot dir `{d}` matches no modules — "
                            f"renamed? update L011_HOT_DIRS or the jit "
                            f"pass stops guarding it"
                        ),
                    )
                )
    reach = graph.reachable(jit_seeds)
    for qname in sorted(reach):
        fn = graph.functions[qname]
        if local.is_l011_hot(fn.rel):
            continue  # per-file L011 already covers these
        if fn.rel in local.L011_COLD_ALLOWLIST:
            continue
        if fn.module in SANCTIONED_JIT_MODULES:
            continue
        chain = short_chain(graph.chain_to(reach, qname))
        for node, desc in jit_sites(fn):
            findings.append(
                Finding(
                    path=fn.rel,
                    line=node.lineno,
                    code="L013",
                    message=(
                        f"bare {desc} is reachable from hot path "
                        f"`{chain[0]}` — its compiles escape the "
                        f"executable registry (no cost analysis, no "
                        f"recompile attribution); use telemetry.xla"
                        f".instrumented_jit(fn, name=...)"
                    ),
                    chain=chain,
                    site=desc,
                )
            )
    return findings
