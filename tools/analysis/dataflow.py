"""Pass 6 — interprocedural def-use/taint dataflow: L017 donation safety
and L019 unsanctioned host transfer.

The syntactic passes (L001-L016) match names and call chains; none of
them track *values*. The two nastiest bugs in the tree so far were value
bugs exactly those passes could not see: the donated
``dynamic_update_slice`` that aliased a borrowed host-numpy buffer
(freed-heap garbage, timing-dependent — the PR 10 class), and hidden
device->host transfers whose sink was never a named sync call on a
seeded path. This pass is a small, deliberately-bounded dataflow engine
over the existing per-function ASTs:

- **intraprocedural**: each function is executed abstractly, statement
  by statement, propagating *taints* through assignments, tuple
  unpacking, views/slices, loops, and branch joins (branch environments
  union; loop bodies run twice to reach the loop-carried fixpoint);
- **interprocedural, one call level deep**: every function gets a
  *summary* — the taints it returns, the parameters it donates, the
  parameters it pushes into host-forcing sinks — and call sites stitch
  caller taints through callee summaries using the SAME import/self/
  re-export resolution rules the L013/L014 passes use. Summaries are
  computed in a first phase and consumed in a second, so a flow through
  one helper (and often deeper, via summaries-of-summaries) is visible.

Taint kinds:

- ``borrowed`` — host memory this code does not own: the result of
  ``np.load(..., mmap_mode=...)``, ``np.frombuffer``, a staging-ring
  slot, or a view/slice/field of a function parameter (a view NEVER
  transfers ownership). Borrowed values must not reach a donated
  argument slot of ``instrumented_jit``/``jax.jit`` (**L017**): XLA
  frees a donated buffer after the program runs, and when device_put
  zero-copied the borrowed host array, "frees" means another owner's
  heap — the PR 10 freed-heap-garbage bug. Sanctioned laundering
  copies (``parallel.sharding.place_entity_rows``/``_owned_copy`` — the
  ``place_entity_rows_copy`` executable — ``jnp.array(..., copy=True)``,
  ``.copy()``) strip the taint.
- ``device`` — the result of calling a jitted executable (a value
  living in device memory). Flowing one into a host-forcing sink —
  ``float()``/``int()``, ``np.asarray``, ``.tolist()``, ``json.dump``,
  a comparison inside a branch condition — outside
  ``telemetry.device.sync_fetch`` is an unaccounted device->host
  transfer (**L019**): exactly the syncs L013 misses because the sink
  is not a named sync call on a seeded path.
- ``jitref`` — a callable produced by ``instrumented_jit``/``jax.jit``
  (tracked through factory helpers that *return* one, the repo's
  dominant idiom), carrying its ``donate_argnums`` so call sites know
  which argument slots donate.

Findings carry the full flow chain (source, each binding hop, sink) so
a report reads as the story of the bug, not a point coordinate.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from tools.analysis.callgraph import FunctionInfo, PackageGraph
from tools.analysis.core import BAD_SEED, Finding
from tools.analysis.hotpath import SANCTIONED_SYNC, _short

BORROWED = "borrowed"
DEVICE = "device"
PARAM = "param"
JITREF = "jitref"

#: jit wrappers whose result is a device-executable callable; positional
#: arg 0 is the traced function, ``donate_argnums`` names donated slots.
JIT_WRAPPERS = {
    "jax.jit",
    "photon_ml_tpu.telemetry.xla.instrumented_jit",
}

#: Resolved names whose RESULT is owned device memory no matter what went
#: in: the sanctioned laundering copies (strips ``borrowed``).
COPY_SANITIZERS = {
    "photon_ml_tpu.parallel.sharding._owned_copy",
    "photon_ml_tpu.parallel.sharding.place_entity_rows",
}

#: Resolved names whose result is borrowed host memory.
RING_SOURCES = {
    "photon_ml_tpu.ingest.buffers.BufferRing.acquire",
}

#: Attribute calls that return views/aliases of their argument — taint
#: flows THROUGH them (np.asarray may alias; device_put may zero-copy an
#: aligned host array — the exact PR 10 hazard).
_VIEW_FUNCS = {
    "asarray", "device_put", "reshape", "ravel", "transpose", "squeeze",
    "atleast_1d", "atleast_2d",
}

#: Maximum recorded flow hops per taint (keeps messages readable).
_MAX_STEPS = 6

#: Array METADATA attributes: reading them is host-side bookkeeping, not
#: a transfer (``scores.shape[1] > n`` compares static ints) and never a
#: borrowed view.
_METADATA_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
    "is_deleted", "device", "devices",
}

#: Module whose device-sink findings are suppressed wholesale: the
#: instrumented-jit wrapper itself legitimately measures executables.
_SANCTIONED_MODULES = {
    "photon_ml_tpu.telemetry.xla",
    "photon_ml_tpu.telemetry.device",
}


@dataclasses.dataclass(frozen=True)
class Taint:
    """One taint label. ``param`` links the taint to the function's own
    parameter index (summaries key on it); ``steps`` is the flow chain
    accumulated binding by binding."""

    kind: str
    desc: str = ""
    line: int = 0
    param: Optional[int] = None
    donated: tuple = ()  # JITREF: donated positional argnums
    jit_name: str = ""  # JITREF: executable name (for messages)
    steps: tuple = ()

    def with_step(self, step: str) -> "Taint":
        if len(self.steps) >= _MAX_STEPS:
            return self
        return dataclasses.replace(self, steps=self.steps + (step,))

    def flow(self) -> str:
        """`source (line N) -> hop (line M) -> ...` for the message."""
        parts = [f"{self.desc} (line {self.line})"] if self.desc else []
        parts.extend(self.steps)
        return " -> ".join(parts)


@dataclasses.dataclass
class Summary:
    """What a function does with taint, seen from a call site."""

    qname: str
    # taints of the returned value; PARAM entries mean "returns arg i"
    returns: set = dataclasses.field(default_factory=set)
    # param index -> (donation line, executable name, how) — a plain or
    # viewed parameter reaches a donated slot inside this function
    param_donations: dict = dataclasses.field(default_factory=dict)
    # param index -> (sink line, sink description) — a parameter reaches
    # a host-forcing sink inside this function
    param_sinks: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Stats:
    functions: int = 0
    taint_edges: int = 0
    jit_callables: int = 0
    donating_callables: int = 0


def _attr_parts(expr: ast.AST):
    """`a.b.c` -> (Name a, ["b", "c"]); (None, []) otherwise."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    parts.reverse()
    return (expr if isinstance(expr, ast.Name) else None, parts)


def _donated_argnums(call: ast.Call) -> tuple:
    """Donated positional indices from a jit registration call; an
    ``(idxs) if cond else ()`` conditional takes the donating branch —
    the conservative reading."""

    def idxs_of(expr) -> tuple:
        if isinstance(expr, (ast.Tuple, ast.List)):
            return tuple(
                int(e.value)
                for e in expr.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            )
        if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
            return (int(expr.value),)
        if isinstance(expr, ast.IfExp):
            return tuple(sorted(set(idxs_of(expr.body))
                                | set(idxs_of(expr.orelse))))
        return ()

    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return idxs_of(kw.value)
    return ()


def _jit_name(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    if call.args:
        root, parts = _attr_parts(call.args[0])
        if parts:
            return parts[-1]
        if isinstance(call.args[0], ast.Name):
            return call.args[0].id
    return "jit"


class _FunctionFlow:
    """Abstract execution of ONE function body."""

    def __init__(
        self,
        graph: PackageGraph,
        fn: FunctionInfo,
        summaries: dict,
        stats: Stats,
        findings: Optional[list] = None,
    ):
        self.graph = graph
        self.fn = fn
        self.summaries = summaries
        self.stats = stats
        self.findings = findings
        self.summary = Summary(qname=fn.qname)
        self.env: dict[str, frozenset] = {}
        self.param_names: dict[str, int] = {}
        self._emitted: set = set()
        args = fn.node.args
        all_args = (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
        for i, a in enumerate(all_args):
            if a.arg in ("self", "cls"):
                continue
            self.param_names[a.arg] = i
            self.env[a.arg] = frozenset(
                {Taint(kind=PARAM, desc=f"parameter `{a.arg}`",
                       line=fn.lineno, param=i)}
            )

    # -- driving -------------------------------------------------------------

    def run(self) -> Summary:
        self._exec_block(self.fn.node.body)
        return self.summary

    def _exec_block(self, stmts) -> None:
        for stmt in stmts:
            self._exec_stmt(stmt)

    def _exec_stmt(self, stmt) -> None:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # nested defs are their own graph nodes
        if isinstance(stmt, ast.Assign):
            taints = self._eval(stmt.value)
            for target in stmt.targets:
                self._bind(target, taints, stmt.value)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self._eval(stmt.value), stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            taints = self._eval(stmt.value) | self._lookup(stmt.target)
            self._bind(stmt.target, taints, stmt.value)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.summary.returns |= self._eval(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self._eval(stmt.value)
        elif isinstance(stmt, ast.If):
            self._branch_test(stmt.test)
            self._eval(stmt.test)
            before = dict(self.env)
            self._exec_block(stmt.body)
            after_body = self.env
            self.env = dict(before)
            self._exec_block(stmt.orelse)
            self._merge(after_body)
        elif isinstance(stmt, (ast.While,)):
            self._branch_test(stmt.test)
            self._eval(stmt.test)
            for _ in range(2):  # loop-carried taint fixpoint
                snapshot = dict(self.env)
                self._exec_block(stmt.body)
                self._merge(snapshot)
            # the test re-executes per iteration with the LOOP-CARRIED
            # env — `while err > tol:` over a jitted `err` is the
            # canonical convergence-loop transfer
            self._branch_test(stmt.test)
            self._exec_block(stmt.orelse)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            iter_taints = self._eval(stmt.iter)
            self._bind(stmt.target, iter_taints, stmt.iter)
            for _ in range(2):
                snapshot = dict(self.env)
                self._exec_block(stmt.body)
                self._merge(snapshot)
            self._exec_block(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                taints = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, taints, item.context_expr)
            self._exec_block(stmt.body)
        elif isinstance(stmt, ast.Try):
            before = dict(self.env)
            self._exec_block(stmt.body)
            for handler in stmt.handlers:
                self._merge(before)
                self._exec_block(handler.body)
            self._exec_block(stmt.orelse)
            self._exec_block(stmt.finalbody)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._eval(child)
        # pass/break/continue/import/global/del: no taint flow

    def _merge(self, other: dict) -> None:
        for name, taints in other.items():
            if name in self.env:
                self.env[name] = self.env[name] | taints
            else:
                self.env[name] = taints

    # -- binding -------------------------------------------------------------

    def _bind(self, target, taints: frozenset, value_expr) -> None:
        taints = frozenset(
            t for t in taints if t.kind in (BORROWED, DEVICE, JITREF, PARAM)
        )
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = (
                value_expr.elts
                if isinstance(value_expr, (ast.Tuple, ast.List))
                and len(value_expr.elts) == len(target.elts)
                else None
            )
            for i, el in enumerate(target.elts):
                if elts is not None:
                    self._bind(el, self._eval(elts[i]), elts[i])
                else:
                    self._bind(el, taints, value_expr)
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, taints, value_expr)
            return
        key = self._env_key(target)
        if key is None:
            return
        if isinstance(target, ast.Subscript):
            # an ELEMENT write (`buf[0] = x`) mutates the array without
            # disowning it: merge, never kill, the base binding's taint
            taints = taints | self.env.get(key, frozenset())
        if taints:
            step = f"`{key}` (line {getattr(target, 'lineno', 0)})"
            self.env[key] = frozenset(t.with_step(step) for t in taints)
            self.stats.taint_edges += 1
        else:
            self.env[key] = frozenset()

    def _env_key(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return expr.id
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return "self." + expr.attr
        if isinstance(expr, ast.Subscript):
            return self._env_key(expr.value)
        return None

    def _lookup(self, expr) -> frozenset:
        key = self._env_key(expr)
        return self.env.get(key, frozenset()) if key else frozenset()

    # -- expression evaluation ----------------------------------------------

    def _eval(self, expr) -> frozenset:
        if expr is None:
            return frozenset()
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, frozenset())
        if isinstance(expr, ast.Attribute):
            return self._eval_attribute(expr)
        if isinstance(expr, ast.Subscript):
            self._eval(expr.slice)
            base = self._eval(expr.value)
            out = set(base)
            for t in base:
                if t.kind == PARAM:
                    # a slice/view of a parameter is BORROWED memory: the
                    # view aliases the caller's buffer, ownership never
                    # transferred
                    out.add(
                        Taint(
                            kind=BORROWED,
                            desc=f"view/slice of {t.desc}",
                            line=expr.lineno,
                            param=t.param,
                        )
                    )
            return frozenset(out)
        if isinstance(expr, ast.Call):
            return self._eval_call(expr)
        if isinstance(expr, ast.BinOp):
            return self._eval(expr.left) | self._eval(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self._eval(expr.operand)
        if isinstance(expr, ast.BoolOp):
            out = frozenset()
            for v in expr.values:
                out |= self._eval(v)
            return out
        if isinstance(expr, ast.Compare):
            out = self._eval(expr.left)
            for c in expr.comparators:
                out |= self._eval(c)
            return out
        if isinstance(expr, ast.IfExp):
            self._eval(expr.test)
            return self._eval(expr.body) | self._eval(expr.orelse)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = frozenset()
            for el in expr.elts:
                out |= self._eval(el)
            return out
        if isinstance(expr, ast.Dict):
            out = frozenset()
            for k, v in zip(expr.keys, expr.values):
                if k is not None:
                    self._eval(k)
                out |= self._eval(v)
            return out
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value)
        if isinstance(expr, (ast.JoinedStr, ast.FormattedValue)):
            for child in ast.iter_child_nodes(expr):
                if isinstance(child, ast.expr):
                    self._eval(child)
            return frozenset()
        if isinstance(expr, ast.NamedExpr):
            taints = self._eval(expr.value)
            self._bind(expr.target, taints, expr.value)
            return taints
        if isinstance(
            expr, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            # comprehensions: propagate the iterable's taint to the result
            out = frozenset()
            for gen in expr.generators:
                out |= self._eval(gen.iter)
            return out
        if isinstance(expr, ast.Await):
            return self._eval(expr.value)
        return frozenset()

    def _eval_attribute(self, expr: ast.Attribute) -> frozenset:
        root, parts = _attr_parts(expr)
        if root is not None and root.id == "self" and len(parts) == 1:
            return self.env.get("self." + parts[0], frozenset())
        if expr.attr in _METADATA_ATTRS:
            self._eval(expr.value)
            return frozenset()
        base = self._eval(expr.value)
        out = set(base)
        for t in base:
            if t.kind == PARAM:
                # a field of a caller-owned object (a staging-ring slot's
                # `.values`, a chunk's arrays): borrowed, like a view
                out.add(
                    Taint(
                        kind=BORROWED,
                        desc=f"field `.{expr.attr}` of {t.desc}",
                        line=expr.lineno,
                        param=t.param,
                    )
                )
        return frozenset(out)

    # -- calls ---------------------------------------------------------------

    def _eval_call(self, call: ast.Call) -> frozenset:
        arg_taints = [self._eval(a) for a in call.args]
        for kw in call.keywords:
            self._eval(kw.value)
        func = call.func
        root, parts = _attr_parts(func)
        attr = parts[-1] if parts else None
        resolved = self.graph._resolve_func_expr(self.fn, func)

        # ---- sanitizers ----------------------------------------------------
        if resolved in COPY_SANITIZERS:
            return frozenset()
        if resolved in SANCTIONED_SYNC or attr == "sync_fetch":
            return frozenset()  # the accounted fetch: result is host-owned
        if attr == "copy" and not call.args and isinstance(
            func, ast.Attribute
        ):
            return frozenset()  # x.copy(): an owned copy
        if attr == "copy" and root is not None and root.id in (
            "np", "numpy", "jnp",
        ):
            return frozenset()  # np.copy(x) / jnp.copy(x)
        if attr == "array" and root is not None and root.id in (
            "np", "numpy", "jnp",
        ):
            # np.array / jnp.array copy by default; copy=False/None
            # ALIASES — not a sanitizer, taint flows through like a view
            for kw in call.keywords:
                if kw.arg == "copy" and (
                    not isinstance(kw.value, ast.Constant)
                    or kw.value.value in (False, None)
                ):
                    out = set()
                    for at in arg_taints:
                        out |= {
                            t for t in at if t.kind in (BORROWED, DEVICE)
                        }
                    return frozenset(out)
            return frozenset()

        # ---- borrowed sources ----------------------------------------------
        if attr == "load" and root is not None and root.id in (
            "np", "numpy",
        ):
            for kw in call.keywords:
                if kw.arg == "mmap_mode" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None
                ):
                    return frozenset(
                        {Taint(BORROWED,
                               "np.load(mmap_mode=...) memory-mapped file",
                               call.lineno)}
                    )
            return frozenset()
        if attr == "frombuffer" and root is not None and root.id in (
            "np", "numpy",
        ):
            return frozenset(
                {Taint(BORROWED, "np.frombuffer view", call.lineno)}
            )
        if resolved in RING_SOURCES or (
            resolved is None and attr == "acquire" and root is not None
            and "ring" in root.id.lower()
        ):
            return frozenset(
                {Taint(BORROWED, "staging-ring buffer", call.lineno)}
            )

        # ---- jit registration ----------------------------------------------
        if resolved in JIT_WRAPPERS or attr == "instrumented_jit":
            donated = _donated_argnums(call)
            self.stats.jit_callables += 1
            if donated:
                self.stats.donating_callables += 1
            return frozenset(
                {Taint(JITREF, "jitted callable", call.lineno,
                       donated=donated, jit_name=_jit_name(call))}
            )

        # ---- calling a jitted callable -------------------------------------
        func_taints = self._eval(func) if not isinstance(
            func, (ast.Name, ast.Attribute)
        ) else self._lookup_callable(func)
        result: set = set()
        for t in func_taints:
            if t.kind != JITREF:
                continue
            result.add(
                Taint(DEVICE, f"result of jitted `{t.jit_name}`",
                      call.lineno)
            )
            for i in t.donated:
                if i < len(arg_taints):
                    self._check_donation(call, i, arg_taints[i], t.jit_name)

        # ---- callee summaries (one call level deep) ------------------------
        target = self.graph.resolve_call_target(resolved)
        summary = self.summaries.get(target) if target else None
        if summary is not None:
            callee = self.graph.functions[target]
            for i, (dline, jname, how) in sorted(
                summary.param_donations.items()
            ):
                if i < len(arg_taints):
                    self._check_donation_via(
                        call, i, arg_taints[i], callee, dline, jname, how
                    )
            for i, (sline, sdesc) in sorted(summary.param_sinks.items()):
                if i < len(arg_taints):
                    self._check_sink_via(
                        call, i, arg_taints[i], callee, sline, sdesc
                    )
            for t in summary.returns:
                if t.kind == PARAM and t.param is not None:
                    if t.param < len(arg_taints):
                        result |= set(arg_taints[t.param])
                elif t.kind == BORROWED and t.param is not None:
                    # callee returns a view of its parameter: the result
                    # aliases whatever the caller passed
                    if t.param < len(arg_taints):
                        src = arg_taints[t.param]
                        link = None
                        for s in src:
                            if s.kind == PARAM:
                                link = s.param
                        result.add(
                            Taint(BORROWED,
                                  f"{t.desc} via `{callee.name}`",
                                  call.lineno, param=link)
                        )
                elif t.kind in (BORROWED, DEVICE, JITREF):
                    result.add(
                        dataclasses.replace(
                            t, param=None, line=call.lineno,
                            desc=(t.desc if t.kind == JITREF
                                  else f"{t.desc} via `{callee.name}`"),
                            steps=(),
                        )
                    )

        # ---- host-forcing sinks (L019) -------------------------------------
        self._check_host_sinks(call, arg_taints, root, attr, func)
        if isinstance(func, ast.Name) and resolved is None and not parts:
            # unresolved bare-name call (builtins): no propagation
            return frozenset(result)
        if attr in _VIEW_FUNCS:
            for at in arg_taints:
                result |= {
                    t for t in at if t.kind in (BORROWED, DEVICE)
                }
        return frozenset(result)

    def _lookup_callable(self, func) -> frozenset:
        """Taints of a call's FUNC expression: env for names/self-attrs,
        full eval for anything else (e.g. ``factory(x)(args)``)."""
        key = self._env_key(func)
        if key is not None and key in self.env:
            return self.env[key]
        return self._eval(func)

    # -- L017 emission -------------------------------------------------------

    def _check_donation(
        self, call, idx: int, taints: frozenset, jit_name: str
    ) -> None:
        for t in taints:
            if t.kind == BORROWED:
                if t.param is not None:
                    # a view of OUR OWN parameter donated here: flag it
                    # (the view aliases the caller's buffer no matter
                    # what the caller passed) AND summarize it so a
                    # caller handing us borrowed memory is flagged too
                    self.summary.param_donations.setdefault(
                        t.param, (call.lineno, jit_name, t.desc)
                    )
                self._emit_l017(call.lineno, idx, jit_name, t, chain=None)
            elif t.kind == PARAM:
                # donating the plain parameter is the CALLER's contract
                # (the streaming-table idiom): summary only
                self.summary.param_donations.setdefault(
                    t.param, (call.lineno, jit_name, t.desc)
                )

    def _check_donation_via(
        self, call, idx, taints, callee, dline, jname, how
    ) -> None:
        for t in taints:
            if t.kind == BORROWED:
                if t.param is not None:
                    self.summary.param_donations.setdefault(
                        t.param, (call.lineno, jname, t.desc)
                    )
                else:
                    self._emit_l017(
                        call.lineno, idx, jname, t,
                        chain=(self.fn.qname, callee.qname),
                        via=(callee, dline, how),
                    )
            elif t.kind == PARAM:
                self.summary.param_donations.setdefault(
                    t.param, (call.lineno, jname, t.desc)
                )

    def _emit_l017(
        self, lineno, idx, jit_name, taint, chain=None, via=None
    ) -> None:
        if self.findings is None:
            return
        key = ("L017", lineno, idx, jit_name, taint.desc)
        if key in self._emitted:
            return
        self._emitted.add(key)
        flow = taint.flow()
        if via is not None:
            callee, dline, how = via
            detail = (
                f"flows into `{callee.name}` which donates it "
                f"(argument {idx} -> `{jit_name}`, "
                f"{callee.rel}:{dline})"
            )
        else:
            detail = f"flows into donated argument {idx} of `{jit_name}`"
        self.findings.append(
            Finding(
                path=self.fn.rel,
                line=lineno,
                code="L017",
                message=(
                    f"borrowed host memory [{flow}] {detail} — XLA frees "
                    f"donated buffers after the program runs, so a "
                    f"zero-copied borrowed view becomes freed-heap "
                    f"garbage (the PR 10 bug class); launder through "
                    f"parallel.sharding.place_entity_rows_copy or "
                    f"jnp.array(..., copy=True) before donating"
                ),
                chain=tuple(_short(q) for q in chain) if chain else (
                    _short(self.fn.qname),
                ),
                site=f"donation:{idx}:{jit_name}:{taint.desc}",
            )
        )

    # -- L019 emission -------------------------------------------------------

    def _device_taints(self, taints: frozenset):
        return [t for t in taints if t.kind == DEVICE]

    def _param_taints(self, taints: frozenset):
        return [t for t in taints if t.kind == PARAM]

    def _check_host_sinks(self, call, arg_taints, root, attr, func) -> None:
        sink = None
        checked: list = []
        if isinstance(func, ast.Name) and func.id in ("float", "int"):
            if call.args and not all(
                isinstance(a, ast.Constant) for a in call.args
            ):
                sink = f"{func.id}()"
                checked = arg_taints[:1]
        elif attr == "asarray" and root is not None and root.id in (
            "np", "numpy",
        ):
            sink = "np.asarray"
            checked = arg_taints[:1]
        elif attr == "tolist":
            sink = ".tolist()"
            checked = [self._eval(func.value)]
        elif attr == "dump" and root is not None and root.id == "json":
            sink = "json.dump"
            checked = arg_taints[:1]
        if sink is None:
            return
        for taints in checked:
            for t in self._device_taints(taints):
                self._emit_l019(call.lineno, sink, t)
            for t in self._param_taints(taints):
                self.summary.param_sinks.setdefault(
                    t.param, (call.lineno, sink)
                )

    def _branch_test(self, test) -> None:
        """Comparison-in-branch: `if jitted_result > x:` forces the
        transfer implicitly — no named sync call for L013 to see.
        Identity checks (`is None` / `is not None`) read a pointer, not
        the value, and are exempt."""
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                if all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
                ):
                    continue
                for side in [node.left] + list(node.comparators):
                    for t in self._device_taints(self._eval(side)):
                        self._emit_l019(
                            node.lineno, "comparison in a branch condition",
                            t,
                        )

    def _check_sink_via(self, call, idx, taints, callee, sline, sdesc):
        for t in self._device_taints(taints):
            self._emit_l019(
                call.lineno, sdesc, t,
                chain=(self.fn.qname, callee.qname),
                via=(callee, sline),
            )

    def _emit_l019(self, lineno, sink, taint, chain=None, via=None) -> None:
        if self.findings is None:
            return
        if self.fn.qname in SANCTIONED_SYNC or any(
            self.fn.qname.startswith(s + ".") for s in SANCTIONED_SYNC
        ):
            return
        if self.fn.module in _SANCTIONED_MODULES:
            return
        key = ("L019", lineno, sink, taint.desc)
        if key in self._emitted:
            return
        self._emitted.add(key)
        where = ""
        if via is not None:
            callee, sline = via
            where = f" (inside `{callee.name}`, {callee.rel}:{sline})"
        self.findings.append(
            Finding(
                path=self.fn.rel,
                line=lineno,
                code="L019",
                message=(
                    f"{sink}{where} forces a device->host transfer of "
                    f"{taint.flow()} outside telemetry.device.sync_fetch "
                    f"— an unaccounted sync the hot-path walk cannot "
                    f"see; fetch through sync_fetch (the accounted "
                    f"crossing) or keep the value on device"
                ),
                chain=tuple(_short(q) for q in chain) if chain else (
                    _short(self.fn.qname),
                ),
                site=f"transfer:{sink}:{taint.desc}",
            )
        )


# ---------------------------------------------------------------------------
# The pass
# ---------------------------------------------------------------------------


def run(
    graph: PackageGraph,
    stats: Optional[Stats] = None,
    require_seeds: bool = False,
) -> list[Finding]:
    """Two-phase taint analysis over the whole package graph.

    ``require_seeds=True`` (the real tree) additionally verifies the
    configured sanitizer/ring-source qnames still resolve: a rename of
    ``parallel.sharding._owned_copy`` or ``ingest.buffers.BufferRing
    .acquire`` must surface as W002, not as L017 silently laundering
    nothing / missing the ring source."""
    if stats is None:
        stats = Stats()
    findings: list[Finding] = []
    if require_seeds:
        for qname, what in sorted(
            [(q, "COPY_SANITIZERS") for q in COPY_SANITIZERS]
            + [(q, "RING_SOURCES") for q in RING_SOURCES]
        ):
            if qname not in graph.functions:
                findings.append(
                    Finding(
                        path="tools/analysis/dataflow.py",
                        line=0,
                        code=BAD_SEED,
                        message=(
                            f"dataflow seed `{qname}` ({what}) no longer "
                            f"resolves — renamed? update the table or "
                            f"L017 silently stops "
                            f"{'laundering' if what == 'COPY_SANITIZERS' else 'tracking'}"
                            f" through it"
                        ),
                    )
                )
    summaries: dict[str, Summary] = {}
    # phase A: local summaries (no callee knowledge)
    for qname, fn in sorted(graph.functions.items()):
        flow = _FunctionFlow(graph, fn, {}, Stats())
        summaries[qname] = flow.run()
    # phase B: re-analyze with summaries; collect findings + real stats
    for qname, fn in sorted(graph.functions.items()):
        stats.functions += 1
        flow = _FunctionFlow(graph, fn, summaries, stats, findings)
        summaries[qname] = flow.run()
    return findings
