"""The crash matrix: prove recovery for EVERY write-path fault point.

Spark's recovery machinery was exercised continuously by production task
retries; ours only runs when something breaks. This harness makes the
proof systematic instead of anecdotal: for every registered write-path
``FaultPoint`` (the atomic checkpoint protocol's phases — see
``photon_ml_tpu/faults/plan.py``), it

1. runs a deterministic streamed random-effect fit in a SUBPROCESS armed
   via ``PHOTON_FAULT_PLAN`` with an ``exit`` rule at that point — the
   process dies with ``os._exit`` (no unwinding, no atexit: a real
   preemption/OOM-kill shape) and the harness asserts it died with the
   injection exit code (113), i.e. AT the seam and not elsewhere;
2. re-runs the same fit UNARMED in the same working directory — the
   restore path walks newest-first past whatever the crash left behind
   (a half-assembled ``.tmp-`` dir, a payload without a manifest, a
   durable checkpoint without retention applied) and resumes;
3. asserts the resumed fit's final table EXACTLY matches the
   uninterrupted reference fit.

"newest-valid restore falls back past corrupt checkpoints" is thereby an
enumerated, CI-enforced property: tests/test_chaos.py runs a
budget-bounded slice of this matrix in tier-1, and static-analysis rule
L016 (tools/analysis/faultcov.py) refuses fault points no test names.

The DISTRIBUTED matrix (``--fleet``) extends the proof to partial fleet
failure: for every registered *distributed* fault point
(``faults.distributed_points()`` — fleet init, the heartbeat touch, the
per-process quorum manifest, collective entry), a 2-process gloo fleet
is launched under the ``tools/fleet.py`` supervisor with ONE member
armed to hard-kill at that seam (rc=113 asserted), the survivors are
boundary-stopped and the fit relaunched on the surviving host set via
``restore_placed()``; the resumed fit's final LOSS must match the
uninterrupted fleet reference to 1e-6, and an audit of the checkpoint
directory must find zero partially-certified checkpoints.

CLI::

    python -m tools.chaos --workdir /tmp/chaos            # full matrix
    python -m tools.chaos --workdir /tmp/chaos --json out.json
    python -m tools.chaos --workdir /tmp/chaos --fleet    # distributed rows
    python -m tools.chaos --workdir /tmp/chaos --pipeline # conductor rows
    python -m tools.chaos --workdir /tmp/chaos --quality  # publish-gate row
    python -m tools.chaos --worker --dir D                # one fit (internal)

The worker fit is self-contained and seed-deterministic (same chunk data
in every process), checkpoints at EVERY chunk boundary, and resumes from
the newest valid checkpoint on restart — the crash can land anywhere in
the protocol and the rerun must still converge to the reference bits.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Optional, Sequence

#: the worker fit's shape: small enough for CI, multi-chunk enough that a
#: first-boundary crash resumes mid-stream, entity count divisible by the
#: 8-device virtual mesh for sharded variants
N_ENTITIES = 16
N_ROWS = 8
DIM = 4
N_CHUNKS = 4
DATA_SEED = 20260803


def _worker_env(plan: Optional[dict]) -> dict:
    """Subprocess environment: CPU jax (cheap, deterministic), the shared
    compile cache if the parent set one, and the fault plan (if any)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PHOTON_FAULT_PLAN", None)
    if plan is not None:
        env["PHOTON_FAULT_PLAN"] = json.dumps(plan)
    return env


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_worker(
    workdir: str, plan: Optional[dict] = None, timeout: float = 600.0
) -> subprocess.CompletedProcess:
    """One worker fit in ``workdir`` (created if needed); checkpoints land
    in ``workdir/ckpt``, the final table in ``workdir/final.npy``."""
    os.makedirs(workdir, exist_ok=True)
    return subprocess.run(
        [sys.executable, "-m", "tools.chaos", "--worker", "--dir", workdir],
        env=_worker_env(plan),
        cwd=_repo_root(),
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def exit_plan(point: str, nth: int = 1) -> dict:
    """A fault plan that hard-kills the process at ``point``'s nth hit."""
    return {"rules": [{"point": point, "action": "exit", "nth": nth}]}


def run_matrix(
    workdir: str,
    points: Optional[Sequence[str]] = None,
    budget_s: Optional[float] = None,
    nth: int = 1,
) -> dict:
    """The crash matrix. Returns a JSON-safe report; ``ok`` is True only
    when every ATTEMPTED point passed all three assertions.

    ``budget_s`` bounds wall time: once exceeded, remaining points are
    reported under ``skipped`` (NEVER silently dropped) — the tier-1
    slice uses this so chaos coverage scales with the CI budget while
    the full matrix stays one CLI call away.
    """
    import numpy as np

    from photon_ml_tpu import faults

    # registration happens at import time; pull in every module that owns
    # a write-path seam so the enumeration is complete
    import photon_ml_tpu.game.checkpoint  # noqa: F401

    all_points = faults.write_path_points()
    points = list(points) if points is not None else all_points
    unknown = sorted(set(points) - set(all_points))
    if unknown:
        raise ValueError(
            f"not registered write-path fault points: {unknown} "
            f"(known: {all_points})"
        )
    t0 = time.monotonic()
    report: dict = {
        "workdir": workdir,
        "points": points,
        "nth": nth,
        "results": {},
        "skipped": [],
        "ok": True,
    }

    # uninterrupted reference fit (also warms the jax compile cache the
    # armed/resume runs reuse)
    ref_dir = os.path.join(workdir, "reference")
    proc = run_worker(ref_dir)
    if proc.returncode != 0:
        raise RuntimeError(
            f"reference fit failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
        )
    reference = np.load(os.path.join(ref_dir, "final.npy"))

    for point in points:
        if budget_s is not None and time.monotonic() - t0 > budget_s:
            report["skipped"] = [
                p for p in points if p not in report["results"]
            ]
            break
        entry: dict = {"point": point}
        point_dir = os.path.join(workdir, point.replace(".", "_"))
        armed = run_worker(point_dir, plan=exit_plan(point, nth=nth))
        entry["armed_rc"] = armed.returncode
        if armed.returncode != faults.DEFAULT_EXIT_CODE:
            entry["error"] = (
                f"armed run exited {armed.returncode}, expected "
                f"{faults.DEFAULT_EXIT_CODE} (did the point fire?)\n"
                f"{armed.stdout[-1000:]}\n{armed.stderr[-1000:]}"
            )
            report["results"][point] = entry
            report["ok"] = False
            continue
        resumed = run_worker(point_dir)  # unarmed rerun: restore + finish
        entry["resume_rc"] = resumed.returncode
        if resumed.returncode != 0:
            entry["error"] = (
                f"resume run failed (rc={resumed.returncode}):\n"
                f"{resumed.stdout[-1000:]}\n{resumed.stderr[-1000:]}"
            )
            report["results"][point] = entry
            report["ok"] = False
            continue
        got = np.load(os.path.join(point_dir, "final.npy"))
        entry["max_abs_delta"] = float(np.max(np.abs(got - reference)))
        entry["exact"] = bool(np.array_equal(got, reference))
        try:
            summary = json.loads(resumed.stdout.strip().splitlines()[-1])
            entry["resumed_from_chunk"] = summary.get("start_chunk")
        except (ValueError, IndexError):
            pass
        if not entry["exact"]:
            entry["error"] = (
                "resumed final table does not match the uninterrupted "
                f"reference (max |delta| = {entry['max_abs_delta']:g})"
            )
            report["ok"] = False
        report["results"][point] = entry
    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    return report


# ---------------------------------------------------------------------------
# the DISTRIBUTED crash matrix (fleet rows, via tools/fleet.py)
# ---------------------------------------------------------------------------

#: which hit of each fleet seam the victim dies on. Chosen so every row
#: that CAN have a certified checkpoint behind it does — the interesting
#: property is resuming from a certified coordinated checkpoint on the
#: survivors, not restarting from scratch:
#:   multihost.init          1st hit — dead before ever joining (the
#:                           relaunch-from-nothing row)
#:   fleet.heartbeat         6th touch — mid-fit between collectives
#:   checkpoint.peer_manifest 2nd save — one coordinated checkpoint is
#:                           already certified; the second is abandoned
#:                           by quorum timeout, never certified partial
#:   parallel.collective.entry 2nd chunk solve — the survivor wedges in
#:                           the collective and needs SIGKILL reclaim
FLEET_NTH = {
    "multihost.init": 1,
    "fleet.heartbeat": 6,
    "checkpoint.peer_manifest": 2,
    "parallel.collective.entry": 2,
}


def fleet_final_loss(table) -> float:
    """Total per-entity L2-regularized objective of a fleet worker's
    final table — the scalar the 1e-6 survivor-resume acceptance is
    stated over (cross-mesh fp noise keeps raw coefficients only to
    ~1e-3; at the optimum the loss delta is second-order)."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optim import glm_adapter
    from tools import fleet

    X, y = fleet.make_problem()
    obj = make_objective("logistic", l2_weight=0.3)
    total = 0.0
    for e in range(X.shape[0]):
        adapter = glm_adapter(obj, DenseBatch.from_arrays(X[e], y[e]))
        total += float(adapter.value_and_grad(jnp.asarray(table[e]))[0])
    return total


def run_fleet_matrix(
    workdir: str,
    points: Optional[Sequence[str]] = None,
    budget_s: Optional[float] = None,
) -> dict:
    """The distributed crash matrix: for every fleet fault seam, a
    2-process gloo fleet with one member hard-killed at the seam must
    (1) observe the member die WITH the injection exit code, (2) resume
    on the survivor and complete, (3) match the uninterrupted fleet
    reference's final loss to 1e-6, and (4) never certify a partial
    checkpoint (audited over the row's whole checkpoint directory).

    Budget-aware like :func:`run_matrix`: points beyond ``budget_s`` are
    reported ``skipped``, never silently dropped.
    """
    import numpy as np

    from photon_ml_tpu import faults

    # distributed seams register at import of their owning modules
    import photon_ml_tpu.game.checkpoint  # noqa: F401
    import photon_ml_tpu.parallel.distributed  # noqa: F401
    import photon_ml_tpu.parallel.multihost  # noqa: F401
    from tools import fleet

    # serving.* distributed seams belong to the SERVING fleet matrix
    # (run_serving_matrix): they fire in router/member processes, not in
    # a training fleet worker — arming one here could never fire
    all_points = [
        p for p in faults.distributed_points()
        if not p.startswith("serving.")
    ]
    points = list(points) if points is not None else all_points
    unknown = sorted(set(points) - set(all_points))
    if unknown:
        raise ValueError(
            f"not registered distributed fault points: {unknown} "
            f"(known: {all_points})"
        )
    t0 = time.monotonic()
    report: dict = {
        "workdir": workdir,
        "points": points,
        "results": {},
        "skipped": [],
        "ok": True,
    }

    def make_spec(
        subdir: str, plan: Optional[dict], detect_by: str = "exit_code"
    ) -> fleet.FleetSpec:
        return fleet.FleetSpec(
            workdir=os.path.join(workdir, subdir),
            num_processes=2,
            devices_per_process=2,
            victim_plan=plan,
            victim_process=1,
            quorum_timeout_s=3.0,
            grace_s=8.0,
            heartbeat_deadline_s=5.0,
            timeout_s=240.0,
            detect_by=detect_by,
        )

    # uninterrupted 2-process fleet reference (also warms the compile
    # cache every armed/relaunched worker reuses)
    ref = fleet.run_fleet(make_spec("reference_fleet", None))
    if not ref.get("ok"):
        raise RuntimeError(
            f"uninterrupted reference fleet failed: "
            f"{json.dumps(ref, default=str)[:2000]}"
        )
    ref_loss = fleet_final_loss(np.load(ref["final_path"]))
    report["reference_loss"] = ref_loss

    for point in points:
        if budget_s is not None and time.monotonic() - t0 > budget_s:
            report["skipped"] = [
                p for p in points if p not in report["results"]
            ]
            break
        entry: dict = {"point": point}
        subdir = point.replace(".", "_")
        plan = exit_plan(point, nth=FLEET_NTH.get(point, 1))
        # the heartbeat row runs detect_by="heartbeat": the lost-host
        # verdict must come from proc-<i>.alive STALENESS, not the exit
        # code — this is what makes the liveness protocol itself
        # crash-proven rather than just present
        run = fleet.run_fleet(make_spec(
            subdir, plan,
            detect_by="heartbeat" if point == "fleet.heartbeat"
            else "exit_code",
        ))
        gen0 = run["generations"][0]
        entry["generations"] = len(run["generations"])
        entry["relaunches"] = run.get("relaunches")
        entry["victim_rc"] = gen0["rcs"].get(1)
        entry["deaths"] = run.get("deaths_total")
        problems = []
        if gen0["rcs"].get(1) != faults.DEFAULT_EXIT_CODE:
            problems.append(
                f"victim exited {gen0['rcs'].get(1)}, expected "
                f"{faults.DEFAULT_EXIT_CODE} (did the seam fire?)"
            )
        if not run.get("ok"):
            problems.append(
                "fleet did not complete after the member death: "
                + json.dumps(run["generations"], default=str)[:1500]
            )
        else:
            got_loss = fleet_final_loss(np.load(run["final_path"]))
            entry["final_loss"] = got_loss
            entry["loss_delta"] = abs(got_loss - ref_loss)
            if entry["loss_delta"] >= 1e-6:
                problems.append(
                    "survivor-resumed final loss off the uninterrupted "
                    f"fleet reference by {entry['loss_delta']:g} (>= 1e-6)"
                )
        partial = fleet.verify_certified_checkpoints(
            os.path.join(workdir, subdir, "ckpt"),
            fleet.N_ENTITIES, fleet.DIM,
        )
        entry["partial_certified"] = partial
        if partial:
            problems.append(
                f"partially-certified checkpoint(s) observed: {partial}"
            )
        if problems:
            entry["error"] = "; ".join(problems)
            report["ok"] = False
        entry["passed"] = not problems
        report["results"][point] = entry
    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    return report


# ---------------------------------------------------------------------------
# the SERVING crash matrix (shard-owning fleet rows, via tools/fleet.py)
# ---------------------------------------------------------------------------

#: the serving rows, cheapest-first so a tight tier-1 budget still lands
#: the in-process seam proofs before the subprocess hard-kill row
SERVING_ROWS = (
    "member_load_io",
    "route_fanout_io",
    "resize_swap",
    "flight_dump_kill",
    "member_hard_kill",
)

#: hard-kill recovery budget: heartbeat-staleness detection plus a full
#: same-slot member relaunch (fresh interpreter + jax import + slice
#: load + warm) on a loaded CI host
KILL_RECOVERY_BUDGET_S = 120.0


def _mini_member(version_dir: str, announce_dir: str, member: int,
                 fleet_size: int, epoch: int = 0):
    """One IN-PROCESS shard member: engine slice behind a
    :class:`ShardMemberSource`, a :class:`ScoringServer` on an ephemeral
    port, and its announce record. Returns (server, source)."""
    from photon_ml_tpu.serving import (
        ScoringServer,
        ScoringService,
        ShardMemberSource,
        load_member_engine,
        write_announce,
    )

    def loader(fs, version=None):
        return load_member_engine(version_dir, member, fs, max_batch=16)

    source = ShardMemberSource(loader, member=member, fleet_size=fleet_size)
    source.commit(*source.stage(fleet_size))
    server = ScoringServer(ScoringService(source, max_batch=16), port=0)
    server.start()
    write_announce(announce_dir, {
        "member": member, "fleet_size": fleet_size, "epoch": epoch,
        "url": f"http://127.0.0.1:{server.port}",
        "version": source.engine.version, "ready": True,
        "pid": os.getpid(), "owned": {},
    })
    return server, source


def _serving_rows(n_entities: int) -> list[dict]:
    """Deterministic scoring rows covering every entity (so every member
    owns part of every batch)."""
    return [
        {
            "features": {
                "global": [[0, 0.5], [1, -0.25]],
                "user": [[0, 1.0], [1, 0.5]],
            },
            "ids": {"userId": str(i)},
        }
        for i in range(n_entities)
    ]


def run_serving_matrix(
    workdir: str,
    rows: Optional[Sequence[str]] = None,
    budget_s: Optional[float] = None,
    traffic_seconds: float = 8.0,
) -> dict:
    """The serving-fleet chaos matrix: every ``serving.*`` distributed
    seam plus the real hard-kill-under-traffic row.

    - ``member_load_io``: an injected IO failure in the slice load
      surfaces as ``OSError`` (a supervisor relaunch retries); the
      unarmed retry loads and serves.
    - ``route_fanout_io``: an injected fan-out failure degrades exactly
      that member's entity margins to fixed-effect-only — the request
      SUCCEEDS, ``serving.degraded_scores`` counts the shed, and the
      next request (seam exhausted, cooldown expired) is back to exact
      single-engine parity.
    - ``resize_swap``: an injected ownership-swap failure leaves the OLD
      fleet view serving untouched (counted
      ``serving.resize_swap_failures``); the unarmed refresh adopts the
      new epoch and parity holds across the swap.
    - ``flight_dump_kill``: a process hard-killed MID flight-recorder
      dump (injected exit at ``telemetry.flight_dump``) leaves nothing a
      fleet report will adopt — the tmp-then-rename contract, including
      planted ``.tmp`` debris — while the unarmed rerun's dump parses
      with every ring record.
    - ``member_hard_kill``: a real 3-process ``cli serve`` fleet under
      sustained router traffic, one member SIGKILLed mid-stream — zero
      non-shed request failures, degraded scores bounded and accounted,
      heartbeat detection + same-slot relaunch within the recovery
      budget, and every surviving member drains to exit 75.

    Budget-aware like :func:`run_matrix`: rows beyond ``budget_s`` are
    reported ``skipped``, never silently dropped.
    """
    import numpy as np

    from photon_ml_tpu import faults, telemetry
    from tools import fleet

    known = list(SERVING_ROWS)
    rows = list(rows) if rows is not None else known
    unknown = sorted(set(rows) - set(known))
    if unknown:
        raise ValueError(
            f"not serving chaos rows: {unknown} (known: {known})"
        )
    t0 = time.monotonic()
    report: dict = {
        "workdir": workdir,
        "rows": rows,
        "results": {},
        "skipped": [],
        "ok": True,
    }
    os.makedirs(workdir, exist_ok=True)
    n_entities = 12
    version_dir = fleet.make_serving_model(
        os.path.join(workdir, "registry"), n_entities=n_entities
    )

    def _fail(entry: dict, problems: list) -> None:
        if problems:
            entry["error"] = "; ".join(problems)
            report["ok"] = False
        entry["passed"] = not problems

    for row in rows:
        if budget_s is not None and time.monotonic() - t0 > budget_s:
            report["skipped"] = [
                r for r in rows if r not in report["results"]
            ]
            break
        entry: dict = {"row": row}
        problems: list = []
        faults.clear_plan()
        try:
            if row == "member_load_io":
                from photon_ml_tpu.serving import load_member_engine

                faults.install_plan(faults.FaultPlan([
                    faults.FaultRule(
                        "serving.member_load", action="io", nth=1
                    ),
                ]))
                try:
                    load_member_engine(version_dir, 0, 2, max_batch=16)
                    problems.append(
                        "armed slice load did not raise (seam misses the "
                        "load path?)"
                    )
                except OSError as e:
                    entry["armed_error"] = f"{type(e).__name__}: {e}"
                finally:
                    faults.clear_plan()
                engine = load_member_engine(version_dir, 0, 2, max_batch=16)
                got = engine.score_rows(_serving_rows(n_entities)[:4])
                entry["retry_scores"] = len(got)
                if len(got) != 4:
                    problems.append("unarmed retry did not serve")

            elif row in ("route_fanout_io", "resize_swap"):
                from photon_ml_tpu.serving import FleetRouter, ScoringEngine

                sub = os.path.join(workdir, row)
                announce = os.path.join(sub, "announce")
                os.makedirs(announce, exist_ok=True)
                members = [
                    _mini_member(version_dir, announce, m, 2)
                    for m in range(2)
                ]
                router = FleetRouter(
                    announce, _version_lookups(version_dir),
                    task="logistic", member_timeout_s=5.0,
                    cooldown_s=0.05, backoff_s=0.01,
                )
                ref_engine = ScoringEngine.load(version_dir, max_batch=16)
                ref_engine.warmup()
                score_rows = _serving_rows(n_entities)
                ref = np.asarray(ref_engine.score_rows(score_rows))
                try:
                    router.refresh()
                    if row == "route_fanout_io":
                        degraded0 = telemetry.counter(
                            "serving.degraded_scores"
                        ).value
                        faults.install_plan(faults.FaultPlan([
                            faults.FaultRule(
                                "serving.route_fanout", action="io", nth=1
                            ),
                        ]))
                        shed = np.asarray(router.score_rows(score_rows))
                        faults.clear_plan()
                        degraded = int(telemetry.counter(
                            "serving.degraded_scores"
                        ).value - degraded0)
                        entry["degraded_scores"] = degraded
                        if len(shed) != len(score_rows):
                            problems.append(
                                "degraded request dropped rows"
                            )
                        if not degraded:
                            problems.append(
                                "injected fan-out failure shed nothing "
                                "(seam misses the request path?)"
                            )
                        time.sleep(0.1)  # let the member cooldown lapse
                        clean = np.asarray(router.score_rows(score_rows))
                        entry["recovered_delta"] = float(
                            np.max(np.abs(clean - ref))
                        )
                        if entry["recovered_delta"] >= 1e-6:
                            problems.append(
                                "post-shed request off single-engine "
                                f"parity by {entry['recovered_delta']:g}"
                            )
                    else:  # resize_swap
                        from photon_ml_tpu.serving import write_announce

                        swaps_failed0 = telemetry.counter(
                            "serving.resize_swap_failures"
                        ).value
                        old_epoch = router.view.epoch
                        for m, (server, source) in enumerate(members):
                            write_announce(announce, {
                                "member": m, "fleet_size": 2, "epoch": 1,
                                "url": f"http://127.0.0.1:{server.port}",
                                "version": source.engine.version,
                                "ready": True, "pid": os.getpid(),
                                "owned": {},
                            })
                        faults.install_plan(faults.FaultPlan([
                            faults.FaultRule(
                                "serving.resize_swap", action="raise",
                                nth=1,
                            ),
                        ]))
                        router.refresh()
                        faults.clear_plan()
                        entry["swap_failures"] = int(telemetry.counter(
                            "serving.resize_swap_failures"
                        ).value - swaps_failed0)
                        if router.view.epoch != old_epoch:
                            problems.append(
                                "injected swap failure still adopted the "
                                "new epoch (old view not preserved)"
                            )
                        if not entry["swap_failures"]:
                            problems.append(
                                "swap failure not counted "
                                "serving.resize_swap_failures"
                            )
                        during = np.asarray(router.score_rows(score_rows))
                        entry["old_view_delta"] = float(
                            np.max(np.abs(during - ref))
                        )
                        if entry["old_view_delta"] >= 1e-6:
                            problems.append(
                                "old view served wrong scores under the "
                                "failed swap"
                            )
                        router.refresh()  # unarmed: adopt epoch 1
                        if router.view.epoch != 1:
                            problems.append(
                                "unarmed refresh did not adopt the new "
                                "epoch"
                            )
                        after = np.asarray(router.score_rows(score_rows))
                        if float(np.max(np.abs(after - ref))) >= 1e-6:
                            problems.append(
                                "post-swap scores off single-engine parity"
                            )
                finally:
                    router.close()
                    for server, _source in members:
                        server.stop()

            elif row == "flight_dump_kill":
                sub = os.path.join(workdir, row)
                os.makedirs(sub, exist_ok=True)
                snippet = (
                    "import os, sys\n"
                    "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
                    "from photon_ml_tpu import faults\n"
                    "faults.warn_if_armed()\n"
                    "from photon_ml_tpu.telemetry import requests as rq\n"
                    "for _ in range(5):\n"
                    "    rq.finish(rq.begin('score', rows=1))\n"
                    "n = rq.flight_dump(rq.flight_path(sys.argv[1], 0))\n"
                    "print('dumped', n)\n"
                )
                env = dict(os.environ)
                env["JAX_PLATFORMS"] = "cpu"
                env["PHOTON_FAULT_PLAN"] = json.dumps({
                    "rules": [{
                        "point": "telemetry.flight_dump",
                        "action": "exit", "nth": 1,
                    }],
                })
                armed = subprocess.run(
                    [sys.executable, "-c", snippet, sub],
                    env=env, capture_output=True, text=True, timeout=120,
                )
                entry["armed_rc"] = armed.returncode
                if armed.returncode != 113:
                    problems.append(
                        f"armed dump process exited {armed.returncode}, "
                        "expected the injected 113 (seam misses the "
                        "dump path?)"
                    )
                # a kill can also land between the tmp write and the
                # rename (the kernel-race shape no seam placement can
                # rule out) — plant exactly that debris and prove
                # discovery adopts neither it nor anything else
                with open(
                    os.path.join(sub, "flight-proc-1.json.tmp"),
                    "w", encoding="utf-8",
                ) as fh:
                    fh.write('{"type": "flight_record", "records": [')
                from photon_ml_tpu.telemetry import fleet_report
                from photon_ml_tpu.telemetry import requests as rq

                adopted = fleet_report.discover_flight_records(sub)
                entry["adopted_after_kill"] = sorted(adopted)
                if adopted:
                    problems.append(
                        "kill mid-dump left an adoptable flight record: "
                        f"{sorted(adopted.values())}"
                    )
                env.pop("PHOTON_FAULT_PLAN")
                clean = subprocess.run(
                    [sys.executable, "-c", snippet, sub],
                    env=env, capture_output=True, text=True, timeout=120,
                )
                if clean.returncode != 0:
                    problems.append(
                        f"unarmed rerun exited {clean.returncode}: "
                        f"{clean.stderr[-200:]}"
                    )
                doc = rq.read_flight(rq.flight_path(sub, 0))
                entry["clean_records"] = (
                    None if doc is None
                    else len(doc.get("records") or [])
                )
                if doc is None:
                    problems.append(
                        "unarmed rerun produced no parseable flight "
                        "record"
                    )
                elif len(doc.get("records") or []) != 5:
                    problems.append(
                        f"flight record carries {entry['clean_records']} "
                        "record(s), expected 5"
                    )

            elif row == "member_hard_kill":
                spec = fleet.ServingFleetSpec(
                    workdir=os.path.join(workdir, row),
                    model_dir=version_dir,
                    fleet_size=3,
                    traffic_seconds=traffic_seconds,
                    traffic_hz=10.0,
                    traffic_rows=6,
                    traffic_features=(("global", 2), ("user", 2)),
                    kill_member=1,
                    kill_after_s=min(2.0, traffic_seconds / 3),
                    relaunch=True,
                    heartbeat_deadline_s=2.0,
                )
                run = fleet.run_serving_fleet(spec)
                entry["routed_rows"] = run.get("routed_rows")
                entry["degraded_scores"] = run.get("degraded_scores")
                entry["degraded_fraction"] = run.get("degraded_fraction")
                entry["failures"] = len(run.get("failures") or [])
                entry["kill"] = run.get("kill")
                entry["rcs"] = run.get("rcs")
                if run.get("failures"):
                    problems.append(
                        "non-shed request failures under the kill: "
                        + "; ".join(
                            str(f) for f in run["failures"][:3]
                        )
                    )
                if not run.get("degraded_scores"):
                    problems.append(
                        "hard kill shed nothing (did the outage window "
                        "overlap traffic?)"
                    )
                if run.get("degraded_scores", 0) > run.get(
                    "routed_rows", 0
                ):
                    problems.append(
                        "degraded accounting exceeds routed rows"
                    )
                recovery = (run.get("kill") or {}).get("recovery_s")
                if recovery is None:
                    problems.append("no relaunch recovery recorded")
                elif recovery > KILL_RECOVERY_BUDGET_S:
                    problems.append(
                        f"recovery took {recovery:.1f}s "
                        f"(> {KILL_RECOVERY_BUDGET_S:.0f}s budget)"
                    )
                bad_rcs = {
                    m: rc for m, rc in (run.get("rcs") or {}).items()
                    if rc != 75
                }
                if bad_rcs:
                    problems.append(
                        f"members did not drain to exit 75: {bad_rcs}"
                    )
        except Exception as e:  # noqa: BLE001 — a row crash IS the finding
            problems.append(f"row crashed: {type(e).__name__}: {e}")
        finally:
            faults.clear_plan()
        _fail(entry, problems)
        report["results"][row] = entry
    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    return report


def _version_lookups(version_dir: str) -> dict:
    from photon_ml_tpu.serving import fleet_lookups_from_version_dir

    _task, _link, lookups = fleet_lookups_from_version_dir(version_dir)
    return lookups


# ---------------------------------------------------------------------------
# the PIPELINE crash matrix (freshness-conductor daemon rows)
# ---------------------------------------------------------------------------

#: the conductor's supervised-cycle seams, in cycle order. Every row
#: hard-kills the ``cli pipeline`` daemon subprocess AT the seam (rc=113
#: asserted) and must leave the warm-start base checkpoint byte-identical
#: and the registry free of partial versions; the unarmed rerun over the
#: same directories must publish a lineage-linked version.
PIPELINE_POINTS = (
    "pipeline.cycle_start",
    "pipeline.reconcile",
    "pipeline.escalate",
)


def _tree_digest(root: str) -> str:
    """Byte-level digest of a directory tree (relative paths + content) —
    the 'base checkpoint untouched' assertions are stated over this."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def _pipeline_fixture(workdir: str) -> dict:
    """The pipeline rows' shared world: a tiny avro base + one delta
    shard (touching 2 of 8 users plus one NEW user — a touched fraction
    safely under the conductor's default escalation threshold, so
    unarmed reruns stay incremental) + train config, and the base fit's
    step checkpoint built via ``cli train`` in a CPU subprocess.
    Returns {cfg_path, ckpt, delta_dir}."""
    import numpy as np

    from photon_ml_tpu.data.avro import TRAINING_EXAMPLE_AVRO, write_avro

    os.makedirs(workdir, exist_ok=True)
    rng = np.random.default_rng(DATA_SEED)
    d, n_users, n_base, n_delta = 6, 8, 160, 36
    X = rng.normal(size=(n_base + n_delta, d))
    users = np.concatenate([
        rng.integers(0, n_users, n_base),
        np.array([1, 2, n_users] * (n_delta // 3)),  # u1, u2 + NEW u8
    ])
    w = rng.normal(size=d)
    u_eff = rng.normal(size=n_users + 1)
    logits = X @ w + u_eff[users]
    y = (rng.random(len(users)) < 1 / (1 + np.exp(-logits))).astype(float)

    def recs(lo, hi):
        for i in range(lo, hi):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"c{j}", "term": "", "value": float(X[i, j])}
                    for j in range(d)
                ],
                "metadataMap": {"userId": str(users[i])},
                "weight": None,
                "offset": None,
            }

    train_path = os.path.join(workdir, "train.avro")
    delta_dir = os.path.join(workdir, "deltas")
    os.makedirs(delta_dir, exist_ok=True)
    write_avro(train_path, TRAINING_EXAMPLE_AVRO, recs(0, n_base))
    write_avro(os.path.join(delta_dir, "delta-0001.avro"),
               TRAINING_EXAMPLE_AVRO, recs(n_base, n_base + n_delta))
    ckpt = os.path.join(workdir, "base-ckpt")
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [train_path],
            "feature_shards": {"global": ["features"]},
            "id_columns": ["userId"],
        },
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "global",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 0.1},
            },
            "perUser": {
                "type": "random_effect",
                "shard_name": "global",
                "id_name": "userId",
                "optimizer": {"regularization": "l2",
                              "regularization_weight": 1.0},
            },
        },
        "num_iterations": 1,
        "output_dir": os.path.join(workdir, "base-model"),
        "checkpoint": {"dir": ckpt, "resume": False},
    }
    cfg_path = os.path.join(workdir, "train.json")
    with open(cfg_path, "w", encoding="utf-8") as fh:
        json.dump(config, fh)
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli", "train",
         "--config", cfg_path],
        env=_worker_env(None), cwd=_repo_root(),
        capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"pipeline fixture base train failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
        )
    return {"cfg_path": cfg_path, "ckpt": ckpt, "delta_dir": delta_dir}


def run_pipeline_matrix(
    workdir: str,
    points: Optional[Sequence[str]] = None,
    budget_s: Optional[float] = None,
) -> dict:
    """The freshness-conductor crash matrix: for every ``pipeline.*``
    seam, a ``cli pipeline`` daemon armed to hard-kill at that seam must
    (1) die WITH the injection exit code (at the seam, not elsewhere),
    (2) leave the warm-start base checkpoint BYTE-IDENTICAL,
    (3) leave the registry free of partial versions and ``.tmp-`` debris,
    and (4) publish a lineage-linked version on the unarmed rerun over
    the exact same directories — the restart story a supervisor relies
    on. The ``pipeline.escalate`` row arms escalation-after-1-cycle so
    the seam actually fires (and its rerun proves the FULL-retrain cycle
    also leaves the original base untouched: escalations re-base into
    new generations under the daemon workdir, never in place).

    Budget-aware like :func:`run_matrix`: points beyond ``budget_s`` are
    reported ``skipped``, never silently dropped.
    """
    from photon_ml_tpu import faults

    # the pipeline seams register at import of the conductor package
    import photon_ml_tpu.pipeline  # noqa: F401

    known = list(PIPELINE_POINTS)
    points = list(points) if points is not None else known
    unknown = sorted(set(points) - set(known))
    if unknown:
        raise ValueError(
            f"not pipeline fault points: {unknown} (known: {known})"
        )
    t0 = time.monotonic()
    report: dict = {
        "workdir": workdir,
        "points": points,
        "results": {},
        "skipped": [],
        "ok": True,
    }
    fix = _pipeline_fixture(workdir)
    base_before = _tree_digest(fix["ckpt"])
    report["base_digest"] = base_before

    for point in points:
        if budget_s is not None and time.monotonic() - t0 > budget_s:
            report["skipped"] = [
                p for p in points if p not in report["results"]
            ]
            break
        entry: dict = {"point": point}
        problems: list = []
        sub = os.path.join(workdir, point.replace(".", "_"))
        reg = os.path.join(sub, "registry")
        cmd = [
            sys.executable, "-m", "photon_ml_tpu.cli", "pipeline",
            "--config", fix["cfg_path"],
            "--base", fix["ckpt"],
            "--delta-dir", fix["delta_dir"],
            "--registry-dir", reg,
            "--workdir", os.path.join(sub, "work"),
            "--cycles", "1",
            "--interval-s", "0.1",
        ]
        if point == "pipeline.escalate":
            cmd += ["--escalate-after-cycles", "1"]
        armed = subprocess.run(
            cmd, env=_worker_env(exit_plan(point)), cwd=_repo_root(),
            capture_output=True, text=True, timeout=600,
        )
        entry["armed_rc"] = armed.returncode
        if armed.returncode != faults.DEFAULT_EXIT_CODE:
            problems.append(
                f"armed daemon exited {armed.returncode}, expected "
                f"{faults.DEFAULT_EXIT_CODE} (did the seam fire?) "
                f"{armed.stderr[-500:]}"
            )
        if _tree_digest(fix["ckpt"]) != base_before:
            problems.append(
                "hard kill mutated the warm-start base checkpoint"
            )
        debris = sorted(os.listdir(reg)) if os.path.isdir(reg) else []
        entry["registry_after_kill"] = debris
        if any(n.startswith("v-") for n in debris):
            problems.append(
                f"kill mid-cycle left published version(s): {debris}"
            )
        if any(n.startswith(".tmp-") for n in debris):
            problems.append(
                f"kill left .tmp- assembly debris: {debris}"
            )
        # unarmed rerun over the SAME directories: the daemon re-seeds
        # its digest cursor, re-runs the cycle, and publishes
        resumed = subprocess.run(
            cmd, env=_worker_env(None), cwd=_repo_root(),
            capture_output=True, text=True, timeout=600,
        )
        entry["resume_rc"] = resumed.returncode
        if resumed.returncode != 0:
            problems.append(
                f"unarmed rerun failed (rc={resumed.returncode}): "
                f"{resumed.stdout[-500:]} {resumed.stderr[-500:]}"
            )
        else:
            try:
                summary = json.loads(
                    resumed.stdout.strip().splitlines()[-1]
                )
            except (ValueError, IndexError):
                summary = {}
            entry["published_versions"] = summary.get("published_versions")
            entry["staleness_p99_s"] = summary.get(
                "event_to_served_staleness_p99_s"
            )
            if not summary.get("published_versions"):
                problems.append("unarmed rerun published nothing")
            versions = sorted(
                n for n in os.listdir(reg) if n.startswith("v-")
            ) if os.path.isdir(reg) else []
            entry["registry_after_resume"] = versions
            if not versions:
                problems.append(
                    "no registry version after the unarmed rerun"
                )
        if _tree_digest(fix["ckpt"]) != base_before:
            problems.append("unarmed rerun mutated the base checkpoint")
        if problems:
            entry["error"] = "; ".join(problems)
            report["ok"] = False
        entry["passed"] = not problems
        report["results"][point] = entry
    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    return report


def _quality_worker_main(directory: str, mode: str) -> int:
    """Publish ONE version through the champion/challenger gate (runs in
    a subprocess so the armed variant can hard-kill at the seam).

    Modes: ``champion`` publishes a healthy first version (no champion
    yet — gate passes with decision no_champion); ``challenger-bad``
    submits quality stats whose AUC sits below the champion's bootstrap
    CI (must quarantine); ``challenger-good`` submits stats inside the
    CI (must publish)."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.game.models import FixedEffectModel, GameModel
    from photon_ml_tpu.quality import QualityGateRefused, QualityStats
    from photon_ml_tpu.serving.registry import publish_version

    model = GameModel(
        task="logistic",
        models={
            "fixed": FixedEffectModel(
                coefficients=jnp.asarray(
                    np.linspace(-0.5, 0.5, DIM), jnp.float32
                ),
                shard_name="global",
            )
        },
    )
    index_maps = {"global": [f"f{i}" for i in range(DIM)]}
    stats = {
        "champion": QualityStats(
            auc=0.80, auc_ci_low=0.75, auc_ci_high=0.85,
            rows=200, bootstrap_samples=8,
        ),
        "challenger-bad": QualityStats(
            auc=0.60, auc_ci_low=0.55, auc_ci_high=0.65,
            rows=200, bootstrap_samples=8,
        ),
        "challenger-good": QualityStats(
            auc=0.82, auc_ci_low=0.77, auc_ci_high=0.87,
            rows=200, bootstrap_samples=8,
        ),
    }[mode]
    try:
        path = publish_version(
            os.path.join(directory, "registry"),
            model,
            index_maps,
            quality=stats.to_json(),
            lineage={"base_kind": "chaos", "mode": mode},
        )
        print(json.dumps({"published": os.path.basename(path)}))
    except QualityGateRefused as exc:
        print(json.dumps({
            "quarantined": os.path.basename(exc.quarantine_path or ""),
            "decision": exc.decision.to_json(),
        }))
    return 0


def run_quality_matrix(workdir: str) -> dict:
    """The publish-gate crash row (ISSUE 20): a publisher hard-killed
    MID-GATE-EVALUATION (``quality.publish_gate`` fires before any
    registry write) must leave the registry with (1) no partial or
    ``.tmp-`` version, (2) no WRONGLY-quarantined version, and (3) the
    champion byte-identical. The unarmed rerun must then make the
    CORRECT decision over the same registry: the regressed challenger
    quarantines (champion still serving), the healthy challenger
    publishes."""
    from photon_ml_tpu import faults

    import photon_ml_tpu.quality  # noqa: F401 — registers the seam

    point = "quality.publish_gate"
    t0 = time.monotonic()
    report: dict = {
        "workdir": workdir,
        "points": [point],
        "results": {},
        "skipped": [],
        "ok": True,
    }
    entry: dict = {"point": point}
    problems: list = []
    os.makedirs(workdir, exist_ok=True)
    reg = os.path.join(workdir, "registry")

    def worker(mode, plan=None):
        return subprocess.run(
            [sys.executable, "-m", "tools.chaos", "--worker-quality",
             "--dir", workdir, "--mode", mode],
            env=_worker_env(plan), cwd=_repo_root(),
            capture_output=True, text=True, timeout=600,
        )

    def last_json(proc):
        try:
            return json.loads(proc.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return {}

    # 1. the champion lands (first version: gate passes, no champion yet)
    champ = worker("champion")
    champ_name = last_json(champ).get("published")
    if champ.returncode != 0 or not champ_name:
        problems.append(
            f"champion publish failed (rc={champ.returncode}): "
            f"{champ.stderr[-500:]}"
        )
    champion_digest = _tree_digest(reg)
    champ_dir = os.path.join(reg, champ_name or "")
    listing_before = sorted(os.listdir(reg)) if os.path.isdir(reg) else []

    # 2. hard kill mid-gate-evaluation on a REGRESSED challenger: the
    # seam fires before any write, so the kill must be invisible
    armed = worker("challenger-bad", plan=exit_plan(point))
    entry["armed_rc"] = armed.returncode
    if armed.returncode != faults.DEFAULT_EXIT_CODE:
        problems.append(
            f"armed publisher exited {armed.returncode}, expected "
            f"{faults.DEFAULT_EXIT_CODE} (did the seam fire?) "
            f"{armed.stderr[-500:]}"
        )
    listing = sorted(os.listdir(reg)) if os.path.isdir(reg) else []
    entry["registry_after_kill"] = listing
    if any(n.startswith(".tmp-") for n in listing):
        problems.append(f"kill left .tmp- assembly debris: {listing}")
    if any(n.startswith("quarantined-") for n in listing):
        problems.append(
            f"kill mid-gate left a wrongly-quarantined version: {listing}"
        )
    if listing != listing_before:
        problems.append(
            f"kill changed the registry: {listing_before} -> {listing}"
        )
    if _tree_digest(reg) != champion_digest:
        problems.append("hard kill mutated the champion version")

    # 3. unarmed rerun of the regressed challenger: quarantines, and the
    # champion keeps serving
    rerun = worker("challenger-bad")
    out = last_json(rerun)
    entry["rerun_rc"] = rerun.returncode
    entry["quarantined"] = out.get("quarantined")
    if rerun.returncode != 0 or not out.get("quarantined"):
        problems.append(
            f"unarmed regressed challenger did not quarantine cleanly "
            f"(rc={rerun.returncode}, out={out}) {rerun.stderr[-500:]}"
        )
    listing = sorted(os.listdir(reg)) if os.path.isdir(reg) else []
    if not any(n.startswith("quarantined-") for n in listing):
        problems.append(f"no quarantine directory after rerun: {listing}")

    # 4. a healthy challenger publishes over the same registry
    good = worker("challenger-good")
    out = last_json(good)
    entry["published"] = out.get("published")
    if good.returncode != 0 or not out.get("published"):
        problems.append(
            f"healthy challenger failed to publish "
            f"(rc={good.returncode}, out={out}) {good.stderr[-500:]}"
        )
    if champ_name and not os.path.isdir(champ_dir):
        problems.append(
            f"champion {champ_name} vanished during the matrix"
        )

    if problems:
        entry["error"] = "; ".join(problems)
        report["ok"] = False
    entry["passed"] = not problems
    report["results"][point] = entry
    report["elapsed_s"] = round(time.monotonic() - t0, 3)
    return report


# ---------------------------------------------------------------------------
# the worker fit (runs in the subprocess)
# ---------------------------------------------------------------------------


def _worker_main(directory: str) -> int:
    import numpy as np

    os.makedirs(directory, exist_ok=True)
    import jax.numpy as jnp

    from photon_ml_tpu import faults
    from photon_ml_tpu.game.checkpoint import (
        CheckpointSpec,
        StreamingCheckpointManager,
    )
    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )

    faults.warn_if_armed()
    rng = np.random.default_rng(DATA_SEED)
    X = rng.normal(size=(N_ENTITIES, N_ROWS, DIM))
    W = rng.normal(size=(N_ENTITIES, DIM))
    z = np.einsum("erk,ek->er", X, W)
    y = (rng.random((N_ENTITIES, N_ROWS)) < 1 / (1 + np.exp(-z))).astype(
        float
    )
    per = N_ENTITIES // N_CHUNKS

    def chunk(lo, hi):
        return DenseBatch(
            x=X[lo:hi].astype(np.float32),
            labels=y[lo:hi].astype(np.float32),
            offsets=np.zeros((hi - lo, N_ROWS), np.float32),
            weights=np.ones((hi - lo, N_ROWS), np.float32),
        )

    chunks = [(i * per, chunk(i * per, (i + 1) * per))
              for i in range(N_CHUNKS)]
    cfg = OptimizerConfig(
        max_iterations=60,
        tolerance=1e-9,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=0.3,
    )
    mgr = StreamingCheckpointManager(
        CheckpointSpec(directory=os.path.join(directory, "ckpt"), every=1)
    )
    state = mgr.restore()  # newest VALID; falls back past crash debris
    table = ShardedCoefficientTable(N_ENTITIES, DIM)
    start_chunk = 0
    if state is not None:
        table.write_chunk(0, jnp.asarray(state.coefficients))
        start_chunk = state.next_chunk
    trainer = StreamingRandomEffectTrainer("logistic", cfg, prefetch=False)
    trainer.train(table, chunks, checkpointer=mgr, start_chunk=start_chunk)
    final = os.path.join(directory, "final.npy")
    np.save(final, table.to_numpy())
    print(json.dumps({
        "final": final,
        "resumed": state is not None,
        "start_chunk": start_chunk,
    }))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.chaos", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--worker", action="store_true",
                        help="run ONE worker fit (internal)")
    parser.add_argument("--dir", help="worker fit directory (--worker)")
    parser.add_argument("--workdir", help="matrix working directory")
    parser.add_argument("--fleet", action="store_true",
                        help="run the DISTRIBUTED matrix (2-process gloo "
                        "fleets, one member hard-killed per seam) instead "
                        "of the single-process write-path matrix")
    parser.add_argument("--serving-fleet", action="store_true",
                        help="run the SERVING matrix (shard-owning fleet "
                        "seams + the hard-kill-under-traffic row) instead "
                        "of the write-path matrix")
    parser.add_argument("--pipeline", action="store_true",
                        help="run the PIPELINE matrix (the freshness-"
                        "conductor daemon hard-killed at each pipeline.* "
                        "seam) instead of the write-path matrix")
    parser.add_argument("--quality", action="store_true",
                        help="run the QUALITY row (a publisher hard-"
                        "killed mid-gate-evaluation at "
                        "quality.publish_gate must leave no partial or "
                        "wrongly-quarantined version; the unarmed rerun "
                        "quarantines the regressed challenger and "
                        "publishes the healthy one)")
    parser.add_argument("--worker-quality", action="store_true",
                        help="publish ONE gated version (internal)")
    parser.add_argument("--mode", default="champion",
                        help="worker-quality mode: champion | "
                        "challenger-bad | challenger-good")
    parser.add_argument("--points", nargs="*",
                        help="subset of write-path points (default: all)")
    parser.add_argument("--nth", type=int, default=1,
                        help="crash on the nth hit of each point (default 1)")
    parser.add_argument("--budget-s", type=float,
                        help="wall-time budget; leftover points reported "
                        "as skipped")
    parser.add_argument("--json", dest="json_out",
                        help="write the matrix report to this path")
    args = parser.parse_args(argv)
    if args.worker:
        if not args.dir:
            parser.error("--worker requires --dir")
        return _worker_main(args.dir)
    if args.worker_quality:
        if not args.dir:
            parser.error("--worker-quality requires --dir")
        return _quality_worker_main(args.dir, args.mode)
    if not args.workdir:
        parser.error("--workdir is required (or --worker --dir)")
    if args.quality:
        report = run_quality_matrix(args.workdir)
    elif args.pipeline:
        report = run_pipeline_matrix(
            args.workdir, points=args.points, budget_s=args.budget_s,
        )
    elif args.serving_fleet:
        report = run_serving_matrix(
            args.workdir, rows=args.points, budget_s=args.budget_s,
        )
    elif args.fleet:
        report = run_fleet_matrix(
            args.workdir, points=args.points, budget_s=args.budget_s,
        )
    else:
        report = run_matrix(
            args.workdir, points=args.points, budget_s=args.budget_s,
            nth=args.nth,
        )
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    for point, entry in report["results"].items():
        if args.quality:
            status = "ok" if entry.get("passed") else "FAIL"
            print(f"{status:4s} {point}  (armed rc={entry.get('armed_rc')}, "
                  f"quarantined={entry.get('quarantined')}, "
                  f"published={entry.get('published')}, "
                  f"error={entry.get('error')})")
        elif args.pipeline:
            status = "ok" if entry.get("passed") else "FAIL"
            print(f"{status:4s} {point}  (armed rc={entry.get('armed_rc')}, "
                  f"published={entry.get('published_versions')}, "
                  f"error={entry.get('error')})")
        elif args.serving_fleet:
            status = "ok" if entry.get("passed") else "FAIL"
            print(f"{status:4s} {point}  (degraded="
                  f"{entry.get('degraded_scores')}, "
                  f"error={entry.get('error')})")
        elif args.fleet:
            status = "ok" if entry.get("passed") else "FAIL"
            print(f"{status:4s} {point}  (victim rc="
                  f"{entry.get('victim_rc')}, relaunches="
                  f"{entry.get('relaunches')}, loss delta="
                  f"{entry.get('loss_delta')})")
        else:
            status = "ok" if entry.get("exact") else "FAIL"
            print(f"{status:4s} {point}  (armed rc={entry.get('armed_rc')}, "
                  f"resumed from chunk {entry.get('resumed_from_chunk')})")
    for point in report["skipped"]:
        print(f"skip {point}  (budget exhausted)")
    print(f"{'OK' if report['ok'] else 'FAILED'} in "
          f"{report['elapsed_s']:.1f}s")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
