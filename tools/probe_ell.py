"""ELL-layout probe: measure the lane-aligned margins kernel against the
current tiled margins kernel at the bench shape, using the K-repetition
slope method from PERF_NOTES (per-pass device time, tunnel overhead
excluded). Decides whether the full ELL integration is worth it."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import sys
sys.path.insert(0, "/root/repo")

from photon_ml_tpu.ops.tiled import (
    LANE, ROWS_PER_TILE, TiledBatch, _mm2, _split_bf16, _spec_w,
)

# bench shape: 1M x 10K, 20 nnz/row
N, D, NNZ = 1_000_000, 10_000, 20


def _ell_margins_kernel(S2, *refs):
    """Lane-aligned: slot (s2, j) belongs to ROW j of the tile (lane j).
    The gather runs one UNROLLED step per s2 (Mosaic cannot shape-cast
    [S2,128] vectors to flat slots): each step one-hots 128 slots and
    picks w lanes; per-row margins accumulate elementwise in [1, 128] —
    NO row one-hot, no row matvecs, no transposed-broadcast."""
    (vals_ref, hi_ref, lo_ref, w_ref, out_z_ref) = refs
    B = w_ref.shape[0]
    w = w_ref[:]
    whi, wlo = _split_bf16(w)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (LANE, B), 1)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
    ones = jnp.ones((LANE, 1), jnp.bfloat16)
    z = jnp.zeros((1, LANE), jnp.float32)
    for s2 in range(S2):
        hi = hi_ref[0, s2, :]                    # [128] slot block ids
        lo = lo_ref[0, s2, :]
        vals = vals_ref[0, s2, :]
        mask_hi = (hi[:, None] == iota_b).astype(jnp.bfloat16)  # [128, B]
        mask_lo = (lo[:, None] == iota_l).astype(jnp.bfloat16)  # [128,128]
        wrow = _mm2(mask_hi, whi, wlo)           # [128(slots), 128(lanes)]
        e = (wrow * mask_lo) * vals[:, None]
        eh, el = _split_bf16(e)
        g = jax.lax.dot_general(
            eh, ones, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        g = g + jax.lax.dot_general(
            el, ones, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [128, 1]: slot j = row j
        z = z + g.reshape(1, LANE)
    out_z_ref[0, :, :] = z


@functools.lru_cache(maxsize=None)
def _ell_call(T, S2, B):
    kern = functools.partial(_ell_margins_kernel, S2)
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, S2, LANE), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ] * 3 + [_spec_w(B)],
        out_specs=pl.BlockSpec((1, 1, ROWS_PER_TILE), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((T, 1, ROWS_PER_TILE), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )


def main():
    rng = np.random.default_rng(0)
    rows = np.repeat(np.arange(N, dtype=np.int64), NNZ)
    cols = rng.integers(0, D, size=N * NNZ)
    vals = rng.normal(size=N * NNZ)
    y = rng.integers(0, 2, size=N).astype(float)

    tb = TiledBatch.from_coo(values=vals, rows=rows, cols=cols, labels=y,
                             num_features=D)
    T = tb.num_tiles
    B = tb.num_blocks
    S2 = NNZ  # constant nnz/row -> exact ELL occupancy

    # ELL arrays: slot (t, s2, j) = nnz s2 of row t*128+j
    ell_vals = np.zeros((T, S2, LANE), np.float32)
    ell_hi = np.full((T, S2, LANE), B, np.int32)
    ell_lo = np.zeros((T, S2, LANE), np.int32)
    t_idx = (rows // LANE).astype(np.int64)
    j_idx = (rows % LANE).astype(np.int64)
    s_idx = np.tile(np.arange(NNZ, dtype=np.int64), N)
    ell_vals[t_idx, s_idx, j_idx] = vals
    ell_hi[t_idx, s_idx, j_idx] = cols // LANE
    ell_lo[t_idx, s_idx, j_idx] = cols % LANE

    w = jnp.asarray(rng.normal(size=D), jnp.float32)
    w2 = jnp.zeros((B * LANE,), jnp.float32).at[:D].set(w).reshape(B, LANE)
    ev = jnp.asarray(ell_vals)
    eh = jnp.asarray(ell_hi)
    el = jnp.asarray(ell_lo)

    # correctness vs the tiled path
    z_ell = _ell_call(T, S2, B)(ev, eh, el, w2).reshape(-1)[:N]
    z_ref = tb.margins(w)[:N]
    err = float(jnp.max(jnp.abs(z_ell - z_ref)))
    print("max |z_ell - z_tiled| =", err)

    # slope timing: K repetitions inside one jit, with a dependency chain
    # through the weight argument so XLA cannot CSE the repetitions
    def time_slope(fn, w_arg, *rest):
        def rep(k):
            @jax.jit
            def run(ww, *a):
                acc = jnp.float32(0.0)
                for _ in range(k):
                    s = jnp.sum(fn(ww, *a))
                    acc = acc + s
                    ww = ww + s * 1e-30
                return acc
            float(run(w_arg, *rest))  # compile+warm
            t0 = time.perf_counter()
            float(run(w_arg, *rest))
            return time.perf_counter() - t0
        t1, t9 = rep(1), rep(9)
        return (t9 - t1) / 8

    ell_pass = time_slope(
        lambda ww, v, h, lo_: _ell_call(T, S2, B)(v, h, lo_, ww),
        w2, ev, eh, el)
    tiled_pass = time_slope(lambda ww, b: b.margins(ww), w, tb)
    print(f"ELL margins pass:   {ell_pass*1e3:.1f} ms")
    print(f"tiled margins pass: {tiled_pass*1e3:.1f} ms")
    print(f"speedup: {tiled_pass/ell_pass:.3f}x")


if __name__ == "__main__":
    main()
