// Native TrainingExampleAvro block writer — the fixture-generation side of
// the ingestion path (photon_ml_tpu.data.avro.write_training_examples_fast).
//
// The Python writer (data/avro.py write_avro) walks the schema per record
// at ~16K rows/s; generating north-star-scale fixtures (20M rows) needs
// ~100x that. This encoder appends record BLOCKS to a container whose
// header (magic, schema JSON, codec=null, sync) Python already wrote —
// the record wire format mirrors data/avro.py _encode for the
// TrainingExampleAvro shape exactly:
//   uid: union[null,string]      -> branch 0 (null)
//   label: double                -> 8 bytes LE
//   features: array<FeatureAvro> -> count, (name,term,value)*, 0
//   metadataMap: union[null,map] -> branch 1, count, (key,val)*, 0
//   weight/offset: union[null,double] -> branch 0
//
// Reference analog: the reference ships fixtures and converts LibSVM via
// dev-scripts/libsvm_text_to_trainingexample_avro.py; generation-at-scale
// is a bench-infrastructure need unique to this repo.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

thread_local std::string g_enc_error;

inline void put_zigzag(std::string& out, int64_t v) {
  uint64_t u = (static_cast<uint64_t>(v) << 1) ^
               static_cast<uint64_t>(v >> 63);
  while (u >= 0x80) {
    out.push_back(static_cast<char>((u & 0x7F) | 0x80));
    u >>= 7;
  }
  out.push_back(static_cast<char>(u));
}

inline void put_str(std::string& out, const char* p, int64_t n) {
  put_zigzag(out, n);
  out.append(p, static_cast<size_t>(n));
}

inline void put_double(std::string& out, double v) {
  char buf[8];
  std::memcpy(buf, &v, 8);
  out.append(buf, 8);
}

}  // namespace

extern "C" {

const char* avro_encode_last_error() { return g_enc_error.c_str(); }

// Append blocks of TrainingExampleAvro-shaped records to `path` (opened
// append). The record carries n_bags feature arrays between label and
// metadataMap (the multi-shard GameDatum featureShardContainer analog);
// bag b's features for row r are feat_name_id/feat_vals[
// feat_starts[b*(n_rows+1)+r] : feat_starts[b*(n_rows+1)+r+1]] (absolute
// into the flat arrays) with names resolved through (name_bytes,
// name_offs); terms are always "".
// id columns become metadataMap entries: key strings in
// (id_key_bytes, id_key_offs); per-row values resolved from each column's
// vocab via id_codes (laid out [n_ids][n_rows]); per-column vocab c's
// strings live at id_vocab_offs[vocab_base[c] + code .. +1] into
// id_vocab_bytes.
// Returns rows written, or -1 (avro_encode_last_error()).
int64_t avro_write_training_blocks(
    const char* path, int64_t n_rows, const double* labels,
    int32_t n_bags, const int64_t* feat_starts,
    const int32_t* feat_name_id, const double* feat_vals,
    const uint8_t* name_bytes, const int64_t* name_offs, int32_t n_ids,
    const uint8_t* id_key_bytes, const int64_t* id_key_offs,
    const int64_t* id_codes, const uint8_t* id_vocab_bytes,
    const int64_t* id_vocab_offs, const int64_t* id_vocab_counts,
    int64_t block_records, const uint8_t* sync) {
  g_enc_error.clear();
  FILE* f = std::fopen(path, "ab");
  if (!f) {
    g_enc_error = "cannot open for append";
    return -1;
  }
  // per-column base into the flat id_vocab_offs table (counts+1 slots each)
  int64_t vocab_base[64];
  if (n_ids > 64) {
    g_enc_error = "too many id columns";
    std::fclose(f);
    return -1;
  }
  int64_t base = 0;
  for (int32_t c = 0; c < n_ids; ++c) {
    vocab_base[c] = base;
    base += id_vocab_counts[c] + 1;
  }

  std::string block;
  std::string head;
  block.reserve(static_cast<size_t>(block_records) * 192);
  int64_t written = 0;
  int64_t n_in_block = 0;

  auto flush = [&]() -> bool {
    if (n_in_block == 0) return true;
    head.clear();
    put_zigzag(head, n_in_block);
    put_zigzag(head, static_cast<int64_t>(block.size()));
    if (std::fwrite(head.data(), 1, head.size(), f) != head.size() ||
        std::fwrite(block.data(), 1, block.size(), f) != block.size() ||
        std::fwrite(sync, 1, 16, f) != 16) {
      g_enc_error = "write failed";
      return false;
    }
    block.clear();
    n_in_block = 0;
    return true;
  };

  for (int64_t r = 0; r < n_rows; ++r) {
    put_zigzag(block, 0);  // uid: null branch
    put_double(block, labels[r]);
    for (int32_t b = 0; b < n_bags; ++b) {
      const int64_t* bs = feat_starts + static_cast<int64_t>(b) * (n_rows + 1);
      int64_t lo = bs[r], hi = bs[r + 1];
      if (hi > lo) {
        put_zigzag(block, hi - lo);
        for (int64_t k = lo; k < hi; ++k) {
          int64_t nid = feat_name_id[k];
          put_str(block,
                  reinterpret_cast<const char*>(name_bytes) + name_offs[nid],
                  name_offs[nid + 1] - name_offs[nid]);
          put_zigzag(block, 0);  // term ""
          put_double(block, feat_vals[k]);
        }
      }
      put_zigzag(block, 0);  // feature array end
    }
    if (n_ids > 0) {
      put_zigzag(block, 1);  // metadataMap: map branch
      put_zigzag(block, n_ids);
      for (int32_t c = 0; c < n_ids; ++c) {
        put_str(block,
                reinterpret_cast<const char*>(id_key_bytes) + id_key_offs[c],
                id_key_offs[c + 1] - id_key_offs[c]);
        int64_t code = id_codes[static_cast<int64_t>(c) * n_rows + r];
        if (code < 0 || code >= id_vocab_counts[c]) {
          g_enc_error = "id code out of vocab range (row " +
                        std::to_string(r) + ")";
          std::fclose(f);
          return -1;
        }
        const int64_t* offs = id_vocab_offs + vocab_base[c];
        put_str(block,
                reinterpret_cast<const char*>(id_vocab_bytes) + offs[code],
                offs[code + 1] - offs[code]);
      }
      put_zigzag(block, 0);  // map end
    } else {
      put_zigzag(block, 0);  // metadataMap: null branch
    }
    put_zigzag(block, 0);  // weight: null
    put_zigzag(block, 0);  // offset: null
    ++n_in_block;
    ++written;
    if (n_in_block >= block_records && !flush()) {
      std::fclose(f);
      return -1;
    }
  }
  if (!flush()) {
    std::fclose(f);
    return -1;
  }
  std::fclose(f);
  return written;
}

}  // extern "C"
