// Native Avro object-container decoder for TrainingExampleAvro-shaped
// records — the ingestion hot loop behind
// photon_ml_tpu.data.avro.read_game_dataset_from_avro.
//
// The Python side parses the container HEADER (schema JSON, codec, sync
// marker) and compiles the record schema into a compact i32 "program"
// (see photon_ml_tpu/data/avro_native.py). This file interprets that
// program over every record of every block at C speed: varint/zigzag
// decoding, deflate inflation (zlib), feature key formation
// (name '\x01' term — photon-client util/Utils.getFeatureKey), hash
// lookups into the caller's index map (or interning when the map is
// being BUILT), and id-column interning. Two-phase C ABI: parse into a
// heap Result, then copy out into caller-allocated numpy buffers.
//
// Reference analog: AvroDataReader.scala:87-237 runs this loop on Spark
// executors; here it is one host core at ~1e6 rows/s (vs ~1.6e4 for the
// schema-interpreting pure-Python decoder).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

thread_local std::string g_error;

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
      }
      shift += 7;
      if (shift > 63) break;
    }
    fail = true;
    return 0;
  }

  bool skip(int64_t n) {
    if (n < 0 || end - p < n) {
      fail = true;
      return false;
    }
    p += n;
    return true;
  }

  bool read_raw(void* out, int64_t n) {
    if (end - p < n) {
      fail = true;
      return false;
    }
    std::memcpy(out, p, n);
    p += n;
    return true;
  }

  double read_double() {
    double v = 0;
    read_raw(&v, 8);
    return v;
  }

  float read_float() {
    float v = 0;
    read_raw(&v, 4);
    return v;
  }

  // length-prefixed bytes/string; returns view into the buffer
  bool read_bytes(const char** out, int64_t* len) {
    int64_t n = read_long();
    if (fail || n < 0 || end - p < n) {
      fail = true;
      return false;
    }
    *out = reinterpret_cast<const char*>(p);
    *len = n;
    p += n;
    return true;
  }
};

// program opcodes — mirror photon_ml_tpu/data/avro_native.py
enum Op : int32_t {
  OP_SKIP_LONG = 1,    //
  OP_SKIP_FLOAT = 2,   //
  OP_SKIP_DOUBLE = 3,  //
  OP_SKIP_BYTES = 4,   // string/bytes
  OP_SKIP_BOOL = 5,    //
  OP_SKIP_FIXED = 6,   // +n
  OP_SCALAR_D = 7,     // +dest: double -> scalar channel
  OP_SCALAR_F = 8,     // +dest: float
  OP_SCALAR_L = 9,     // +dest: int/long
  OP_SCALAR_B = 10,    // +dest: boolean
  OP_UNION = 11,       // +n, then n branch lengths, then branches
  OP_FEATURE_BAG = 12, // +shard, +item_len, then item program
  OP_FNAME = 13,       //
  OP_FTERM = 14,       //
  OP_FVALUE_D = 15,    //
  OP_FVALUE_F = 16,    //
  OP_ID_FIELD = 17,    // +col: top-level string id column (overwrites)
  OP_ID_MAP = 18,      // string->string map matched against id columns
  OP_ARRAY_SKIP = 19,  // +item_len, then item program
  OP_MAP_SKIP = 20,    // +value_len, then value program (string keys)
};

// scalar channel dests
enum Dest : int32_t { DEST_LABEL = 0, DEST_OFFSET = 1, DEST_WEIGHT = 2 };

inline uint64_t fnv1a(const char* p, int64_t n, uint64_t h) {
  for (int64_t i = 0; i < n; ++i)
    h = (h ^ static_cast<uint8_t>(p[i])) * 1099511628211ULL;
  return h;
}
constexpr uint64_t kFnvSeed = 1469598103934665603ULL;

// Open-addressing string map specialized for the per-feature hot loop:
// the key is (name, optional '\x01' + term) hashed INCREMENTALLY — no
// composed std::string is ever built for a lookup (std::unordered_map
// with per-feature string allocation measured ~180 ns/lookup; this is
// ~3x faster).
struct FastMap {
  std::vector<uint64_t> hashes;  // 0 = empty slot
  std::vector<int64_t> ids;
  std::vector<uint64_t> key_off;
  std::vector<uint32_t> key_len;
  std::string blob;  // all keys concatenated (for collision verify)
  uint64_t mask = 0;
  int64_t count = 0;

  void reserve_for(int64_t n) {
    uint64_t cap = 16;
    while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
    hashes.assign(cap, 0);
    ids.assign(cap, -1);
    key_off.assign(cap, 0);
    key_len.assign(cap, 0);
    mask = cap - 1;
  }

  void grow() {
    FastMap bigger;
    bigger.reserve_for(static_cast<int64_t>(hashes.size()));
    bigger.blob.swap(blob);
    for (size_t s = 0; s < hashes.size(); ++s) {
      if (!hashes[s]) continue;
      uint64_t slot = hashes[s] & bigger.mask;
      while (bigger.hashes[slot]) slot = (slot + 1) & bigger.mask;
      bigger.hashes[slot] = hashes[s];
      bigger.ids[slot] = ids[s];
      bigger.key_off[slot] = key_off[s];
      bigger.key_len[slot] = key_len[s];
    }
    bigger.count = count;
    *this = std::move(bigger);
  }

  bool match(uint64_t slot, const char* a, int64_t an, const char* b,
             int64_t bn) const {
    // stored key == a ++ ('\x01' + b when bn > 0)
    uint64_t total = static_cast<uint64_t>(an) + (bn > 0 ? bn + 1 : 0);
    if (key_len[slot] != total) return false;
    const char* k = blob.data() + key_off[slot];
    if (std::memcmp(k, a, an)) return false;
    if (bn > 0) {
      if (k[an] != '\x01') return false;
      if (std::memcmp(k + an + 1, b, bn)) return false;
    }
    return true;
  }

  static uint64_t hash_parts(const char* a, int64_t an, const char* b,
                             int64_t bn) {
    uint64_t h = fnv1a(a, an, kFnvSeed);
    if (bn > 0) {
      const char sep = '\x01';
      h = fnv1a(&sep, 1, h);
      h = fnv1a(b, bn, h);
    }
    return h ? h : 1;  // 0 marks empty slots
  }

  // lookup only; -1 when absent
  int64_t find(const char* a, int64_t an, const char* b, int64_t bn) const {
    uint64_t h = hash_parts(a, an, b, bn);
    uint64_t slot = h & mask;
    while (hashes[slot]) {
      if (hashes[slot] == h && match(slot, a, an, b, bn)) return ids[slot];
      slot = (slot + 1) & mask;
    }
    return -1;
  }

  // insert-or-get with a caller-chosen id for fresh keys
  int64_t intern(const char* a, int64_t an, const char* b, int64_t bn) {
    if (static_cast<uint64_t>(count) * 2 >= hashes.size()) grow();
    uint64_t h = hash_parts(a, an, b, bn);
    uint64_t slot = h & mask;
    while (hashes[slot]) {
      if (hashes[slot] == h && match(slot, a, an, b, bn)) return ids[slot];
      slot = (slot + 1) & mask;
    }
    hashes[slot] = h;
    ids[slot] = count++;
    key_off[slot] = blob.size();
    blob.append(a, an);
    if (bn > 0) {
      blob.push_back('\x01');
      blob.append(b, bn);
    }
    key_len[slot] = static_cast<uint32_t>(blob.size() - key_off[slot]);
    return ids[slot];
  }

  // seed one key with an explicit id (lookup-table construction)
  void put(const char* k, int64_t n, int64_t id) {
    if (static_cast<uint64_t>(count) * 2 >= hashes.size()) grow();
    uint64_t h = hash_parts(k, n, nullptr, 0);
    uint64_t slot = h & mask;
    while (hashes[slot]) slot = (slot + 1) & mask;
    hashes[slot] = h;
    ids[slot] = id;
    key_off[slot] = blob.size();
    blob.append(k, n);
    key_len[slot] = static_cast<uint32_t>(n);
    ++count;
  }

  // export interned keys in id order (intern ids are dense 0..count-1)
  void export_keys(std::vector<std::string>& out) const {
    out.assign(count, std::string());
    for (size_t s = 0; s < hashes.size(); ++s) {
      if (hashes[s])
        out[ids[s]] = blob.substr(key_off[s], key_len[s]);
    }
  }
};

struct Shard {
  // lookup mode: `lookup` points at a SHARED read-only key->dense-id map
  // (never copied per worker); intern mode: `keys` interns on the fly
  FastMap keys;
  const FastMap* lookup = nullptr;
  bool interning = false;
  std::vector<double> vals;
  std::vector<int64_t> rows;
  std::vector<int64_t> cols;
};

struct IdCol {
  // FastMap interner (string views, no per-row std::string allocation —
  // the old unordered_map<std::string> interner cost ~150 ns/row)
  FastMap vocab;
  std::vector<int64_t> codes;  // per row
};

struct Result {
  std::vector<double> labels, offsets, weights;
  std::vector<uint8_t> label_seen;  // genuine NaN labels stay distinguishable
  std::vector<Shard> shards;
  std::vector<IdCol> id_cols;
  std::vector<std::string> id_names;
  int64_t rows = 0;
};

struct RecState {
  // feature name/term as VIEWS into the (stable-for-the-block) payload
  const char* fname = nullptr;
  int64_t fname_len = 0;
  const char* fterm = nullptr;
  int64_t fterm_len = 0;
  double fvalue = 0;
  bool has_name = false, has_value = false;
  std::vector<int32_t> id_mark;  // 0 unset, 1 map-set, 2 field-set
};

bool run_program(Cursor& c, const int32_t* prog, int64_t len, Result& res,
                 RecState& st, int64_t row);

bool run_feature_item(Cursor& c, const int32_t* prog, int64_t len,
                      Result& res, RecState& st, Shard& sh, int64_t row) {
  st.fname_len = st.fterm_len = 0;
  st.has_name = st.has_value = false;
  if (!run_program(c, prog, len, res, st, row)) return false;
  if (!st.has_name || !st.has_value) return true;  // malformed item: drop
  int64_t id;
  if (sh.interning) {
    id = sh.keys.intern(st.fname, st.fname_len, st.fterm, st.fterm_len);
  } else {
    id = sh.lookup->find(st.fname, st.fname_len, st.fterm, st.fterm_len);
    if (id < 0) return true;  // unknown feature: dropped
  }
  sh.vals.push_back(st.fvalue);
  sh.rows.push_back(row);
  sh.cols.push_back(id);
  return true;
}

bool run_program(Cursor& c, const int32_t* prog, int64_t len, Result& res,
                 RecState& st, int64_t row) {
  int64_t i = 0;
  while (i < len && !c.fail) {
    int32_t op = prog[i++];
    switch (op) {
      case OP_SKIP_LONG:
        c.read_long();
        break;
      case OP_SKIP_FLOAT:
        c.skip(4);
        break;
      case OP_SKIP_DOUBLE:
        c.skip(8);
        break;
      case OP_SKIP_BYTES: {
        int64_t n = c.read_long();
        c.skip(n);
        break;
      }
      case OP_SKIP_BOOL:
        c.skip(1);
        break;
      case OP_SKIP_FIXED:
        c.skip(prog[i++]);
        break;
      case OP_SCALAR_D:
      case OP_SCALAR_F:
      case OP_SCALAR_L:
      case OP_SCALAR_B: {
        int32_t dest = prog[i++];
        double v;
        if (op == OP_SCALAR_D) v = c.read_double();
        else if (op == OP_SCALAR_F) v = c.read_float();
        else if (op == OP_SCALAR_L) v = static_cast<double>(c.read_long());
        else {
          uint8_t b = 0;
          c.read_raw(&b, 1);
          v = b ? 1.0 : 0.0;
        }
        if (dest == DEST_LABEL) {
          res.labels[row] = v;
          res.label_seen[row] = 1;
        } else if (dest == DEST_OFFSET) res.offsets[row] = v;
        else if (dest == DEST_WEIGHT) res.weights[row] = v;
        break;
      }
      case OP_UNION: {
        // layout: n, len_0..len_{n-1}, branch_0 ... branch_{n-1}
        int32_t n = prog[i++];
        int64_t branch = c.read_long();
        if (c.fail || branch < 0 || branch >= n) {
          g_error = "union branch out of range";
          c.fail = true;
          return false;
        }
        int64_t off = i + n;
        for (int32_t b = 0; b < branch; ++b) off += prog[i + b];
        if (!run_program(c, prog + off, prog[i + branch], res, st, row))
          return false;
        int64_t total = 0;
        for (int32_t b = 0; b < n; ++b) total += prog[i + b];
        i += n + total;
        break;
      }
      case OP_FEATURE_BAG: {
        int32_t shard = prog[i++];
        int32_t item_len = prog[i++];
        const int32_t* item = prog + i;
        i += item_len;
        Shard& sh = res.shards[shard];
        // canonical FeatureAvro item (name, term, value — no unions)
        // gets a dispatch-free loop; ~30% of decode time at 15 nnz/row
        const bool simple = item_len == 3 && item[0] == OP_FNAME &&
                            item[1] == OP_FTERM && item[2] == OP_FVALUE_D;
        for (;;) {
          int64_t n = c.read_long();
          if (c.fail) return false;
          if (n == 0) break;
          if (n < 0) {
            n = -n;
            c.read_long();  // block byte size
          }
          if (simple) {
            for (int64_t k = 0; k < n; ++k) {
              const char *nm, *tm;
              int64_t nl, tl;
              if (!c.read_bytes(&nm, &nl)) return false;
              if (!c.read_bytes(&tm, &tl)) return false;
              double v = c.read_double();
              if (c.fail) return false;
              int64_t id = sh.interning ? sh.keys.intern(nm, nl, tm, tl)
                                        : sh.lookup->find(nm, nl, tm, tl);
              if (id < 0) continue;  // unknown feature: dropped
              sh.vals.push_back(v);
              sh.rows.push_back(row);
              sh.cols.push_back(id);
            }
            continue;
          }
          for (int64_t k = 0; k < n; ++k) {
            if (!run_feature_item(c, item, item_len, res, st, sh, row))
              return false;
            if (c.fail) return false;
          }
        }
        break;
      }
      case OP_FNAME:
      case OP_FTERM: {
        const char* s;
        int64_t n;
        if (!c.read_bytes(&s, &n)) return false;
        if (op == OP_FNAME) {
          st.fname = s;
          st.fname_len = n;
          st.has_name = true;
        } else {
          st.fterm = s;
          st.fterm_len = n;
        }
        break;
      }
      case OP_FVALUE_D:
        st.fvalue = c.read_double();
        st.has_value = true;
        break;
      case OP_FVALUE_F:
        st.fvalue = c.read_float();
        st.has_value = true;
        break;
      case OP_ID_FIELD: {
        int32_t col = prog[i++];
        const char* s;
        int64_t n;
        if (!c.read_bytes(&s, &n)) return false;
        IdCol& ic = res.id_cols[col];
        ic.codes[row] = ic.vocab.intern(s, n, nullptr, 0);
        st.id_mark[col] = 2;
        break;
      }
      case OP_ID_MAP: {
        for (;;) {
          int64_t n = c.read_long();
          if (c.fail) return false;
          if (n == 0) break;
          if (n < 0) {
            n = -n;
            c.read_long();
          }
          for (int64_t k = 0; k < n; ++k) {
            const char* ks;
            int64_t kn;
            const char* vs;
            int64_t vn;
            if (!c.read_bytes(&ks, &kn)) return false;
            if (!c.read_bytes(&vs, &vn)) return false;
            for (size_t ci = 0; ci < res.id_names.size(); ++ci) {
              const std::string& want = res.id_names[ci];
              if (st.id_mark[ci] == 0 &&
                  want.size() == static_cast<size_t>(kn) &&
                  std::memcmp(want.data(), ks, kn) == 0) {
                IdCol& ic = res.id_cols[ci];
                ic.codes[row] = ic.vocab.intern(vs, vn, nullptr, 0);
                st.id_mark[ci] = 1;
              }
            }
          }
        }
        break;
      }
      case OP_ARRAY_SKIP: {
        int32_t item_len = prog[i++];
        const int32_t* item = prog + i;
        i += item_len;
        for (;;) {
          int64_t n = c.read_long();
          if (c.fail) return false;
          if (n == 0) break;
          if (n < 0) {
            n = -n;
            c.read_long();
          }
          for (int64_t k = 0; k < n; ++k)
            if (!run_program(c, item, item_len, res, st, row)) return false;
        }
        break;
      }
      case OP_MAP_SKIP: {
        int32_t val_len = prog[i++];
        const int32_t* val = prog + i;
        i += val_len;
        for (;;) {
          int64_t n = c.read_long();
          if (c.fail) return false;
          if (n == 0) break;
          if (n < 0) {
            n = -n;
            c.read_long();
          }
          for (int64_t k = 0; k < n; ++k) {
            int64_t kn = c.read_long();
            if (!c.skip(kn)) return false;
            if (!run_program(c, val, val_len, res, st, row)) return false;
          }
        }
        break;
      }
      default:
        g_error = "bad opcode " + std::to_string(op);
        c.fail = true;
        return false;
    }
  }
  return !c.fail;
}

struct BlockSpan {
  const uint8_t* payload;
  int64_t size;
  int64_t n_rec;
};

// decode blocks [lo, hi) into res (rows LOCAL to res); false on error with
// err set. Each caller owns its own res/scratch -> thread-safe.
bool decode_blocks(const std::vector<BlockSpan>& blocks, size_t lo, size_t hi,
                   int32_t codec_deflate, const int32_t* prog,
                   int64_t prog_len, Result& res, std::string& err) {
  std::vector<uint8_t> inflated;
  RecState st;
  st.id_mark.assign(res.id_cols.size(), 0);
  int64_t total_rows = 0;
  for (size_t bi = lo; bi < hi; ++bi) total_rows += blocks[bi].n_rec;
  res.labels.reserve(res.labels.size() + total_rows);
  res.offsets.reserve(res.offsets.size() + total_rows);
  res.weights.reserve(res.weights.size() + total_rows);
  res.label_seen.reserve(res.label_seen.size() + total_rows);
  for (auto& ic : res.id_cols) ic.codes.reserve(ic.codes.size() + total_rows);
  bool reserved_nnz = false;
  for (size_t bi = lo; bi < hi; ++bi) {
    const uint8_t* payload = blocks[bi].payload;
    int64_t payload_len = blocks[bi].size;
    if (codec_deflate) {
      // raw deflate; grow-only scratch (a clear+resize would memset
      // multi-MB per block in the hot loop just to be overwritten)
      size_t want = static_cast<size_t>(payload_len) * 4 + 1024;
      if (inflated.size() < want) inflated.resize(want);
      z_stream zs{};
      if (inflateInit2(&zs, -15) != Z_OK) {
        err = "zlib init failed";
        return false;
      }
      zs.next_in = const_cast<uint8_t*>(payload);
      zs.avail_in = static_cast<uInt>(payload_len);
      size_t out_pos = 0;
      int zr;
      do {
        if (out_pos == inflated.size()) inflated.resize(inflated.size() * 2);
        zs.next_out = inflated.data() + out_pos;
        zs.avail_out = static_cast<uInt>(inflated.size() - out_pos);
        zr = inflate(&zs, Z_NO_FLUSH);
        out_pos = inflated.size() - zs.avail_out;
      } while (zr == Z_OK);
      inflateEnd(&zs);
      if (zr != Z_STREAM_END) {
        err = "deflate block corrupt";
        return false;
      }
      payload = inflated.data();
      payload_len = static_cast<int64_t>(out_pos);
    }
    Cursor c{payload, payload + payload_len};
    for (int64_t r = 0; r < blocks[bi].n_rec; ++r) {
      int64_t row = res.rows++;
      res.labels.push_back(0.0);
      res.label_seen.push_back(0);
      res.offsets.push_back(0.0);
      res.weights.push_back(1.0);
      for (auto& ic : res.id_cols) ic.codes.push_back(-1);
      std::fill(st.id_mark.begin(), st.id_mark.end(), 0);
      if (!run_program(c, prog, prog_len, res, st, row)) {
        err = g_error.empty() ? "corrupt record" : g_error;
        return false;
      }
    }
    if (!reserved_nnz && res.rows > 0) {
      // size the COO arrays from the first block's observed density —
      // one reservation instead of log2(total) grow/copy cycles
      reserved_nnz = true;
      for (auto& sh : res.shards) {
        size_t per_row = sh.vals.size() / static_cast<size_t>(res.rows) + 1;
        size_t want = per_row * static_cast<size_t>(total_rows) + 64;
        sh.vals.reserve(want);
        sh.rows.reserve(want);
        sh.cols.reserve(want);
      }
    }
  }
  return true;
}

// merge worker results into dst (dst already holds worker 0's data when
// dst == &workers[0]; callers pass workers[1..] with dst = workers[0]).
// Interned ids (intern-mode shards, id vocabs) are remapped through dst's
// maps; rows are re-based by dst's current row count.
void merge_result(Result& dst, Result& src) {
  int64_t row_base = dst.rows;
  dst.rows += src.rows;
  auto append = [](auto& a, auto& b) {
    a.insert(a.end(), b.begin(), b.end());
  };
  append(dst.labels, src.labels);
  append(dst.offsets, src.offsets);
  append(dst.weights, src.weights);
  append(dst.label_seen, src.label_seen);
  for (size_t s = 0; s < dst.shards.size(); ++s) {
    Shard& d = dst.shards[s];
    Shard& x = src.shards[s];
    for (int64_t& r : x.rows) r += row_base;
    if (d.interning && x.keys.count) {
      // remap src's locally-interned feature ids through dst's map
      std::vector<std::string> keys;
      x.keys.export_keys(keys);
      std::vector<int64_t> remap(keys.size());
      for (size_t k = 0; k < keys.size(); ++k)
        remap[k] = d.keys.intern(keys[k].data(),
                                 static_cast<int64_t>(keys[k].size()),
                                 nullptr, 0);
      for (int64_t& ccol : x.cols) ccol = remap[ccol];
    }
    append(d.vals, x.vals);
    append(d.rows, x.rows);
    append(d.cols, x.cols);
  }
  for (size_t ci = 0; ci < dst.id_cols.size(); ++ci) {
    IdCol& d = dst.id_cols[ci];
    IdCol& x = src.id_cols[ci];
    if (x.vocab.count) {
      std::vector<std::string> keys;
      x.vocab.export_keys(keys);
      std::vector<int64_t> remap(keys.size());
      for (size_t k = 0; k < keys.size(); ++k)
        remap[k] = d.vocab.intern(keys[k].data(),
                                  static_cast<int64_t>(keys[k].size()),
                                  nullptr, 0);
      for (int64_t& code : x.codes)
        if (code >= 0) code = remap[code];
    }
    append(d.codes, x.codes);
  }
}

}  // namespace

extern "C" {

// parse blocks; returns heap Result* or nullptr (avro_last_error()).
//
// data/len: the file bytes; block_start: offset of the first block;
// sync: 16-byte marker; codec_deflate: 1 if blocks are raw-deflate.
// prog/prog_len: record program. feat tables (per shard, lookup mode):
// concatenated key bytes + (n+1) offsets + dense ids; n_keys < 0 marks
// INTERN mode for that shard. id_names: concatenated + offsets.
// n_threads: parallel block decode workers (<=0 = hardware concurrency);
// Avro blocks are sync-delimited and independent, the executor-parallel
// decode of AvroDataReader.scala:87-237 folded into one process.
void* avro_parse(const uint8_t* data, int64_t len, int64_t block_start,
                 const uint8_t* sync, int32_t codec_deflate,
                 const int32_t* prog, int64_t prog_len, int32_t n_shards,
                 const uint8_t* feat_bytes, const int64_t* feat_offs,
                 const int64_t* feat_ids, const int64_t* shard_key_counts,
                 int32_t n_id_cols, const uint8_t* id_name_bytes,
                 const int64_t* id_name_offs, int32_t n_threads) {
  g_error.clear();
  auto res = new Result();
  res->shards.resize(n_shards);
  int64_t off_base = 0;  // index into feat_offs (each shard has nk+1 slots)
  int64_t id_base = 0;   // index into feat_ids (nk per shard)
  for (int32_t s = 0; s < n_shards; ++s) {
    int64_t nk = shard_key_counts[s];
    Shard& sh = res->shards[s];
    if (nk < 0) {
      sh.interning = true;
      sh.keys.reserve_for(1024);
      continue;
    }
    sh.keys.reserve_for(nk > 0 ? nk : 1);
    for (int64_t k = 0; k < nk; ++k) {
      const char* p =
          reinterpret_cast<const char*>(feat_bytes) + feat_offs[off_base + k];
      int64_t n = feat_offs[off_base + k + 1] - feat_offs[off_base + k];
      sh.keys.put(p, n, feat_ids[id_base + k]);
    }
    sh.lookup = &sh.keys;
    off_base += nk + 1;
    id_base += nk;
  }
  res->id_cols.resize(n_id_cols);
  for (int32_t ci = 0; ci < n_id_cols; ++ci) {
    res->id_cols[ci].vocab.reserve_for(1024);
    const char* p =
        reinterpret_cast<const char*>(id_name_bytes) + id_name_offs[ci];
    int64_t n = id_name_offs[ci + 1] - id_name_offs[ci];
    res->id_names.emplace_back(p, n);
  }

  // serial block scan: offsets + record counts + sync verification
  std::vector<BlockSpan> blocks;
  Cursor file{data + block_start, data + len};
  while (file.p < file.end) {
    int64_t n_rec = file.read_long();
    int64_t size = file.read_long();
    if (file.fail || size < 0 || file.end - file.p < size) {
      g_error = "corrupt block header";
      delete res;
      return nullptr;
    }
    blocks.push_back(BlockSpan{file.p, size, n_rec});
    file.p += size;
    uint8_t got_sync[16];
    if (!file.read_raw(got_sync, 16) || std::memcmp(got_sync, sync, 16)) {
      g_error = "sync marker mismatch (corrupt block)";
      delete res;
      return nullptr;
    }
  }

  int64_t want_threads =
      n_threads > 0
          ? n_threads
          : static_cast<int64_t>(std::thread::hardware_concurrency());
  size_t T = static_cast<size_t>(
      std::max<int64_t>(1, std::min<int64_t>(
                               want_threads,
                               static_cast<int64_t>(blocks.size()))));
  std::string err;
  if (T <= 1) {
    if (!decode_blocks(blocks, 0, blocks.size(), codec_deflate, prog,
                       prog_len, *res, err)) {
      g_error = err;
      delete res;
      return nullptr;
    }
    return res;
  }

  // parallel decode: contiguous block spans into per-worker Results that
  // carry a COPY of the lookup maps (read-only in the hot loop) and their
  // own interners, merged (with id remap) afterwards
  std::vector<Result> workers(T);
  std::vector<std::string> errs(T);
  std::vector<std::thread> pool;
  size_t per = (blocks.size() + T - 1) / T;
  for (size_t t = 0; t < T; ++t) {
    Result& w = workers[t];
    w.shards.resize(n_shards);
    for (int32_t s = 0; s < n_shards; ++s) {
      w.shards[s].interning = res->shards[s].interning;
      if (res->shards[s].interning)
        w.shards[s].keys.reserve_for(1024);
      else
        // POINT at the parent's map — read-only in the hot loop; a full
        // per-worker copy of a production-size feature map would cost
        // O(map) RAM x threads
        w.shards[s].lookup = &res->shards[s].keys;
    }
    w.id_cols.resize(n_id_cols);
    for (int32_t ci = 0; ci < n_id_cols; ++ci)
      w.id_cols[ci].vocab.reserve_for(1024);
    w.id_names = res->id_names;
    size_t lo = t * per;
    size_t hi = std::min(blocks.size(), lo + per);
    pool.emplace_back([&, t, lo, hi]() {
      decode_blocks(blocks, lo, hi, codec_deflate, prog, prog_len,
                    workers[t], errs[t]);
    });
  }
  for (auto& th : pool) th.join();
  for (size_t t = 0; t < T; ++t) {
    if (!errs[t].empty()) {
      g_error = errs[t];
      delete res;
      return nullptr;
    }
  }
  for (size_t t = 0; t < T; ++t) merge_result(*res, workers[t]);
  return res;
}

const char* avro_last_error() { return g_error.c_str(); }

int64_t avro_rows(void* h) { return static_cast<Result*>(h)->rows; }

void avro_fill_scalars(void* h, double* labels, double* offsets,
                       double* weights, uint8_t* label_seen) {
  auto* r = static_cast<Result*>(h);
  std::memcpy(labels, r->labels.data(), r->rows * 8);
  std::memcpy(offsets, r->offsets.data(), r->rows * 8);
  std::memcpy(weights, r->weights.data(), r->rows * 8);
  std::memcpy(label_seen, r->label_seen.data(), r->rows);
}

int64_t avro_shard_nnz(void* h, int32_t s) {
  return static_cast<int64_t>(static_cast<Result*>(h)->shards[s].vals.size());
}

void avro_fill_coo(void* h, int32_t s, double* vals, int64_t* rows,
                   int64_t* cols) {
  auto& sh = static_cast<Result*>(h)->shards[s];
  std::memcpy(vals, sh.vals.data(), sh.vals.size() * 8);
  std::memcpy(rows, sh.rows.data(), sh.rows.size() * 8);
  std::memcpy(cols, sh.cols.data(), sh.cols.size() * 8);
}

int64_t avro_shard_vocab_size(void* h, int32_t s) {
  return static_cast<Result*>(h)->shards[s].keys.count;
}

int64_t avro_shard_vocab_bytes(void* h, int32_t s) {
  return static_cast<int64_t>(
      static_cast<Result*>(h)->shards[s].keys.blob.size());
}

void avro_fill_shard_vocab(void* h, int32_t s, uint8_t* bytes,
                           int64_t* offs) {
  std::vector<std::string> order;
  static_cast<Result*>(h)->shards[s].keys.export_keys(order);
  int64_t pos = 0;
  for (size_t k = 0; k < order.size(); ++k) {
    offs[k] = pos;
    std::memcpy(bytes + pos, order[k].data(), order[k].size());
    pos += static_cast<int64_t>(order[k].size());
  }
  offs[order.size()] = pos;
}

int64_t avro_id_vocab_size(void* h, int32_t c) {
  return static_cast<Result*>(h)->id_cols[c].vocab.count;
}

int64_t avro_id_vocab_bytes(void* h, int32_t c) {
  return static_cast<int64_t>(
      static_cast<Result*>(h)->id_cols[c].vocab.blob.size());
}

void avro_fill_ids(void* h, int32_t c, int64_t* codes, uint8_t* bytes,
                   int64_t* offs) {
  auto& ic = static_cast<Result*>(h)->id_cols[c];
  std::memcpy(codes, ic.codes.data(), ic.codes.size() * 8);
  std::vector<std::string> order;
  ic.vocab.export_keys(order);
  int64_t pos = 0;
  for (size_t k = 0; k < order.size(); ++k) {
    offs[k] = pos;
    std::memcpy(bytes + pos, order[k].data(), order[k].size());
    pos += static_cast<int64_t>(order[k].size());
  }
  offs[order.size()] = pos;
}

void avro_free(void* h) { delete static_cast<Result*>(h); }

}  // extern "C"
