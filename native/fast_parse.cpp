// Native host-side ingestion kernels for photon-ml-tpu.
//
// The reference delegates ingestion to Spark executors (AvroDataReader /
// LibSVMInputDataFormat); the TPU build's ingestion is host-side, so the
// hot text-parsing loop is native C++ exposed through a C ABI and loaded
// via ctypes (no pybind11 in this environment). Semantics mirror
// photon_ml_tpu/data/libsvm.py::read_libsvm exactly: '#' starts a comment
// (full-line or trailing), blank lines skipped, feature ids 1-based by
// default, negative resulting indices are an error.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Pass 1: count data rows and nnz so the caller can allocate exactly.
// Returns 0 on success.
int libsvm_count(const char* buf, int64_t len, int64_t* out_rows,
                 int64_t* out_nnz) {
  int64_t rows = 0, nnz = 0;
  int64_t i = 0;
  while (i < len) {
    // line start: skip leading whitespace
    while (i < len && (buf[i] == ' ' || buf[i] == '\t')) i++;
    if (i >= len) break;
    if (buf[i] == '\n' || buf[i] == '\r') {  // blank line
      i++;
      continue;
    }
    if (buf[i] == '#') {  // comment line
      while (i < len && buf[i] != '\n') i++;
      continue;
    }
    rows++;
    // skip the label token
    while (i < len && !isspace((unsigned char)buf[i])) i++;
    // tokens until newline/comment
    while (i < len && buf[i] != '\n') {
      while (i < len && (buf[i] == ' ' || buf[i] == '\t' || buf[i] == '\r'))
        i++;
      if (i >= len || buf[i] == '\n') break;
      if (buf[i] == '#') {  // trailing comment
        while (i < len && buf[i] != '\n') i++;
        break;
      }
      nnz++;
      while (i < len && !isspace((unsigned char)buf[i])) i++;
    }
    if (i < len) i++;  // consume newline
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// Pass 2: fill caller-allocated arrays. ``one_based`` nonzero subtracts 1
// from feature ids. Returns max 0-based column id on success, -1 on a
// negative index (wrong zero_based setting), -2 on a malformed token.
// out_rows/out_slots report how many labels/nnz were actually written so
// the caller can cross-check against libsvm_count (mismatch = malformed
// input that the two passes tokenized differently).
int64_t libsvm_parse(const char* buf, int64_t len, int one_based,
                     double* values, int64_t* rows, int64_t* cols,
                     double* labels, int64_t* out_rows, int64_t* out_slots) {
  int64_t row = -1, slot = 0, max_col = -1;
  int64_t i = 0;
  *out_rows = 0;
  *out_slots = 0;
  while (i < len) {
    while (i < len && (buf[i] == ' ' || buf[i] == '\t')) i++;
    if (i >= len) break;
    if (buf[i] == '\n' || buf[i] == '\r') {
      i++;
      continue;
    }
    if (buf[i] == '#') {
      while (i < len && buf[i] != '\n') i++;
      continue;
    }
    row++;
    char* end = nullptr;
    labels[row] = strtod(buf + i, &end);
    if (end == buf + i) return -2;
    i = end - buf;
    while (i < len && buf[i] != '\n') {
      while (i < len && (buf[i] == ' ' || buf[i] == '\t' || buf[i] == '\r'))
        i++;
      if (i >= len || buf[i] == '\n') break;
      if (buf[i] == '#') {
        while (i < len && buf[i] != '\n') i++;
        break;
      }
      int64_t c = strtoll(buf + i, &end, 10);
      if (end == buf + i || *end != ':') return -2;
      i = (end - buf) + 1;  // skip ':'
      // the value must start IMMEDIATELY after ':' — strtod would skip
      // whitespace/newlines and swallow the next line's label
      if (i >= len || isspace((unsigned char)buf[i])) return -2;
      double v = strtod(buf + i, &end);
      if (end == buf + i) return -2;
      i = end - buf;
      if (one_based) c -= 1;
      if (c < 0) return -1;
      values[slot] = v;
      rows[slot] = row;
      cols[slot] = c;
      if (c > max_col) max_col = c;
      slot++;
    }
    if (i < len) i++;
  }
  *out_rows = row + 1;
  *out_slots = slot;
  return max_col;
}

}  // extern "C"
