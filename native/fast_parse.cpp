// Native host-side ingestion kernels for photon-ml-tpu.
//
// The reference delegates ingestion to Spark executors (AvroDataReader /
// LibSVMInputDataFormat); the TPU build's ingestion is host-side, so the
// hot text-parsing loop is native C++ exposed through a C ABI and loaded
// via ctypes (no pybind11 in this environment). Semantics mirror
// photon_ml_tpu/data/libsvm.py::read_libsvm over text-mode files:
// '#' starts a comment (full-line or as a standalone trailing token),
// blank lines skipped, '\r' and '\n' are line terminators (python's
// universal newlines), any other whitespace separates tokens, feature ids
// 1-based by default, and malformed tokens (value not directly after ':',
// trailing junk inside a token) are errors — never silently accepted.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {
inline bool is_eol(char c) { return c == '\n' || c == '\r'; }
inline bool is_blank(char c) {
  return std::isspace(static_cast<unsigned char>(c)) && !is_eol(c);
}
inline bool is_space_any(char c) {
  return std::isspace(static_cast<unsigned char>(c));
}
}  // namespace

extern "C" {

// Pass 1: count data rows and nnz so the caller can allocate exactly.
// Returns 0 on success.
int libsvm_count(const char* buf, int64_t len, int64_t* out_rows,
                 int64_t* out_nnz) {
  int64_t rows = 0, nnz = 0;
  int64_t i = 0;
  while (i < len) {
    while (i < len && is_blank(buf[i])) i++;
    if (i >= len) break;
    if (is_eol(buf[i])) {  // blank line
      i++;
      continue;
    }
    if (buf[i] == '#') {  // comment line
      while (i < len && !is_eol(buf[i])) i++;
      continue;
    }
    rows++;
    // skip the label token
    while (i < len && !is_space_any(buf[i])) i++;
    // feature tokens until end of line / trailing comment
    while (i < len && !is_eol(buf[i])) {
      while (i < len && is_blank(buf[i])) i++;
      if (i >= len || is_eol(buf[i])) break;
      if (buf[i] == '#') {  // trailing comment token
        while (i < len && !is_eol(buf[i])) i++;
        break;
      }
      nnz++;
      while (i < len && !is_space_any(buf[i])) i++;
    }
    if (i < len && is_eol(buf[i])) i++;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return 0;
}

// Pass 2: fill caller-allocated arrays. ``one_based`` nonzero subtracts 1
// from feature ids. Returns the max 0-based column id on success (-1 when
// the file has labels but no features — a valid input), -2 on a malformed
// token, -3 on a negative resulting index (wrong zero_based setting).
// out_rows/out_slots report how many labels/nnz were written so the caller
// can cross-check against libsvm_count (mismatch = the two passes
// tokenized differently = malformed input).
int64_t libsvm_parse(const char* buf, int64_t len, int one_based,
                     double* values, int64_t* rows, int64_t* cols,
                     double* labels, int64_t* out_rows, int64_t* out_slots) {
  int64_t row = -1, slot = 0, max_col = -1;
  int64_t i = 0;
  *out_rows = 0;
  *out_slots = 0;
  while (i < len) {
    while (i < len && is_blank(buf[i])) i++;
    if (i >= len) break;
    if (is_eol(buf[i])) {
      i++;
      continue;
    }
    if (buf[i] == '#') {
      while (i < len && !is_eol(buf[i])) i++;
      continue;
    }
    row++;
    char* end = nullptr;
    labels[row] = strtod(buf + i, &end);
    if (end == buf + i) return -2;
    i = end - buf;
    // the label token must end cleanly (python float("1x") raises)
    if (i < len && !is_space_any(buf[i])) return -2;
    while (i < len && !is_eol(buf[i])) {
      while (i < len && is_blank(buf[i])) i++;
      if (i >= len || is_eol(buf[i])) break;
      if (buf[i] == '#') {
        while (i < len && !is_eol(buf[i])) i++;
        break;
      }
      int64_t c = strtoll(buf + i, &end, 10);
      if (end == buf + i || *end != ':') return -2;
      i = (end - buf) + 1;  // skip ':'
      // the value must start IMMEDIATELY after ':' — strtod would skip
      // whitespace/newlines and swallow the next line's label
      if (i >= len || is_space_any(buf[i])) return -2;
      double v = strtod(buf + i, &end);
      if (end == buf + i) return -2;
      i = end - buf;
      // the value token must end cleanly ("3#x" is an error in python too)
      if (i < len && !is_space_any(buf[i])) return -2;
      if (one_based) c -= 1;
      if (c < 0) return -3;
      values[slot] = v;
      rows[slot] = row;
      cols[slot] = c;
      if (c > max_col) max_col = c;
      slot++;
    }
    if (i < len && is_eol(buf[i])) i++;
  }
  *out_rows = row + 1;
  *out_slots = slot;
  return max_col;
}

}  // extern "C"
