"""Benchmark: BASELINE config #5 — full GAME at ~1B coefficients, one chip.

Shape mirrors the MovieLens-20M GAME stack (FE + per-user RE + per-item RE
+ MF latent factors) at the reference's headline coefficient scale
(/root/reference/README.md:73): 1M user models x 512 local dims + 1M item
models x 512 + 2M latent rows x 16 + a 10K-feature FE ≈ **1.056B trained
coefficients**.

HBM residency math (v5e, 16 GB):
  - each RE coefficient table is N*K*4 = 2.0 GB and stays RESIDENT for its
    whole fit (ShardedCoefficientTable, donated in-place chunk updates);
  - the dense training data (R*4 bytes per coefficient) does NOT fit and
    streams per entity chunk: a 125K-entity chunk is 2.0 GB of design +
    ~2 GB optimizer state, double-buffered against the next chunk's
    generation. Peak live ≈ table 2 + chunk 2x2 + state 2 ≈ 8 GB.
  - across a mesh the table and chunks shard over the entity axis
    (tests/test_streaming.py + __graft_entry__.dryrun_multichip prove the
    sharded path on the 8-device virtual CPU mesh).

Chunk data is generated ON DEVICE from a planted per-entity model (the
tunnel link to this chip moves ~5 MB/s, so host-streamed gigabytes would
measure the link, not the trainer; the host-upload streaming path is the
same trainer code and is exercised by tests/test_streaming.py).

Prints one JSON line: game_1B_coeffs_trained_per_sec.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )

    cfg = OptimizerConfig(
        max_iterations=8,
        tolerance=1e-5,
        lbfgs_history=4,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )

    @functools.partial(jax.jit, static_argnums=(1, 2, 3))
    def gen_chunk(key, E, R, K):
        """Planted logistic per-entity problems: X ~ N(0,1), w* ~ N(0, .3),
        offsets stand in for the residual scores of the other coordinates."""
        kx, kw, ky, ko = jax.random.split(key, 4)
        x = jax.random.normal(kx, (E, R, K), jnp.float32)
        w_true = jax.random.normal(kw, (E, K), jnp.float32) * 0.3
        off = jax.random.normal(ko, (E, R), jnp.float32) * 0.2
        z = jnp.einsum("erk,ek->er", x, w_true) + off
        y = (
            jax.random.uniform(ky, (E, R)) < jax.nn.sigmoid(z)
        ).astype(jnp.float32)
        return DenseBatch(
            x=x, labels=y, offsets=off, weights=jnp.ones((E, R), jnp.float32)
        )

    def run_re(name, n_entities, dim, chunk_entities, rows, seed,
               opt_cfg=cfg):
        table = ShardedCoefficientTable(n_entities, dim)
        trainer = StreamingRandomEffectTrainer("logistic", opt_cfg)
        key = jax.random.key(seed)

        def chunk_source(i):
            return lambda: gen_chunk(
                jax.random.fold_in(key, i), chunk_entities, rows, dim
            )

        chunks = [
            (start, chunk_source(i))
            for i, start in enumerate(
                range(0, n_entities, chunk_entities)
            )
        ]
        # warm every compiled path at the REAL shapes (including the
        # full-size table's chunk reader/writer — jits are
        # shape-specialized), then reset the table: compile time is not
        # trainer throughput
        trainer.train(table, chunks[:1])
        table = ShardedCoefficientTable(n_entities, dim)

        t0 = time.perf_counter()
        stats = trainer.train(table, chunks)  # final fetch = true sync
        secs = time.perf_counter() - t0
        # per-entity tracker sample OUTSIDE the timed window (the packed
        # telemetry fetch crosses the narrow bench tunnel, which a
        # PCIe-attached chip would not feel): the FIRST chunk's entities
        # only — labeled as such below
        tr_stats = trainer.train(
            ShardedCoefficientTable(n_entities, dim),
            chunks[:1],
            with_tracker=True,
        ).tracker
        its = tr_stats.iterations
        pct = {
            f"p{p}": int(np.percentile(its, p)) for p in (50, 90, 99)
        }
        return {
            "name": name,
            "coefficients": stats.total_coefficients,
            "entities": stats.total_entities,
            "chunks": stats.num_chunks,
            "mean_iterations": round(stats.mean_iterations, 2),
            "tracker_sample_entities": len(its),  # first chunk only
            "iteration_percentiles_first_chunk": pct,
            # reasons >= 3: a tolerance test fired (codes: 0 not-converged,
            # 1 max-iterations, 2 line-search stall; optim/common.py)
            "converged_frac_first_chunk": round(
                float(np.mean(tr_stats.reasons >= 3)), 4
            ),
            "stalled_frac_first_chunk": round(
                float(np.mean(tr_stats.reasons == 2)), 4
            ),
            "seconds": round(secs, 3),
            "table_gb": round(table.nbytes / 2**30, 2),
        }

    parts = []
    parts.append(run_re("per_user_re", 1_000_000, 512, 125_000, 8, seed=1))
    parts.append(run_re("per_item_re", 1_000_000, 512, 125_000, 8, seed=2))
    parts.append(run_re("mf_latent", 2_000_000, 16, 1_000_000, 8, seed=3))

    total_coeffs = sum(p["coefficients"] for p in parts)
    total_secs = sum(p["seconds"] for p in parts)
    rate = total_coeffs / total_secs

    print(
        json.dumps(
            {
                "metric": "game_1B_coeffs_trained_per_sec",
                "value": round(rate, 1),
                "unit": "coeffs/s",
                "vs_baseline": None,
                "detail": {
                    "total_coefficients": total_coeffs,
                    "total_seconds": round(total_secs, 3),
                    "parts": parts,
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
