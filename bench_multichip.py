"""Benchmark: multi-chip GSPMD scaling efficiency (ROADMAP item 1).

Measures the two headline training loops at 1 device vs N devices on the
SAME host and reports *scaling efficiency*, plus the ``game_10B``
sharded-capacity config that only fits when the coefficient tables span
the mesh:

  multichip_glm_rows_per_sec        headline GLM logistic FE solve: flat
                                    design committed P("batch"), whole
                                    LBFGS while-loop in one GSPMD jit
                                    (parallel.distributed.gspmd_solve)
  multichip_glmix_cd_coeffs_per_sec GLMix CD inner loop: streamed
                                    entity-sharded RE chunk solves over
                                    P("model") (game.streaming)
  multichip_game10B_per_device_gb   the game_10B config's per-device
                                    table bytes (estimate_table_bytes)
                                    + proof that the unsharded fit is
                                    REFUSED with a headroom message

Each line's detail carries the 1-device and N-device rates,
``scaling_efficiency`` (the N-device/1-device speedup — target >= 6x on
real 8-chip hardware), ``parallel_efficiency`` (speedup / devices), and
the ``comms.*`` byte estimates recorded by the solves so RunReport's
comms fraction stays honest.

Self-provisioning: when the current process sees fewer than N devices
(single-chip bench hosts), the script re-execs itself under
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N``
— the same recipe as tests/conftest.py and the MULTICHIP dryrun. CPU-mesh
runs mark ``"simulated": true`` and do NOT assert the speedup (8 virtual
CPU devices share one socket; the ratio measures the host, not ICI).

Budget: honors ``PHOTON_BENCH_BUDGET_S`` — metrics skipped past the
deadline emit valid ``{"truncated": true}`` JSON (bench_suite recipe).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

#: Devices the scaling comparison targets (env-overridable).
DEFAULT_DEVICES = 8

MULTICHIP_METRICS = (
    "multichip_glm_rows_per_sec",
    "multichip_glmix_cd_coeffs_per_sec",
    "multichip_game10B_per_device_gb",
    # fleet observability (ISSUE 13): a real 2-process gloo fleet run
    # aggregated by telemetry.fleet_report — how much of the fleet's time
    # went to waiting at collectives, and how far apart the members' MFU
    # sits (both lower-is-better; bench_suite gates them that way)
    "fleet_collective_wait_fraction",
    "fleet_mfu_spread",
)

#: The game_10B configuration: ~10.24B coefficients of per-entity state.
#: One 16 GB chip cannot hold the 40.96 GB f32 table — the fit only
#: exists sharded (PAPER.md "hundreds of billions" needs the pod).
GAME_10B = {
    "name": "game_10B",
    "entities": 20_000_000,
    "dim": 512,
    "chunk_entities": 62_500,
    "rows_per_entity": 8,
}

#: Per-chip HBM assumed when the backend publishes no memory stats
#: (PHOTON_CHIP_HBM_GB overrides); 16 GB = v5e.
DEFAULT_CHIP_HBM_GB = 16.0


def _chip_hbm_bytes() -> int:
    raw = os.environ.get("PHOTON_CHIP_HBM_GB")
    if raw:
        try:
            return int(float(raw) * 2**30)
        except ValueError:
            print(f"ignoring malformed PHOTON_CHIP_HBM_GB={raw!r}",
                  file=sys.stderr)
    from photon_ml_tpu.telemetry import memory as telemetry_memory

    stats = telemetry_memory.hbm_stats()
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    return int(DEFAULT_CHIP_HBM_GB * 2**30)


def game_10b_plan(n_devices: int) -> dict:
    """The game_10B memory math: total/per-device table bytes and whether
    the table fits a single chip (it must not — that is the point)."""
    from photon_ml_tpu.telemetry.memory import (
        DEFAULT_SAFETY_FRACTION,
        estimate_table_bytes,
    )

    total = estimate_table_bytes(GAME_10B["entities"], GAME_10B["dim"])
    chip = _chip_hbm_bytes()
    usable = int(chip * DEFAULT_SAFETY_FRACTION)
    min_devices = -(-total // usable)
    return {
        "total_coefficients": GAME_10B["entities"] * GAME_10B["dim"],
        "table_bytes": total,
        "table_gb": round(total / 2**30, 2),
        "chip_hbm_gb": round(chip / 2**30, 2),
        "per_device_bytes": total // max(n_devices, 1),
        "per_device_gb": round(total / max(n_devices, 1) / 2**30, 3),
        "fits_unsharded": total <= usable,
        "min_devices": int(min_devices),
    }


def check_game_10b_headroom(n_devices: int) -> None:
    """Refuse the game_10B fit when its per-device table shard cannot fit
    one chip — BEFORE any allocation, with the memory math in the error.
    ``n_devices=1`` (unsharded) must always refuse on real chips."""
    from photon_ml_tpu.telemetry.memory import DEFAULT_SAFETY_FRACTION

    plan = game_10b_plan(n_devices)
    per_dev = plan["table_bytes"] // max(n_devices, 1)
    usable = int(_chip_hbm_bytes() * DEFAULT_SAFETY_FRACTION)
    if per_dev > usable:
        raise RuntimeError(
            f"game_10B refuses to run on {n_devices} device(s): the "
            f"{plan['table_gb']} GB coefficient table needs "
            f"{plan['per_device_gb']} GB per device but only "
            f"{usable / 2**30:.2f} GB of {plan['chip_hbm_gb']} GB HBM is "
            f"usable per chip — shard the entity axis over at least "
            f"{plan['min_devices']} devices (--mesh model={plan['min_devices']})"
        )


def _provisioned(n_devices: int) -> bool:
    import jax

    return len(jax.devices()) >= n_devices


def _reexec_forced(n_devices: int) -> int:
    """Re-exec under a forced n-device virtual CPU platform and forward
    the child's metric lines (the dryrun_multichip recipe)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PHOTON_MULTICHIP_NO_REEXEC"] = "1"
    here = os.path.abspath(__file__)
    proc = subprocess.run(
        [sys.executable, here],
        env=env,
        cwd=os.path.dirname(here),
        capture_output=True,
        text=True,
        timeout=3600,
    )
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            print(line, flush=True)
    if proc.returncode != 0:
        print(proc.stderr[-2000:], file=sys.stderr)
    return proc.returncode


def _timed_rate(run, units: float) -> tuple[float, dict]:
    """PERF_NOTES timing recipe: ``run(salt)`` returns a scalar device
    value; warm with one salt, time a different one, sync by scalar
    fetch."""
    from photon_ml_tpu import telemetry

    float(telemetry.sync_fetch(run(0), label="warmup"))
    t0 = time.perf_counter()
    final = float(telemetry.sync_fetch(run(1), label="timed"))
    elapsed = time.perf_counter() - t0
    return units / elapsed, {"elapsed_s": round(elapsed, 3),
                             "final_value": final}


def bench_glm(n_devices: int, simulated: bool) -> dict:
    """Headline GLM FE solve at 1 vs N devices (GSPMD data parallel)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import metrics as telemetry_metrics
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.sparse import SparseBatch
    from photon_ml_tpu.ops.tiled import TiledBatch
    from photon_ml_tpu.optim import LBFGSConfig, glm_adapter, lbfgs_solve
    from photon_ml_tpu.optim.factory import OptimizerConfig
    from photon_ml_tpu.parallel import gspmd_solve, make_mesh, place_batch

    # full headline shape on real chips; a CPU mesh gets a scaled-down
    # problem (same code paths, tractable wall clock)
    if simulated:
        n_rows, n_features, nnz_per_row, iters = 100_000, 2_000, 10, 8
    else:
        n_rows, n_features, nnz_per_row, iters = 1_000_000, 10_000, 20, 20
    rng = np.random.default_rng(0)
    nnz = n_rows * nnz_per_row
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    w_true = rng.normal(size=n_features) * 0.5
    margins = np.zeros(n_rows)
    np.add.at(margins, rows, values * w_true[cols])
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float64)

    make = TiledBatch.from_coo if not simulated else SparseBatch.from_coo
    batch = make(
        values=values, rows=rows, cols=cols, labels=y,
        num_features=n_features,
    )
    obj = make_objective("logistic", l2_weight=1.0)
    lcfg = LBFGSConfig(max_iterations=iters, tolerance=0.0)  # fixed work
    cfg = OptimizerConfig(max_iterations=iters, tolerance=0.0,
                          regularization_weight=1.0)

    # -- 1 device: plain jit solve on the default device ------------------
    def single(w0, b):
        return lbfgs_solve(glm_adapter(obj, b), w0, lcfg)

    single_jit = telemetry.instrumented_jit(single, name="bench_mc_glm_1dev")

    def run_single(salt):
        w0 = jnp.full((n_features,), salt * 1e-6, jnp.float32)
        return single_jit(w0, batch).value

    passes = iters + 1  # init eval + one pass per LBFGS iteration
    rate_1, d1 = _timed_rate(run_single, n_rows * passes)

    # -- N devices: flat design committed P("batch"), one GSPMD jit -------
    mesh = make_mesh({"batch": n_devices})
    sharded = place_batch(batch, mesh)
    comms_before = telemetry_metrics.peek_counter("comms.bytes_total") or 0.0

    def run_mesh(salt):
        w0 = jnp.full((n_features,), salt * 1e-6, jnp.float32)
        return gspmd_solve("logistic", sharded, cfg, w0, mesh).value

    rate_n, dn = _timed_rate(run_mesh, n_rows * passes)
    comms_bytes = (telemetry_metrics.peek_counter("comms.bytes_total") or 0.0) - comms_before

    speedup = rate_n / rate_1 if rate_1 else None
    return {
        "metric": "multichip_glm_rows_per_sec",
        "value": round(rate_n, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "detail": {
            "devices": n_devices,
            "simulated": simulated,
            "rows": n_rows,
            "features": n_features,
            "data_passes": passes,
            "rows_per_sec_1dev": round(rate_1, 1),
            "rows_per_sec_ndev": round(rate_n, 1),
            "scaling_efficiency": None if speedup is None else round(speedup, 3),
            "parallel_efficiency": (
                None if speedup is None else round(speedup / n_devices, 3)
            ),
            "comms_bytes_estimated": comms_bytes,
            "single_device": d1,
            "mesh": dn,
        },
    }


def bench_glmix_cd(n_devices: int, simulated: bool) -> dict:
    """GLMix CD inner loop: streamed entity-sharded RE solves at 1 vs N
    devices (the coordinate-descent hot path at streaming scale)."""
    import functools

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.telemetry import metrics as telemetry_metrics
    from photon_ml_tpu.game.streaming import (
        ShardedCoefficientTable,
        StreamingRandomEffectTrainer,
    )
    from photon_ml_tpu.ops.dense import DenseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.parallel import make_mesh

    if simulated:
        n_entities, dim, chunk, rows = 4096, 32, 1024, 8
    else:
        n_entities, dim, chunk, rows = 1_000_000, 512, 125_000, 8
    cfg = OptimizerConfig(
        max_iterations=8,
        tolerance=1e-5,
        lbfgs_history=4,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )

    @functools.partial(jax.jit, static_argnums=(1, 2, 3))
    def gen_chunk(key, E, R, K):
        kx, kw, ky, ko = jax.random.split(key, 4)
        x = jax.random.normal(kx, (E, R, K), jnp.float32)
        w_star = jax.random.normal(kw, (E, K), jnp.float32) * 0.3
        off = jax.random.normal(ko, (E, R), jnp.float32) * 0.2
        z = jnp.einsum("erk,ek->er", x, w_star) + off
        y = (jax.random.uniform(ky, (E, R)) < jax.nn.sigmoid(z)).astype(
            jnp.float32
        )
        return DenseBatch(
            x=x, labels=y, offsets=off, weights=jnp.ones((E, R), jnp.float32)
        )

    def run(mesh) -> float:
        table = ShardedCoefficientTable(n_entities, dim, mesh=mesh)
        trainer = StreamingRandomEffectTrainer("logistic", cfg, mesh=mesh)
        key = jax.random.key(7)
        chunks = [
            (start, (lambda i=i: gen_chunk(
                jax.random.fold_in(key, i), chunk, rows, dim
            )))
            for i, start in enumerate(range(0, n_entities, chunk))
        ]
        trainer.train(table, chunks[:1])  # warm the compiled paths
        table = ShardedCoefficientTable(n_entities, dim, mesh=mesh)
        t0 = time.perf_counter()
        stats = trainer.train(table, chunks)  # final fetch = true sync
        secs = time.perf_counter() - t0
        return stats.total_coefficients / secs

    rate_1 = run(None)
    comms_before = telemetry_metrics.peek_counter("comms.bytes_total") or 0.0
    rate_n = run(make_mesh({"model": n_devices}))
    comms_bytes = (telemetry_metrics.peek_counter("comms.bytes_total") or 0.0) - comms_before
    speedup = rate_n / rate_1 if rate_1 else None
    return {
        "metric": "multichip_glmix_cd_coeffs_per_sec",
        "value": round(rate_n, 1),
        "unit": "coeffs/s",
        "vs_baseline": None,
        "detail": {
            "devices": n_devices,
            "simulated": simulated,
            "entities": n_entities,
            "dim": dim,
            "coeffs_per_sec_1dev": round(rate_1, 1),
            "coeffs_per_sec_ndev": round(rate_n, 1),
            "scaling_efficiency": None if speedup is None else round(speedup, 3),
            "parallel_efficiency": (
                None if speedup is None else round(speedup / n_devices, 3)
            ),
            "comms_bytes_estimated": comms_bytes,
        },
    }


def bench_game_10b(n_devices: int, simulated: bool) -> dict:
    """The sharded-capacity config: memory math + the unsharded refusal.

    The actual 10B fit only runs on real hardware with enough chips AND
    an explicit opt-in (PHOTON_RUN_10B=1) — it is a capacity proof, not a
    throughput line. Everywhere else this verifies the math and that the
    unsharded attempt is refused with the headroom message."""
    plan = game_10b_plan(n_devices)
    refusal = None
    try:
        check_game_10b_headroom(1)
    except RuntimeError as e:
        refusal = str(e)
    sharded_ok = True
    sharded_error = None
    try:
        check_game_10b_headroom(max(n_devices, plan["min_devices"]))
    except RuntimeError as e:  # even the sharded plan does not fit
        sharded_ok = False
        sharded_error = str(e)
    ran_fit = False
    if (
        not simulated
        and sharded_ok
        and n_devices >= plan["min_devices"]
        and os.environ.get("PHOTON_RUN_10B") == "1"
    ):
        import jax

        from photon_ml_tpu.game.streaming import ShardedCoefficientTable
        from photon_ml_tpu.parallel import make_mesh

        mesh = make_mesh({"model": n_devices})
        check_game_10b_headroom(n_devices)
        table = ShardedCoefficientTable(
            GAME_10B["entities"], GAME_10B["dim"], mesh=mesh
        )
        assert table.sharding is not None
        ran_fit = True
        del table
    return {
        "metric": "multichip_game10B_per_device_gb",
        "value": plan["per_device_gb"],
        "unit": "GB/device",
        "vs_baseline": None,
        "detail": {
            "devices": n_devices,
            "simulated": simulated,
            **plan,
            "unsharded_refused": refusal is not None,
            "refusal": refusal,
            "sharded_plan_fits": sharded_ok,
            "sharded_plan_error": sharded_error,
            "table_allocated": ran_fit,
        },
    }


#: One shared fleet run feeds both fleet_* metric lines (module-level
#: memo: the steps loop calls one step per metric).
_FLEET_OBS_CACHE: dict[str, dict] = {}

#: Simulated per-chip peak FLOP/s handed to CPU fleet workers so their
#: per-member MFU (and thus the spread) is computable at all — the
#: NUMBER is meaningless off-TPU (marked simulated), the plumbing is
#: what the gate protects.
_SIMULATED_PEAK_FLOPS = 1.0e12


def _fleet_observability_lines(simulated: bool) -> dict[str, dict]:
    """Run one supervised 2-process gloo fleet with per-member telemetry
    and derive the fleet_* metrics from the aggregated FleetReport —
    the bench-side proof the whole observability chain (identity
    suffixing -> collective-wait attribution -> fleet aggregation)
    holds under a real multi-process fit.

    These two lines are ALWAYS ``simulated: true``, regardless of the
    host platform: the supervised workers force JAX_PLATFORMS=cpu + gloo
    by harness design (tools/fleet._worker_env), so even on a TPU box
    this measures the CPU fleet — the plumbing, not the hardware. For
    the same reason the per-chip peak is injected (when the operator set
    none) so per-member MFU, and thus fleet_mfu_spread, is computable at
    all. A failed run is memoized too: the second metric step must not
    repeat a known-failing (up to 420 s) fleet launch."""
    import shutil
    import tempfile

    from photon_ml_tpu.telemetry.fleet_report import FleetReport
    from tools import fleet

    if _FLEET_OBS_CACHE:
        cached_error = _FLEET_OBS_CACHE.get("error")
        if cached_error is not None:
            raise RuntimeError(cached_error)
        return _FLEET_OBS_CACHE
    workdir = tempfile.mkdtemp(prefix="bench_fleet_obs_")
    try:
        injected_peak = "PHOTON_PEAK_FLOPS" not in os.environ
        if injected_peak:
            os.environ["PHOTON_PEAK_FLOPS"] = str(_SIMULATED_PEAK_FLOPS)
        try:
            report = fleet.run_fleet(fleet.FleetSpec(
                workdir=workdir,
                num_processes=2,
                devices_per_process=2,
                progress_heartbeat_every_s=0.5,
                timeout_s=420.0,
            ))
        finally:
            if injected_peak:
                del os.environ["PHOTON_PEAK_FLOPS"]
        if not report.get("ok"):
            raise RuntimeError(
                f"fleet observability run failed: "
                f"{json.dumps(report, default=str)[:1500]}"
            )
        fleet_report = FleetReport.load(report["telemetry_dir"])
        km = fleet_report.key_metrics()
    except Exception as e:
        # memoize EVERY failure shape (launch error, not-ok report,
        # aggregation error): the second metric step must never repeat a
        # known-failing fleet launch, and no attempt may leak its workdir
        _FLEET_OBS_CACHE["error"] = f"{type(e).__name__}: {e}"[:1600]
        shutil.rmtree(workdir, ignore_errors=True)
        raise
    detail = {
        "simulated": True,  # the fleet is CPU+gloo even on a TPU host
        "host_platform_simulated": simulated,
        "num_processes": 2,
        "devices_per_process": 2,
        "lost_members": fleet_report.lost_members(),
        "straggler": fleet_report.straggler(),
        "fleet_rows_per_sec": km.get("fleet_rows_per_sec"),
        "fleet_collective_wait_s": km.get("fleet_collective_wait_s"),
        "member_mfu": {
            str(m.process_index): m.key_metrics().get("mfu")
            for m in fleet_report.members
        },
    }
    if injected_peak:
        detail["simulated_peak_flops"] = _SIMULATED_PEAK_FLOPS
    # the aggregates are extracted; repeated gated bench runs must not
    # accumulate full fleet workdirs (checkpoints + traces) in tempdir
    shutil.rmtree(workdir, ignore_errors=True)
    _FLEET_OBS_CACHE.update({
        "fleet_collective_wait_fraction": {
            "metric": "fleet_collective_wait_fraction",
            "value": km.get("fleet_collective_wait_fraction"),
            "unit": "fraction",
            "vs_baseline": None,
            "detail": detail,
        },
        "fleet_mfu_spread": {
            "metric": "fleet_mfu_spread",
            "value": km.get("fleet_mfu_spread"),
            "unit": "mfu delta",
            "vs_baseline": None,
            "detail": detail,
        },
    })
    return _FLEET_OBS_CACHE


def run_multichip(deadline=None) -> dict[str, float | None]:
    """Emit the multichip metric lines (budget-aware); returns
    {metric: value or None} for the bench_suite --gate flow."""
    from bench_suite import truncated_line

    import jax

    from photon_ml_tpu import telemetry

    telemetry.configure_from_env()
    n_devices = int(
        os.environ.get("PHOTON_MULTICHIP_DEVICES", str(DEFAULT_DEVICES))
    )
    n_devices = min(n_devices, len(jax.devices()))
    simulated = jax.devices()[0].platform != "tpu"
    steps = (
        ("multichip_glm_rows_per_sec", lambda: bench_glm(n_devices, simulated)),
        (
            "multichip_glmix_cd_coeffs_per_sec",
            lambda: bench_glmix_cd(n_devices, simulated),
        ),
        (
            "multichip_game10B_per_device_gb",
            lambda: bench_game_10b(n_devices, simulated),
        ),
        (
            "fleet_collective_wait_fraction",
            lambda: _fleet_observability_lines(simulated)[
                "fleet_collective_wait_fraction"
            ],
        ),
        (
            "fleet_mfu_spread",
            lambda: _fleet_observability_lines(simulated)["fleet_mfu_spread"],
        ),
    )
    results: dict[str, float | None] = {}
    truncated = False
    for metric, step in steps:
        if truncated or (
            deadline is not None and time.monotonic() > deadline
        ):
            truncated = True
            print(truncated_line(metric), flush=True)
            results[metric] = None
            continue
        try:
            line = step()
        except Exception as e:  # noqa: BLE001 — report, don't kill the suite
            print(
                json.dumps(
                    {"metric": metric, "value": None, "unit": None,
                     "vs_baseline": None, "error": str(e)[-400:]}
                ),
                flush=True,
            )
            results[metric] = None
            continue
        results[metric] = line["value"]
        print(json.dumps(line), flush=True)
    return results


def main() -> int:
    n_devices = int(
        os.environ.get("PHOTON_MULTICHIP_DEVICES", str(DEFAULT_DEVICES))
    )
    if (
        not _provisioned(n_devices)
        and os.environ.get("PHOTON_MULTICHIP_NO_REEXEC") != "1"
    ):
        return _reexec_forced(n_devices)
    from bench_suite import budget_deadline

    run_multichip(deadline=budget_deadline())
    return 0


if __name__ == "__main__":
    sys.exit(main())
