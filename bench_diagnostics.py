"""Bootstrap overhead: B=64 GLMix random-effect bootstrap vs ONE fit
(ISSUE 20 acceptance: ``bootstrap_overhead_ratio`` <= 2.0 on TPU).

The diagnostics claim is that B bootstrap resamples ride the sweep
machinery as B vmapped lanes composed with the per-entity vmap — so the
marginal cost of 64 resampled re-fits is vectorization, not 64x wall
clock. This bench measures exactly that composition through the public
:func:`photon_ml_tpu.diagnostics.bootstrap.bootstrap_random_effect`
entry point:

  1. the SINGLE fit: one all-ones lane (identity resample weights) —
     the same compiled solver family a plain per-entity vmap fit uses,
  2. the BOOTSTRAP: B=64 multinomial-count lanes drawn by
     ``bootstrap_re_weights`` (the same draws the publish path attaches
     CIs from),

both warmed (compilation excluded; fresh-valued args defeat the tunnel
result cache per PERF_NOTES.md), min-of-reps timed, and reports
``bootstrap_overhead_ratio`` = bootstrap_s / single_s — LOWER is
better, gated at <= 2.0 by ``bench_suite --diagnostics --gate``.

On non-TPU backends the entity geometry shrinks and the line carries
``"simulated": true`` — lane-vectorization economics are a TPU claim;
the CPU run proves wiring, not the ratio.

Budget: ``PHOTON_BENCH_BUDGET_S`` honored; skipped phases emit valid
``"truncated": true`` lines.
"""

from __future__ import annotations

import json
import time

import numpy as np

DIAGNOSTICS_METRICS = ("bootstrap_overhead_ratio",)

NUM_SAMPLES = 64
RATIO_CEILING = 2.0
REPS = 3


def _entity_batch(rng, n_entities, rows, feats):
    """A dense-as-COO entity batch: E same-geometry per-entity logistic
    problems with planted coefficients, leading entity axis for vmap."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops.sparse import SparseBatch

    x = rng.normal(size=(n_entities, rows, feats))
    w_true = rng.normal(size=(n_entities, feats)) * 0.5
    margins = np.einsum("erk,ek->er", x, w_true)
    y = (rng.random((n_entities, rows)) < 1.0 / (1.0 + np.exp(-margins)))
    nnz = rows * feats
    batch = SparseBatch(
        values=jnp.asarray(x.reshape(n_entities, nnz), jnp.float32),
        rows=jnp.asarray(
            np.broadcast_to(
                np.repeat(np.arange(rows, dtype=np.int32), feats),
                (n_entities, nnz),
            )
        ),
        cols=jnp.asarray(
            np.broadcast_to(
                np.tile(np.arange(feats, dtype=np.int32), rows),
                (n_entities, nnz),
            )
        ),
        labels=jnp.asarray(y, jnp.float32),
        offsets=jnp.zeros((n_entities, rows), jnp.float32),
        weights=jnp.ones((n_entities, rows), jnp.float32),
        num_features=feats,
    )
    return batch


def run_diagnostics(deadline=None) -> dict[str, float | None]:
    from bench_suite import truncated_line

    def truncated():
        for metric in DIAGNOSTICS_METRICS:
            print(truncated_line(metric), flush=True)
        return {metric: None for metric in DIAGNOSTICS_METRICS}

    if deadline is not None and time.monotonic() > deadline:
        return truncated()

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.diagnostics.bootstrap import (
        bootstrap_random_effect,
        bootstrap_re_weights,
    )
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    telemetry.configure_from_env()
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # a realistic RE bucket: the bench_game per-user shape
        n_entities, rows, feats = 4096, 64, 16
    else:
        n_entities, rows, feats = 16, 8, 4

    rng = np.random.default_rng(0)
    ebatch = _entity_batch(rng, n_entities, rows, feats)
    w0 = jnp.zeros((n_entities, feats), jnp.float32)
    config = OptimizerConfig(
        optimizer_type=OptimizerType.NEWTON,
        max_iterations=10,
        tolerance=1e-7,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )

    # identity lanes = the single fit; multinomial lanes = the bootstrap
    single_lanes = np.ones((1, n_entities, rows), np.float32)
    boot_lanes = bootstrap_re_weights(
        NUM_SAMPLES, np.ones((n_entities, rows), np.float32), seed=0
    )

    def timed(lane_weights):
        # warm-up compiles this lane count's executable; the timed reps
        # then perturb w0 so the tunnel cannot replay a cached result
        bootstrap_random_effect(
            ebatch, "logistic", config, w0, lane_weights=lane_weights
        )
        best = None
        for rep in range(1, REPS + 1):
            t0 = time.perf_counter()
            report = bootstrap_random_effect(
                ebatch, "logistic", config, w0 + 1e-6 * rep,
                lane_weights=lane_weights,
            )
            elapsed = time.perf_counter() - t0
            best = elapsed if best is None else min(best, elapsed)
        return best, report

    single_s, _ = timed(single_lanes)
    if deadline is not None and time.monotonic() > deadline:
        return truncated()
    boot_s, report = timed(boot_lanes)
    ratio = boot_s / max(single_s, 1e-9)

    if on_tpu:
        assert ratio <= RATIO_CEILING, (
            f"B={NUM_SAMPLES} bootstrap cost {ratio:.2f}x a single fit "
            f"(> {RATIO_CEILING}x): the resample lanes are not riding "
            "the vmap composition"
        )
    print(
        json.dumps(
            {
                "metric": "bootstrap_overhead_ratio",
                "value": round(ratio, 3),
                "unit": "x",
                "vs_baseline": None,
                "detail": {
                    "num_samples": NUM_SAMPLES,
                    "single_fit_s": round(single_s, 4),
                    "bootstrap_s": round(boot_s, 4),
                    "entities": n_entities,
                    "rows_per_entity": rows,
                    "features_per_entity": feats,
                    "mean_ci_width": report.summary().get("mean_ci_width"),
                    "ceiling": RATIO_CEILING,
                    "platform": jax.devices()[0].platform,
                    "simulated": not on_tpu,
                },
            }
        ),
        flush=True,
    )
    return {"bootstrap_overhead_ratio": round(ratio, 3)}


def main():
    from bench_suite import budget_deadline

    run_diagnostics(deadline=budget_deadline())


if __name__ == "__main__":
    main()
