"""16-config λ-sweep wall time vs single-fit wall time on the headline
GLM config (ISSUE 8 acceptance: ``sweep_over_single_ratio`` < 3x on TPU).

Measures, on the headline problem shape (logistic 1M x 10K, tiled
layout, LBFGS fixed-work):

  1. one single fit (the bench.py headline recipe), and
  2. one 16-point vmapped λ sweep through sweep.runner.sweep_glm
     (warm_start=False, rounds=1: identical per-lane work to 16
     independent fits — the ratio measures pure batching efficiency),

and reports ``sweep_over_single_ratio`` = sweep_s / single_s. A value of
16 means the config axis bought nothing; the MXU target is < 3. Also
emits ``sweep_parity_max_rel_err``: the max relative loss difference of
3 probed lanes vs true independent single fits (the correctness side of
the acceptance, cheap enough to ride the bench).

On non-TPU backends the problem shrinks (vmapped pallas-interpret at
1M x 10K x 16 is not a benchmark) and the line carries
``"simulated": true`` — the <3x target is only meaningful on TPU.

Budget: ``PHOTON_BENCH_BUDGET_S`` honored; skipped phases emit valid
``"truncated": true`` lines.
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np

SWEEP_METRICS = ("sweep_over_single_ratio",)

N_CONFIGS = 16


def _problem(n_rows, n_features, nnz_per_row):
    rng = np.random.default_rng(0)
    nnz = n_rows * nnz_per_row
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), nnz_per_row)
    cols = rng.integers(0, n_features, size=nnz)
    values = rng.normal(size=nnz)
    w_true = rng.normal(size=n_features) * 0.5
    margins = np.zeros(n_rows)
    np.add.at(margins, rows, values * w_true[cols])
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-margins))).astype(
        np.float64
    )
    return values, rows, cols, y


def run_sweep_bench(deadline=None) -> dict[str, float | None]:
    from bench_suite import truncated_line

    if deadline is not None and time.monotonic() > deadline:
        print(truncated_line("sweep_over_single_ratio"), flush=True)
        return {"sweep_over_single_ratio": None}

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.ops.tiled import TiledBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optim.factory import solve
    from photon_ml_tpu.sweep.runner import sweep_glm

    telemetry.configure_from_env()
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        n_rows, n_features, nnz_per_row, max_iters = 1_000_000, 10_000, 20, 20
    else:
        # CPU smoke shape: same code path, honest "simulated" marker
        n_rows, n_features, nnz_per_row, max_iters = 50_000, 1_000, 10, 10

    values, rows, cols, y = _problem(n_rows, n_features, nnz_per_row)
    batch = TiledBatch.from_coo(
        values=values, rows=rows, cols=cols, labels=y,
        num_features=n_features,
    ) if on_tpu else None
    if batch is None:
        from photon_ml_tpu.ops.sparse import SparseBatch

        batch = SparseBatch.from_coo(
            values=values, rows=rows, cols=cols, labels=y,
            num_features=n_features,
        ).device()
    cfg = OptimizerConfig(
        max_iterations=max_iters,
        tolerance=0.0,  # fixed work: every lane runs max_iters
        regularization=RegularizationContext(RegularizationType.L2),
    )
    lams = tuple(float(v) for v in np.logspace(2, -4, N_CONFIGS))
    w0 = jnp.zeros((n_features,), jnp.float32)

    # --- single fit (headline recipe: warm with different args, then time)
    single_cfg = dataclasses.replace(cfg, regularization_weight=lams[0])

    def single_run(w, b):
        return solve("logistic", b, single_cfg, w)

    single_jit = telemetry.instrumented_jit(single_run, name="bench_single")
    float(single_jit(w0 + 1e-3, batch).value)  # warmup
    t0 = time.perf_counter()
    res = single_jit(w0, batch)
    float(telemetry.sync_fetch(res.value, label="single"))
    single_s = time.perf_counter() - t0

    if deadline is not None and time.monotonic() > deadline:
        print(truncated_line("sweep_over_single_ratio"), flush=True)
        return {"sweep_over_single_ratio": None}

    # --- 16-config vmapped sweep (cold lanes = same work as 16 fits)
    sweep_glm(batch, "logistic", lams, cfg, warm_start=False)  # warmup
    t0 = time.perf_counter()
    sres = sweep_glm(batch, "logistic", lams, cfg, warm_start=False)
    float(telemetry.sync_fetch(sres.values[-1], label="sweep"))
    sweep_s = time.perf_counter() - t0
    ratio = sweep_s / max(single_s, 1e-9)

    # --- parity probe: 3 lanes vs true independent fits
    probes = (0, N_CONFIGS // 2, N_CONFIGS - 1)
    max_rel = 0.0
    sweep_vals = np.asarray(sres.values)
    for g in probes:
        ind = solve(
            "logistic", batch,
            dataclasses.replace(cfg, regularization_weight=lams[g]), w0,
        )
        iv = float(telemetry.sync_fetch(ind.value, label="parity"))
        max_rel = max(max_rel, abs(sweep_vals[g] - iv) / max(abs(iv), 1e-12))

    print(
        json.dumps(
            {
                "metric": "sweep_over_single_ratio",
                "value": round(ratio, 3),
                "unit": "x",
                "vs_baseline": None,
                "detail": {
                    "configs": N_CONFIGS,
                    "single_fit_s": round(single_s, 3),
                    "sweep_s": round(sweep_s, 3),
                    "rows": n_rows,
                    "features": n_features,
                    "max_iterations": max_iters,
                    "sweep_parity_max_rel_err": float(max_rel),
                    "per_config_iterations": sres.iterations.tolist(),
                    "platform": jax.devices()[0].platform,
                    "simulated": not on_tpu,
                },
            }
        ),
        flush=True,
    )
    return {"sweep_over_single_ratio": round(ratio, 3)}


def main():
    from bench_suite import budget_deadline

    run_sweep_bench(deadline=budget_deadline())


if __name__ == "__main__":
    main()
