"""Online-serving benchmarks: steady-state latency/throughput AND the
sustained-load SLO sweep.

Two layers:

- ``main()`` (the legacy closed-loop bench): builds a synthetic GLMix
  model (FE 2K features + 20K-entity RE with K=16 local dims), warms a
  ScoringEngine, and drives the MicroBatcher from closed-loop client
  threads — ``serving_p50_ms`` / ``serving_p99_ms`` /
  ``serving_rows_per_sec``.

- :func:`run_serving_slo` (the SLO gate, ``bench_suite --serving``): an
  OFFERED-LOAD sweep over (queue_depth x request rate) against the
  continuous batcher — open-loop clients submit on a schedule whether or
  not earlier requests finished, which is what production traffic does —
  reporting per-cell p50/p99 latency and shed fraction, then a sustained
  window that triggers a registry HOT SWAP and a NEARLINE per-entity
  update mid-traffic and compares p99 across each disturbance against
  the steady window:

    serving_slo_rows_per_sec        throughput of the highest offered
                                    rate whose shed fraction stays inside
                                    SHED_BUDGET (higher is better)
    serving_slo_p99_ms              p99 latency at that sustained rate
    serving_slo_p99_swap_ratio      p99 during the hot-swap window over
                                    steady p99 (1.0 = perfectly flat)
    serving_slo_p99_nearline_ratio  same across the nearline update
    serving_nearline_apply_ms       p99 event->applied-on-tables lag (the
                                    time-to-applied-update)

  All ratio/latency metrics gate LOWER-is-better (bench_suite
  LOWER_IS_BETTER_METRICS). On a CPU backend the JSON carries
  ``"simulated_on_cpu": true`` — the shapes are real, the absolute
  milliseconds are not TPU numbers.

``PHOTON_BENCH_BUDGET_S`` caps wall clock; exhausted budget emits
``"truncated": true`` placeholders per metric (bench_suite convention).
The jit-compile counter is asserted flat across measurement windows — a
steady-state recompile is a bug, not a slow run.
"""

from __future__ import annotations

import functools
import json
import shutil
import tempfile
import threading
import time

import numpy as np

SERVING_METRICS = (
    "serving_p50_ms",
    "serving_p99_ms",
    "serving_rows_per_sec",
)

SLO_METRICS = (
    "serving_slo_rows_per_sec",
    "serving_slo_p99_ms",
    "serving_slo_p99_swap_ratio",
    "serving_slo_p99_nearline_ratio",
    "serving_nearline_apply_ms",
)

FLEET_METRICS = (
    "serving_fleet_p99_resize_ratio",
    "serving_fleet_kill_recovery_s",
)

TRACE_OVERHEAD_METRICS = (
    "serving_trace_overhead_ratio",
)

#: Offered load at/below engine capacity may shed at most this fraction
#: of requests — the SLO error budget.
SHED_BUDGET = 0.01

N_FEATURES = 2_000
N_ENTITIES = 20_000
LOCAL_DIM = 16
ROW_NNZ = 24
MAX_BATCH = 64
N_CLIENTS = 8
MEASURE_S = 10.0


def build_model(n_features=N_FEATURES, n_entities=N_ENTITIES,
                local_dim=LOCAL_DIM, seed=0):
    import jax.numpy as jnp

    from photon_ml_tpu.game.models import (
        FixedEffectModel,
        GameModel,
        RandomEffectBucketModel,
        RandomEffectModel,
    )

    rng = np.random.default_rng(seed)
    fe = FixedEffectModel(
        coefficients=jnp.asarray(
            rng.normal(size=n_features) * 0.1, jnp.float32
        ),
        shard_name="global",
    )
    n_buckets = 4
    entity_bucket = (np.arange(n_entities) % n_buckets).astype(np.int64)
    entity_pos = np.zeros(n_entities, np.int64)
    buckets = []
    for b in range(n_buckets):
        codes_b = np.nonzero(entity_bucket == b)[0]
        entity_pos[codes_b] = np.arange(len(codes_b))
        # each entity's local space: local_dim sorted global feature ids
        proj = np.sort(
            rng.choice(n_features, size=(len(codes_b), local_dim),
                       replace=True),
            axis=1,
        ).astype(np.int32)
        buckets.append(
            RandomEffectBucketModel(
                coefficients=jnp.asarray(
                    rng.normal(size=(len(codes_b), local_dim)) * 0.1,
                    jnp.float32,
                ),
                projection=jnp.asarray(proj),
                entity_codes=jnp.asarray(codes_b, jnp.int32),
            )
        )
    re = RandomEffectModel(
        id_name="memberId",
        shard_name="global",
        buckets=tuple(buckets),
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        vocab=np.arange(n_entities),
    )
    return GameModel(task="logistic", models={"fixed": fe, "member": re})


def make_rows(rng, count, n_features=N_FEATURES, n_entities=N_ENTITIES,
              row_nnz=ROW_NNZ):
    rows = []
    for _ in range(count):
        cols = np.sort(
            rng.choice(n_features, size=row_nnz, replace=False)
        )
        vals = rng.normal(size=row_nnz)
        rows.append(
            {
                "features": {
                    "global": [
                        [int(c), float(v)] for c, v in zip(cols, vals)
                    ]
                },
                "ids": {"memberId": int(rng.integers(0, n_entities))},
            }
        )
    return rows


def _percentile(sorted_arr, p):
    if not len(sorted_arr):
        return None
    return round(float(sorted_arr[int(p * (len(sorted_arr) - 1))]), 3)


def _open_loop_cell(batcher, pool, rate, measure_s, n_clients, timeout_s=10.0):
    """Drive one offered-load cell: ``rate`` requests/s aggregate across
    ``n_clients`` open-loop threads for ``measure_s``. Returns
    ``(latencies, sheds, rows_done, elapsed)`` where ``latencies`` is a
    list of ``(t_submit, latency_ms)`` stamped at completion time."""
    from photon_ml_tpu.serving import Overloaded

    latencies: list[tuple[float, float]] = []  # (t_submit, latency_ms)
    sheds = [0]
    rows_done = [0]
    all_futures = []
    closed = [False]  # cell accounting sealed: late callbacks are ignored
    lock = threading.Lock()
    per_client = rate / n_clients
    interval = 1.0 / per_client if per_client > 0 else measure_s
    t_start = time.monotonic()
    stop_at = t_start + measure_s

    # latency is stamped INSIDE the done callback, which the dispatcher
    # runs at completion — recording at the client's next reap would add
    # up to one inter-send interval of schedule gap to every sample. The
    # callback is the ONLY accounting point for submitted requests; after
    # the cell seals (``closed``) a straggler completing during
    # batcher.stop() can neither append a sample nor double a shed count.
    def _record(t0, k, fut):
        now = time.monotonic()
        try:
            fut.result()
        except Exception:  # noqa: BLE001 — counted as shed
            with lock:
                if not closed[0]:
                    sheds[0] += 1
            return
        with lock:
            if not closed[0]:
                latencies.append((t0, (now - t0) * 1000.0))
                rows_done[0] += k

    def client(seed):
        local_rng = np.random.default_rng(seed)
        next_send = time.monotonic() + float(local_rng.random()) * interval
        pending = []
        while True:
            now = time.monotonic()
            if now >= stop_at:
                break
            if now < next_send:
                time.sleep(min(next_send - now, 0.002))
                continue
            next_send += interval  # open loop: the schedule never waits
            rows = pool[int(local_rng.integers(0, len(pool)))]
            t0 = time.monotonic()
            try:
                fut = batcher.submit(rows)
            except Overloaded:
                with lock:
                    sheds[0] += 1
                continue
            fut.add_done_callback(functools.partial(_record, t0, len(rows)))
            pending.append(fut)
            with lock:
                all_futures.append(fut)
        # tail drain: bounded wait for outstanding futures; the callback
        # records each at its true completion time
        deadline = time.monotonic() + timeout_s
        for f in pending:
            try:
                f.result(timeout=max(0.0, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 — accounted by the callback
                pass

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=measure_s + 60)
    elapsed = time.monotonic() - t_start
    with lock:
        closed[0] = True
        timed_out = sum(1 for f in all_futures if not f.done())
        sheds[0] += timed_out  # never completed within the drain budget
    return latencies, sheds[0], rows_done[0], elapsed


def run_serving_slo(
    deadline=None,
    *,
    n_features=N_FEATURES,
    n_entities=N_ENTITIES,
    local_dim=LOCAL_DIM,
    row_nnz=ROW_NNZ,
    max_batch=MAX_BATCH,
    rates=(100, 300, 900),
    queue_depths=(256, 2048),
    measure_s=4.0,
    n_clients=4,
    detail_out=None,
) -> dict:
    """The offered-load SLO sweep + disturbance window. Returns
    ``{metric: value-or-None}`` (None = budget-truncated). ``detail_out``
    (a dict, optional) receives the full per-cell grid and window
    accounting for the JSON ``detail`` field."""
    import jax

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.optim.factory import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.serving import (
        ContinuousBatcher,
        ModelRegistry,
        NearlineUpdater,
        publish_version,
    )

    results: dict = {m: None for m in SLO_METRICS}
    detail = detail_out if detail_out is not None else {}
    detail["simulated_on_cpu"] = jax.devices()[0].platform == "cpu"
    detail["grid"] = []
    if deadline is not None and deadline - time.monotonic() < 20:
        return results

    rng = np.random.default_rng(1)
    index_maps = {"global": [f"f{j}" for j in range(n_features)]}
    registry_dir = tempfile.mkdtemp(prefix="bench-serving-slo-")
    registry = None
    try:
        publish_version(
            registry_dir,
            build_model(n_features, n_entities, local_dim, seed=0),
            index_maps,
        )
        registry = ModelRegistry(
            registry_dir, max_batch=max_batch, max_row_nnz=row_nnz + 8,
            poll_interval=3600.0,  # swaps are triggered explicitly below
        )
        registry.start()
        pool = [
            make_rows(rng, 4, n_features, n_entities, row_nnz)
            for _ in range(256)
        ]

        def scorer(rows):
            engine = registry.engine
            return engine.score_rows(rows), engine.version

        # -- the offered-load sweep ------------------------------------------
        best = None  # (rate, cell) with shed fraction inside budget
        compiles_before = telemetry.snapshot()["counters"].get(
            "jit_compiles", 0
        )
        for queue_depth in queue_depths:
            for rate in rates:
                if deadline is not None and (
                    deadline - time.monotonic() < measure_s + 10
                ):
                    detail["grid_truncated"] = True
                    break
                batcher = ContinuousBatcher(
                    scorer, max_batch=max_batch, queue_depth=queue_depth
                ).start()
                latencies, sheds, rows_done, elapsed = _open_loop_cell(
                    batcher, pool, rate, measure_s, n_clients
                )
                batcher.stop()
                lat = np.sort(np.asarray([x[1] for x in latencies]))
                requests = len(latencies) + sheds
                cell = {
                    "queue_depth": queue_depth,
                    "offered_rate": rate,
                    "requests": requests,
                    "p50_ms": _percentile(lat, 0.50),
                    "p99_ms": _percentile(lat, 0.99),
                    "shed_fraction": (
                        round(sheds / requests, 4) if requests else None
                    ),
                    "rows_per_sec": (
                        round(rows_done / elapsed, 1) if elapsed > 0 else None
                    ),
                }
                detail["grid"].append(cell)
                if (
                    cell["shed_fraction"] is not None
                    and cell["shed_fraction"] <= SHED_BUDGET
                    and (best is None or rate > best[0])
                ):
                    best = (rate, cell)
            else:
                continue
            break
        if best is not None:
            results["serving_slo_rows_per_sec"] = best[1]["rows_per_sec"]
            results["serving_slo_p99_ms"] = best[1]["p99_ms"]
            detail["sustained_rate"] = best[0]
            detail["shed_budget"] = SHED_BUDGET

        # -- disturbance window: hot swap + nearline update mid-traffic ------
        if deadline is None or deadline - time.monotonic() > 3 * measure_s:
            batcher = ContinuousBatcher(
                scorer, max_batch=max_batch, queue_depth=max(queue_depths)
            ).start()
            updater = NearlineUpdater(
                registry,
                id_name="memberId",
                config=OptimizerConfig(
                    max_iterations=8,
                    regularization=RegularizationContext(
                        reg_type=RegularizationType.L2
                    ),
                    regularization_weight=1.0,
                ),
                rows_per_solve=8,
            )
            def nearline_events():
                return [
                    {
                        "ids": {"memberId": int(i)},
                        "features": {"global": [[int(i % n_features), 1.0]]},
                        "label": 1.0,
                    }
                    for i in range(32)
                ]

            # warm the nearline solve traces OFF the measured window with
            # the same batch SHAPE the window applies (the same discipline
            # as engine.warmup(): production pre-compiles; measuring
            # first-compile as "update latency" would gate XLA compile
            # time, not the apply path)
            updater.submit(nearline_events())
            updater.flush()
            window_s = 3 * measure_s
            rate = detail.get("sustained_rate") or rates[0]
            marks: dict[str, float] = {}

            def disturber():
                t0 = time.monotonic()
                time.sleep(window_s / 3)
                marks["swap_start"] = time.monotonic() - t0
                publish_version(
                    registry_dir,
                    build_model(n_features, n_entities, local_dim, seed=7),
                    index_maps,
                )
                registry.refresh()  # load + warm + swap, off request path
                marks["swap_end"] = time.monotonic() - t0
                time.sleep(max(window_s * 2 / 3 - marks["swap_end"], 0))
                marks["nearline_start"] = time.monotonic() - t0
                updater.submit(nearline_events())
                updater.flush()
                marks["nearline_end"] = time.monotonic() - t0

            t_win = time.monotonic()
            d = threading.Thread(target=disturber, daemon=True)
            d.start()
            latencies, sheds, rows_done, elapsed = _open_loop_cell(
                batcher, pool, rate, window_s, n_clients
            )
            d.join(timeout=30)
            batcher.stop()

            def window_p99(lo, hi):
                sel = np.sort(np.asarray([
                    ms for t, ms in latencies
                    if lo <= (t - t_win) <= hi
                ]))
                return _percentile(sel, 0.99)

            steady_p99 = window_p99(0.0, marks.get("swap_start", window_s / 3))
            swap_p99 = window_p99(
                marks.get("swap_start", 0.0),
                marks.get("swap_end", window_s) + 0.5,
            )
            nl_p99 = window_p99(
                marks.get("nearline_start", 0.0),
                marks.get("nearline_end", window_s) + 0.5,
            )
            if steady_p99 and swap_p99:
                results["serving_slo_p99_swap_ratio"] = round(
                    swap_p99 / steady_p99, 3
                )
            if steady_p99 and nl_p99:
                results["serving_slo_p99_nearline_ratio"] = round(
                    nl_p99 / steady_p99, 3
                )
            if "nearline_end" in marks and "nearline_start" in marks:
                # submit -> applied-on-the-live-tables for THIS window's
                # batch (the update-lag histogram also spans the warmup
                # flush, so the window marks are the honest number)
                results["serving_nearline_apply_ms"] = round(
                    (marks["nearline_end"] - marks["nearline_start"])
                    * 1000.0,
                    3,
                )
            detail["window"] = {
                "seconds": round(elapsed, 2),
                "rate": rate,
                "marks_s": {k: round(v, 3) for k, v in marks.items()},
                "steady_p99_ms": steady_p99,
                "swap_p99_ms": swap_p99,
                "nearline_p99_ms": nl_p99,
                "sheds": sheds,
            }
        compiles_after = telemetry.snapshot()["counters"].get(
            "jit_compiles", 0
        )
        # compiles during the sweep come from the v2 engine warmup (off the
        # request path); the steady windows themselves must stay flat —
        # surfaced for the gate's reader rather than asserted here because
        # the swap window legitimately compiles the replacement engine
        detail["compiles_during_run"] = compiles_after - compiles_before
    finally:
        if registry is not None:
            registry.stop()
        shutil.rmtree(registry_dir, ignore_errors=True)
    return results


def run_trace_overhead(
    deadline=None,
    *,
    n_features=512,
    n_entities=2_000,
    local_dim=8,
    row_nnz=12,
    max_batch=32,
    requests_per_arm=250,
    blocks=2,
    detail_out=None,
) -> dict:
    """Request-tracing cost on the serving hot path:
    ``serving_trace_overhead_ratio`` = closed-loop wall clock with the
    request tracer ON (ring record + tail-sampling accounting per
    request) over the same traffic with ``requests.configure(enabled=
    False)``. 1.0 = tracing is free; the acceptance line is <= 1.05.
    Arms alternate in blocks so drift (frequency scaling, page cache)
    lands on both sides."""
    from photon_ml_tpu.serving import MicroBatcher, ScoringEngine
    from photon_ml_tpu.telemetry import requests as rq

    results: dict = {m: None for m in TRACE_OVERHEAD_METRICS}
    detail = detail_out if detail_out is not None else {}
    if deadline is not None and deadline - time.monotonic() < 30:
        return results
    if deadline is not None and deadline - time.monotonic() < 90:
        requests_per_arm = max(50, requests_per_arm // 4)
    rng = np.random.default_rng(3)
    engine = ScoringEngine(
        build_model(n_features, n_entities, local_dim, seed=2),
        max_batch=max_batch,
        max_row_nnz=row_nnz + 8,
        version="bench-trace",
    )
    engine.warmup()
    batcher = MicroBatcher(
        lambda rows: (engine.score_rows(rows), engine.version),
        max_batch=max_batch,
        max_delay_ms=0.5,
        queue_depth=4096,
    ).start()
    pool = [
        make_rows(rng, 4, n_features, n_entities, row_nnz)
        for _ in range(64)
    ]
    try:
        def arm(traced: bool, count: int) -> float:
            rq.configure(enabled=traced)
            t0 = time.monotonic()
            for i in range(count):
                # the server path: every request carries a ctx; with the
                # tracer disabled begin() returns None and the batcher's
                # bookkeeping short-circuits — that delta IS the metric
                fut = batcher.submit(
                    pool[i % len(pool)], ctx=rq.make_context()
                )
                fut.result(timeout=30)
            return time.monotonic() - t0

        arm(True, 32)   # warm both arms off the measured blocks
        arm(False, 32)
        traced_s = untraced_s = 0.0
        for _ in range(blocks):
            untraced_s += arm(False, requests_per_arm)
            traced_s += arm(True, requests_per_arm)
        if untraced_s > 0:
            results["serving_trace_overhead_ratio"] = round(
                traced_s / untraced_s, 4
            )
        total = requests_per_arm * blocks
        detail["trace_overhead"] = {
            "requests_per_arm": requests_per_arm,
            "blocks": blocks,
            "traced_s": round(traced_s, 4),
            "untraced_s": round(untraced_s, 4),
            "traced_us_per_req": round(traced_s / total * 1e6, 1),
            "untraced_us_per_req": round(untraced_s / total * 1e6, 1),
            "ring_dropped": rq.REQUESTS.dropped,
        }
    finally:
        batcher.stop()
        rq.configure(enabled=True)
        rq.reset()
    return results


def run_serving_fleet_bench(
    deadline=None,
    *,
    fleet_size=4,
    resize_to=8,
    traffic_seconds=32.0,
    detail_out=None,
) -> dict:
    """The shard-owning FLEET headline: a real ``fleet_size``-process
    ``cli serve --member`` fleet under sustained router traffic survives
    a mid-stream hard kill (``serving_fleet_kill_recovery_s`` =
    heartbeat detection + same-slot relaunch back to a complete epoch)
    and executes a live ``fleet_size -> resize_to -> fleet_size`` elastic
    resize through the stage/commit barrier —
    ``serving_fleet_p99_resize_ratio`` is p99 latency inside the resize
    windows over the undisturbed steady windows (1.0 = perfectly flat;
    the acceptance line is <= 1.1). Zero non-shed request failures is a
    hard requirement, not a metric."""
    import shutil as _shutil

    from photon_ml_tpu import faults
    from tools import fleet

    faults.warn_if_armed()
    results: dict = {m: None for m in FLEET_METRICS}
    detail = detail_out if detail_out is not None else {}
    # the full run needs every member warm twice (launch + resize)
    if deadline is not None and deadline - time.monotonic() < 60:
        return results
    workdir = tempfile.mkdtemp(prefix="bench-serving-fleet-")
    try:
        version_dir = fleet.make_serving_model(
            tempfile.mkdtemp(prefix="bench-fleet-reg-", dir=workdir),
            n_entities=48,
        )
        kill_after_s = 2.0
        grow_at = traffic_seconds * 0.35
        shrink_at = traffic_seconds * 0.65
        spec = fleet.ServingFleetSpec(
            workdir=workdir,
            model_dir=version_dir,
            fleet_size=fleet_size,
            traffic_seconds=traffic_seconds,
            traffic_hz=20.0,
            traffic_rows=8,
            traffic_features=(("global", 2), ("user", 2)),
            kill_member=1,
            kill_after_s=kill_after_s,
            relaunch=True,
            heartbeat_deadline_s=2.0,
            resizes=((grow_at, resize_to), (shrink_at, fleet_size)),
        )
        run = fleet.run_serving_fleet(spec)
        samples = run.get("samples") or []

        def p99(t_lo, t_hi):
            sel = np.sort(np.asarray(
                [ms for t, ms, _rows in samples if t_lo <= t < t_hi]
            ))
            return _percentile(sel, 0.99)

        kill = run.get("kill") or {}
        resize_windows = [
            (ev["resize"]["t_start"], ev["resize"]["t_swap"] + 0.5)
            for ev in run.get("events", [])
            if "resize" in ev and "t_swap" in ev["resize"]
        ]
        # steady = everything outside the kill outage and resize windows
        disturbed = list(resize_windows)
        if kill.get("t_kill") is not None:
            disturbed.append((
                kill["t_kill"],
                kill["t_kill"] + (kill.get("recovery_s") or 0.0) + 0.5,
            ))
        steady_lat = np.sort(np.asarray([
            ms for t, ms, _rows in samples
            if not any(lo <= t < hi for lo, hi in disturbed)
        ]))
        steady_p99 = _percentile(steady_lat, 0.99)
        resize_lat = np.sort(np.asarray([
            ms for t, ms, _rows in samples
            if any(lo <= t < hi for lo, hi in resize_windows)
        ]))
        resize_p99 = _percentile(resize_lat, 0.99)
        if steady_p99 and resize_p99:
            results["serving_fleet_p99_resize_ratio"] = round(
                resize_p99 / steady_p99, 3
            )
        if kill.get("recovery_s") is not None:
            results["serving_fleet_kill_recovery_s"] = kill["recovery_s"]
        detail["fleet"] = {
            "fleet_size": fleet_size,
            "resize_to": resize_to,
            "steady_p99_ms": steady_p99,
            "resize_p99_ms": resize_p99,
            "resize_windows_s": [
                [round(lo, 2), round(hi, 2)] for lo, hi in resize_windows
            ],
            "kill": kill,
            "routed_rows": run.get("routed_rows"),
            "degraded_scores": run.get("degraded_scores"),
            "degraded_fraction": run.get("degraded_fraction"),
            "request_failures": len(run.get("failures") or []),
            "rcs": run.get("rcs"),
            "ok": run.get("ok"),
        }
        if run.get("failures"):
            # a non-shed failure voids the headline: report no number
            # rather than a flat-looking p99 over a failing fleet
            results["serving_fleet_p99_resize_ratio"] = None
    finally:
        _shutil.rmtree(workdir, ignore_errors=True)
    return results


def main() -> int:
    from bench_suite import budget_deadline, truncated_line

    deadline = budget_deadline()
    if deadline is not None and deadline - time.monotonic() < 30:
        for metric in (SERVING_METRICS + SLO_METRICS
                       + TRACE_OVERHEAD_METRICS + FLEET_METRICS):
            print(truncated_line(metric), flush=True)
        return 0

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.serving import MicroBatcher, Overloaded, ScoringEngine

    telemetry.configure_from_env()
    rng = np.random.default_rng(1)
    engine = ScoringEngine(
        build_model(), max_batch=MAX_BATCH, max_row_nnz=ROW_NNZ + 8,
        version="bench",
    )
    engine.warmup()
    batcher = MicroBatcher(
        lambda rows: (engine.score_rows(rows), engine.version),
        max_batch=MAX_BATCH,
        max_delay_ms=2.0,
        queue_depth=4096,
    ).start()

    # pre-generated request pool so client threads do no numpy in-loop
    pool = [make_rows(rng, 4) for _ in range(256)]
    measure_s = MEASURE_S
    if deadline is not None:
        measure_s = min(measure_s, max(deadline - time.monotonic() - 10, 2.0))

    latencies: list[float] = []
    rows_done = [0]
    lock = threading.Lock()
    stop_at = time.monotonic() + measure_s
    compiles_before = telemetry.snapshot()["counters"].get("jit_compiles", 0)

    def client(seed: int) -> None:
        local_rng = np.random.default_rng(seed)
        while time.monotonic() < stop_at:
            rows = pool[int(local_rng.integers(0, len(pool)))]
            t0 = time.monotonic()
            try:
                fut = batcher.submit(rows)
                fut.result(timeout=30)
            except Overloaded:
                continue
            dt = (time.monotonic() - t0) * 1000.0
            with lock:
                latencies.append(dt)
                rows_done[0] += len(rows)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(N_CLIENTS)
    ]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=measure_s + 60)
    elapsed = time.monotonic() - t_start
    batcher.stop()
    compiles_after = telemetry.snapshot()["counters"].get("jit_compiles", 0)

    lat = np.sort(np.asarray(latencies))
    detail = {
        "requests": len(latencies),
        "clients": N_CLIENTS,
        "max_batch": MAX_BATCH,
        "seconds": round(elapsed, 2),
        "steady_state_compiles": compiles_after - compiles_before,
    }
    for metric, value in (
        ("serving_p50_ms", _percentile(lat, 0.50)),
        ("serving_p99_ms", _percentile(lat, 0.99)),
        ("serving_rows_per_sec",
         round(rows_done[0] / elapsed, 1) if elapsed > 0 else None),
    ):
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": "ms" if metric.endswith("_ms") else "rows/s",
                    "vs_baseline": None,
                    "detail": detail,
                }
            ),
            flush=True,
        )

    # -- the SLO sweep ---------------------------------------------------
    slo_detail: dict = {}
    slo = run_serving_slo(deadline=deadline, detail_out=slo_detail)
    for metric in SLO_METRICS:
        value = slo.get(metric)
        if value is None:
            print(truncated_line(metric), flush=True)
            continue
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": (
                        "ms" if metric.endswith("_ms")
                        else "ratio" if metric.endswith("_ratio")
                        else "rows/s"
                    ),
                    "vs_baseline": None,
                    "detail": slo_detail,
                }
            ),
            flush=True,
        )

    # -- request-tracing overhead ----------------------------------------
    trace_detail: dict = {}
    trace_metrics = run_trace_overhead(
        deadline=deadline, detail_out=trace_detail
    )
    for metric in TRACE_OVERHEAD_METRICS:
        value = trace_metrics.get(metric)
        if value is None:
            print(truncated_line(metric), flush=True)
            continue
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": "ratio",
                    "vs_baseline": None,
                    "detail": trace_detail,
                }
            ),
            flush=True,
        )

    # -- the shard-owning fleet headline ---------------------------------
    fleet_detail: dict = {}
    fleet_metrics = run_serving_fleet_bench(
        deadline=deadline, detail_out=fleet_detail
    )
    for metric in FLEET_METRICS:
        value = fleet_metrics.get(metric)
        if value is None:
            print(truncated_line(metric), flush=True)
            continue
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": (
                        "ratio" if metric.endswith("_ratio") else "s"
                    ),
                    "vs_baseline": None,
                    "detail": fleet_detail,
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
