"""Online-serving benchmark: steady-state latency + throughput at fixed
offered load.

Builds a synthetic GLMix model (FE 2K features + 20K-entity RE with K=16
local dims), compiles it into a ScoringEngine, warms every batch-size
bucket, then drives the MicroBatcher from closed-loop client threads for
a fixed measurement window. Emits BENCH-style JSON lines:

  serving_p50_ms / serving_p99_ms   steady-state request latency
  serving_rows_per_sec              scored rows per second

Latency is measured at the client (submit -> future resolved), so it
includes queue + padding + device time. ``PHOTON_BENCH_BUDGET_S`` caps
wall clock: an exhausted budget emits ``"truncated": true`` placeholder
lines per metric (bench_suite convention). The jit-compile counter is
asserted flat across the measurement window — a recompile in steady state
is a bug, not a slow run.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

SERVING_METRICS = (
    "serving_p50_ms",
    "serving_p99_ms",
    "serving_rows_per_sec",
)

N_FEATURES = 2_000
N_ENTITIES = 20_000
LOCAL_DIM = 16
ROW_NNZ = 24
MAX_BATCH = 64
N_CLIENTS = 8
MEASURE_S = 10.0


def build_model():
    import jax.numpy as jnp

    from photon_ml_tpu.game.models import (
        FixedEffectModel,
        GameModel,
        RandomEffectBucketModel,
        RandomEffectModel,
    )

    rng = np.random.default_rng(0)
    fe = FixedEffectModel(
        coefficients=jnp.asarray(
            rng.normal(size=N_FEATURES) * 0.1, jnp.float32
        ),
        shard_name="global",
    )
    n_buckets = 4
    entity_bucket = (np.arange(N_ENTITIES) % n_buckets).astype(np.int64)
    entity_pos = np.zeros(N_ENTITIES, np.int64)
    buckets = []
    for b in range(n_buckets):
        codes_b = np.nonzero(entity_bucket == b)[0]
        entity_pos[codes_b] = np.arange(len(codes_b))
        # each entity's local space: LOCAL_DIM sorted global feature ids
        proj = np.sort(
            rng.choice(N_FEATURES, size=(len(codes_b), LOCAL_DIM),
                       replace=True),
            axis=1,
        ).astype(np.int32)
        buckets.append(
            RandomEffectBucketModel(
                coefficients=jnp.asarray(
                    rng.normal(size=(len(codes_b), LOCAL_DIM)) * 0.1,
                    jnp.float32,
                ),
                projection=jnp.asarray(proj),
                entity_codes=jnp.asarray(codes_b, jnp.int32),
            )
        )
    re = RandomEffectModel(
        id_name="memberId",
        shard_name="global",
        buckets=tuple(buckets),
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        vocab=np.arange(N_ENTITIES),
    )
    return GameModel(task="logistic", models={"fixed": fe, "member": re})


def make_rows(rng, count):
    rows = []
    for _ in range(count):
        cols = np.sort(
            rng.choice(N_FEATURES, size=ROW_NNZ, replace=False)
        )
        vals = rng.normal(size=ROW_NNZ)
        rows.append(
            {
                "features": {
                    "global": [
                        [int(c), float(v)] for c, v in zip(cols, vals)
                    ]
                },
                "ids": {"memberId": int(rng.integers(0, N_ENTITIES))},
            }
        )
    return rows


def main() -> int:
    from bench_suite import budget_deadline, truncated_line

    deadline = budget_deadline()
    if deadline is not None and deadline - time.monotonic() < 30:
        for metric in SERVING_METRICS:
            print(truncated_line(metric), flush=True)
        return 0

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.serving import MicroBatcher, Overloaded, ScoringEngine

    telemetry.configure_from_env()
    rng = np.random.default_rng(1)
    engine = ScoringEngine(
        build_model(), max_batch=MAX_BATCH, max_row_nnz=ROW_NNZ + 8,
        version="bench",
    )
    engine.warmup()
    batcher = MicroBatcher(
        lambda rows: (engine.score_rows(rows), engine.version),
        max_batch=MAX_BATCH,
        max_delay_ms=2.0,
        queue_depth=4096,
    ).start()

    # pre-generated request pool so client threads do no numpy in-loop
    pool = [make_rows(rng, 4) for _ in range(256)]
    measure_s = MEASURE_S
    if deadline is not None:
        measure_s = min(measure_s, max(deadline - time.monotonic() - 10, 2.0))

    latencies: list[float] = []
    rows_done = [0]
    lock = threading.Lock()
    stop_at = time.monotonic() + measure_s
    compiles_before = telemetry.snapshot()["counters"].get("jit_compiles", 0)

    def client(seed: int) -> None:
        local_rng = np.random.default_rng(seed)
        while time.monotonic() < stop_at:
            rows = pool[int(local_rng.integers(0, len(pool)))]
            t0 = time.monotonic()
            try:
                fut = batcher.submit(rows)
                fut.result(timeout=30)
            except Overloaded:
                continue
            dt = (time.monotonic() - t0) * 1000.0
            with lock:
                latencies.append(dt)
                rows_done[0] += len(rows)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(N_CLIENTS)
    ]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=measure_s + 60)
    elapsed = time.monotonic() - t_start
    batcher.stop()
    compiles_after = telemetry.snapshot()["counters"].get("jit_compiles", 0)

    lat = np.sort(np.asarray(latencies))
    detail = {
        "requests": len(latencies),
        "clients": N_CLIENTS,
        "max_batch": MAX_BATCH,
        "seconds": round(elapsed, 2),
        "steady_state_compiles": compiles_after - compiles_before,
    }
    for metric, value in (
        ("serving_p50_ms",
         round(float(lat[int(0.50 * (len(lat) - 1))]), 3) if len(lat) else None),
        ("serving_p99_ms",
         round(float(lat[int(0.99 * (len(lat) - 1))]), 3) if len(lat) else None),
        ("serving_rows_per_sec",
         round(rows_done[0] / elapsed, 1) if elapsed > 0 else None),
    ):
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": value,
                    "unit": "ms" if metric.endswith("_ms") else "rows/s",
                    "vs_baseline": None,
                    "detail": detail,
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
