"""North-star end-to-end benchmark: the BASELINE.md headline pipeline as
ONE driver invocation at MovieLens-20M scale.

MovieLens-20M-shaped synthetic data (20M ratings, 138,493 users, 26,744
movies — the real dataset is not fetchable in this hermetic environment,
so labels are planted from a known GLMix model, which also gives the AUC a
ground-truth ceiling):

    generate -> write TrainingExampleAvro (native columnar writer)
      -> `cli train` (feature indexing -> ingest -> GLMix fit:
         FE + per-user RE + per-movie RE + factored MF -> validation AUC
         -> model + index-map save)
      -> `cli score` (model load -> ingest validation -> score ->
         ScoringResultAvro write -> AUC)

Reference analog: the reference's full-pipeline fixture test
(photon-client/src/integTest/.../cli/game/training/DriverTest.scala:75-411)
at Yahoo-music scale; here the same composition is proven at the
north-star's 20M rows on one chip.

Prints ONE JSON line: metric north_star_e2e, value = end-to-end pipeline
seconds (train driver + scoring driver; fixture generation/write are
bench infrastructure and reported separately in detail).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

N_ROWS = 20_000_000
N_VAL = 1_000_000
N_USERS = 138_493
N_MOVIES = 26_744
# Feature volumes are sized so the whole pipeline's device residency fits
# one 16 GB chip alongside the 4 coordinates (FE tiled layout + two dense
# RE bucket sets + the MF kron refit): ~2.5 GB of design data at 20M
# rows. Larger per-row feature budgets belong to the multi-host path.
FE_SPACE = 2_000  # movieFeatures id space
FE_NNZ = 4  # movieFeatures per movie
CTX = 4  # movieCtx / userCtx dims


def _generate(rng, n, movie_cols, movie_vals, emb_m, emb_u, w_g, a_u, b_m):
    """One split's rows: ids, label, and the three feature bags."""
    users = rng.integers(0, N_USERS, size=n)
    movies = rng.integers(0, N_MOVIES, size=n)

    # logit = w_g . movieFeatures + a_u . emb_m + b_m . emb_u
    logit = (
        np.einsum("ij,ij->i", movie_vals[movies], w_g[movie_cols[movies]])
        + np.einsum("ij,ij->i", emb_m[movies], a_u[users])
        + np.einsum("ij,ij->i", emb_u[users], b_m[movies])
    )
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)

    bags = {
        "movieFeatures": (
            np.arange(0, (n + 1) * FE_NNZ, FE_NNZ, dtype=np.int64),
            movie_cols[movies].reshape(-1).astype(np.int32),
            movie_vals[movies].reshape(-1).astype(np.float64),
        ),
        "movieCtx": (
            np.arange(0, (n + 1) * CTX, CTX, dtype=np.int64),
            np.tile(
                np.arange(FE_SPACE, FE_SPACE + CTX, dtype=np.int32), n
            ),
            emb_m[movies].reshape(-1).astype(np.float64),
        ),
        "userCtx": (
            np.arange(0, (n + 1) * CTX, CTX, dtype=np.int64),
            np.tile(
                np.arange(
                    FE_SPACE + CTX, FE_SPACE + 2 * CTX, dtype=np.int32
                ),
                n,
            ),
            emb_u[users].reshape(-1).astype(np.float64),
        ),
    }
    return users, movies, y, logit, bags


def _opt(opt_type="lbfgs", max_iterations=15):
    return {
        "type": opt_type,
        "max_iterations": max_iterations,
        "tolerance": 1e-7,
        "regularization": "l2",
        "regularization_weight": 1.0,
    }


def main():
    import shutil

    from photon_ml_tpu.utils import setup_logging

    setup_logging()  # phase timers (timed()) go to stderr for diagnosis
    workdir = tempfile.mkdtemp(prefix="northstar_")
    try:
        _run(workdir)
    finally:
        # the fixture is ~9 GB — never leave it behind for the next round
        shutil.rmtree(workdir, ignore_errors=True)


def _run(workdir):
    from photon_ml_tpu.data.avro import write_training_examples_fast

    rng = np.random.default_rng(0)
    t_gen0 = time.perf_counter()
    # static world: per-movie sparse features + ctx embeddings + truth
    movie_cols = rng.integers(
        0, FE_SPACE, size=(N_MOVIES, FE_NNZ)
    ).astype(np.int32)
    movie_vals = rng.normal(size=(N_MOVIES, FE_NNZ))
    emb_m = rng.normal(size=(N_MOVIES, CTX)) * 0.7
    emb_u = rng.normal(size=(N_USERS, CTX)) * 0.7
    w_g = rng.normal(size=FE_SPACE) * 0.4
    a_u = rng.normal(size=(N_USERS, CTX)) * 0.4
    b_m = rng.normal(size=(N_MOVIES, CTX)) * 0.4

    names = (
        [f"f{i}" for i in range(FE_SPACE)]
        + [f"mctx{j}" for j in range(CTX)]
        + [f"uctx{j}" for j in range(CTX)]
    )
    user_vocab = [str(u) for u in range(N_USERS)]
    movie_vocab = [str(m) for m in range(N_MOVIES)]

    paths = {}
    gen_s = write_s = 0.0
    for split, n in (("train", N_ROWS), ("val", N_VAL)):
        t0 = time.perf_counter()
        users, movies, y, logit, bags = _generate(
            rng, n, movie_cols, movie_vals, emb_m, emb_u, w_g, a_u, b_m
        )
        gen_s += time.perf_counter() - t0
        p = os.path.join(workdir, f"{split}.avro")
        t0 = time.perf_counter()
        write_training_examples_fast(
            p, y, bags, names,
            {"userId": (users, user_vocab), "movieId": (movies, movie_vocab)},
        )
        write_s += time.perf_counter() - t0
        paths[split] = p
        if split == "val":
            # ground-truth ceiling for the AUC the fit should approach
            order = np.argsort(logit)
            ranks = np.empty(n)
            ranks[order] = np.arange(1, n + 1)
            pos = y > 0.5
            n_pos, n_neg = int(pos.sum()), int((~pos).sum())
            auc_ceiling = (
                (ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                / (n_pos * n_neg)
            )
    gen_s, write_s = round(gen_s, 3), round(write_s, 3)
    t_fixture = time.perf_counter() - t_gen0

    model_out = os.path.join(workdir, "model")
    config = {
        "task": "logistic",
        "input": {
            "format": "avro",
            "paths": [paths["train"]],
            "feature_shards": {
                "movieFeatures": ["movieFeatures"],
                "movieCtx": ["movieCtx"],
                "userCtx": ["userCtx"],
            },
            "id_columns": ["userId", "movieId"],
        },
        "validation": {"paths": [paths["val"]]},
        "coordinates": {
            "fixed": {
                "type": "fixed_effect",
                "shard_name": "movieFeatures",
                "optimizer": _opt("lbfgs", 10),
            },
            "per-user": {
                "type": "random_effect",
                "shard_name": "movieCtx",
                "id_name": "userId",
                "optimizer": _opt("newton", 8),
                "active_rows_per_entity": 256,
            },
            "per-movie": {
                "type": "random_effect",
                "shard_name": "userCtx",
                "id_name": "movieId",
                "optimizer": _opt("newton", 8),
                "active_rows_per_entity": 256,
            },
            "mf": {
                "type": "factored_random_effect",
                "shard_name": "movieCtx",
                "id_name": "userId",
                "latent_dim": 2,
                "mf_iterations": 1,
                "optimizer": _opt("lbfgs", 8),
                "latent_optimizer": _opt("lbfgs", 8),
                # the kron refit is built from ACTIVE rows; a tight cap
                # bounds its nnz at rows_cap * users * dim * latent
                "active_rows_per_entity": 32,
            },
        },
        "num_iterations": 1,
        "evaluators": ["auc"],
        "output_dir": model_out,
    }

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.cli.train import run as train_run
    from photon_ml_tpu.cli.score import run as score_run

    # optional span JSONL / metrics flush via PHOTON_TRACE_OUT /
    # PHOTON_TELEMETRY_OUT; fetch + compile counters ride the JSON below
    # either way, so "upload+compile dominated" phases are quantified
    telemetry.configure_from_env()

    # an hours-scale pipeline must never be silent (BENCH_r05 timed out
    # with zero output): one progress line every 30s to stderr via the
    # progress logger, with span path + rows/s + HBM (train_run's own
    # heartbeat is redundant under ours — disabled to avoid double lines)
    config["heartbeat"] = False
    with telemetry.Heartbeat(interval=30.0):
        t0 = time.perf_counter()
        train_summary = train_run(config)
        train_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        score_summary = score_run(
            model_dir=os.path.join(model_out, "best"),
            input_spec={**config["input"], "paths": [paths["val"]]},
            output_path=os.path.join(workdir, "scores.avro"),
            evaluators=("auc",),
        )
        score_s = time.perf_counter() - t0

    import jax

    from photon_ml_tpu.telemetry.report import RunReport

    # roofline summary over the whole pipeline (None = no instrumented
    # executables ran / "unknown" cost fields on analysis-less backends):
    # MFU, bandwidth utilization, comms fraction, compile-time share, and
    # the top executables by cost — the attribution BENCH_r05 lacked
    device_util = RunReport.from_live().device_utilization()

    pipeline_s = train_s + score_s
    print(
        json.dumps(
            {
                "metric": "north_star_e2e",
                "value": round(pipeline_s, 1),
                "unit": "s",
                "vs_baseline": None,
                "detail": {
                    "rows_train": N_ROWS,
                    "rows_val": N_VAL,
                    "users": N_USERS,
                    "movies": N_MOVIES,
                    "train_driver_s": round(train_s, 1),
                    "score_driver_s": round(score_s, 1),
                    "fixture_generate_s": gen_s,
                    "fixture_write_s": write_s,
                    "fixture_total_s": round(t_fixture, 1),
                    "validation_auc": train_summary.get("best_metric"),
                    "auc_ceiling_planted": round(float(auc_ceiling), 4),
                    "scoring_auc": score_summary.get("metrics", {}).get(
                        "auc"
                    ),
                    "phases": [
                        {
                            k: (round(v, 2) if isinstance(v, float) else v)
                            for k, v in e.items()
                            if k in ("iteration", "coordinate", "seconds")
                        }
                        for e in train_summary.get("history", [])
                    ],
                    "platform": jax.devices()[0].platform,
                    # shared telemetry schema (counters of snapshot()):
                    # device_fetches / device_fetch_seconds expose the
                    # ~100ms tunnel tax, jit_compiles the recompile count
                    "telemetry": telemetry.snapshot()["counters"],
                    "device_utilization": device_util,
                },
            },
            default=float,
        )
    )

    trace_out = os.environ.get("PHOTON_TRACE_OUT")
    if trace_out:
        # run report beside the bench JSON: the phase-time tree and
        # fetch/compile accounting, readable without opening Perfetto
        import sys

        from photon_ml_tpu.telemetry.report import RunReport, report_path

        report = RunReport.from_live()
        # per-member suffixing in a fleet (matches the trace sink's path)
        md_path = report_path(telemetry.member_artifact_path(trace_out))
        with open(md_path, "w", encoding="utf-8") as fh:
            fh.write(report.to_markdown())
        report.save_json(md_path[: -len(".md")] + ".json")
        print(f"run report: {md_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
