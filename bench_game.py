"""GAME benchmark: GLMix (fixed effect + per-user random effect) logistic
training throughput on one chip — BASELINE.md config #4.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: MovieLens-1M-shaped synthetic — 1M rows, a 10K-feature sparse FE
shard (~20 nnz/row, trained on the tiled one-hot-matmul pallas fast path)
plus a 10-feature per-user RE shard over 100K users (vmapped bucket solves).
Metric = model coefficients trained per second: every coordinate update
trains its full coefficient set (FE features + sum of per-entity local
dimensions), times CD iterations, over the wall-clock of fit(). The
reference publishes no numbers (BASELINE.json "published": {}), so
vs_baseline is null.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax

    from photon_ml_tpu.game import (
        FixedEffectConfig,
        GameConfig,
        GameEstimator,
        RandomEffectConfig,
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.ops.sparse import SparseBatch
    from photon_ml_tpu.optim import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )

    n_rows = 1_000_000
    n_users = 100_000
    fe_features = 10_000
    fe_nnz_per_row = 20
    re_features = 10
    cd_iterations = 2

    rng = np.random.default_rng(0)

    # --- fixed-effect shard: sparse 1M x 10K ---
    nnz = n_rows * fe_nnz_per_row
    fe_rows = np.repeat(np.arange(n_rows, dtype=np.int64), fe_nnz_per_row)
    fe_cols = rng.integers(0, fe_features, size=nnz)
    fe_vals = rng.normal(size=nnz)
    w_true = rng.normal(size=fe_features) * 0.5

    # --- random-effect shard: dense 10 features per row, 100K users ---
    users = rng.integers(0, n_users, size=n_rows)
    Xu = rng.normal(size=(n_rows, re_features))
    wu_true = rng.normal(size=(n_users, re_features)) * 0.5

    margins = np.zeros(n_rows)
    np.add.at(margins, fe_rows, fe_vals * w_true[fe_cols])
    margins += np.einsum("ij,ij->i", Xu, wu_true[users])
    y = (rng.random(n_rows) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float64)

    fe_batch = SparseBatch.from_coo(
        values=fe_vals, rows=fe_rows, cols=fe_cols, labels=y,
        num_features=fe_features,
    )
    ru_rows, ru_cols = np.nonzero(Xu)
    re_batch = SparseBatch.from_coo(
        values=Xu[ru_rows, ru_cols], rows=ru_rows, cols=ru_cols, labels=y,
        num_features=re_features,
    )
    gds = build_game_dataset(
        response=y,
        feature_shards={"global": fe_batch, "user": re_batch},
        id_columns={"userId": users},
    )

    from photon_ml_tpu.optim import OptimizerType

    opt = OptimizerConfig(
        max_iterations=20,
        tolerance=0.0,
        regularization=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )
    # per-entity solves use the batched-Newton fast path (explicit [K,K]
    # Hessians on the MXU): same optima, ~5x fewer sequential loop steps
    # than vmapped LBFGS for these tiny local dims
    import dataclasses as _dc

    re_opt = _dc.replace(
        opt, optimizer_type=OptimizerType.NEWTON, tolerance=1e-7
    )
    config = GameConfig(
        task="logistic",
        coordinates={
            "fixed": FixedEffectConfig(shard_name="global", optimizer=opt),
            "per-user": RandomEffectConfig(
                shard_name="user", id_name="userId", optimizer=re_opt),
        },
        num_iterations=cd_iterations,
    )

    # count trainable coefficients: FE features + per-entity local dims
    t_build0 = time.perf_counter()
    red = build_random_effect_dataset(gds, "userId", "user")
    build_s = time.perf_counter() - t_build0
    re_coeffs = sum(
        b.num_entities * b.num_local_features for b in red.buckets
    )
    total_coeffs = fe_features + re_coeffs

    est = GameEstimator(config)
    # warmup/compile: tiny prefix of the same structure is NOT possible
    # (shapes differ) — instead run one full fit and time the second, which
    # hits every jit cache (fresh coefficients still solved from zero).
    est.fit(gds)

    t0 = time.perf_counter()
    result = est.fit(gds)
    # sync: fetch scalars from the final model (block_until_ready is a no-op
    # through the tunnel; see PERF_NOTES.md)
    fe_w = np.asarray(result.model.models["fixed"].coefficients)
    elapsed = time.perf_counter() - t0

    coeffs_per_sec = total_coeffs * cd_iterations / elapsed

    print(
        json.dumps(
            {
                "metric": "glmix_fe_re_logistic_1Mx100Kusers_coeffs_per_sec",
                "value": round(coeffs_per_sec, 1),
                "unit": "coeffs/s",
                "vs_baseline": None,
                "detail": {
                    "elapsed_s": round(elapsed, 3),
                    "re_build_s": round(build_s, 3),
                    "total_coeffs": int(total_coeffs),
                    "cd_iterations": cd_iterations,
                    "n_rows": n_rows,
                    "n_users": n_users,
                    "fe_final_norm": float(np.linalg.norm(fe_w)),
                    "platform": jax.devices()[0].platform,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
