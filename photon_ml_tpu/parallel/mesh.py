"""Device-mesh construction and the stacked-shard batch layout.

The MODERN mesh vocabulary is the named (``batch``, ``model``) GSPMD pair
in ``parallel.sharding`` (flat designs committed with NamedSharding, jit
inserts the collectives). This module keeps:

  - :func:`make_mesh` — mesh construction for any axis names;
  - the legacy 1-D axis names (``data`` for fixed-effect rows, ``entity``
    for per-entity batches, SURVEY.md §2.f), which the sharding helpers
    still resolve;
  - :func:`shard_rows` / :func:`put_sharded` — the stacked shard layout
    ([num_shards, ...] leaves with LOCAL row indices) that multi-host
    workers assemble from process-local rows and feed to
    ``distributed_solve`` (flattened back inside the jit);
  - :func:`shard_map_compat` — the cross-version ``shard_map`` shim, for
    callers that genuinely need explicit SPMD.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.sparse import SparseBatch, _round_up

DATA_AXIS = "data"
ENTITY_AXIS = "entity"


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across jax versions: newer jax exposes it at the
    top level with ``check_vma``; older releases only ship
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Every
    framework shard_map goes through here so the distributed solvers run
    on both."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check,
            )
        except TypeError:  # older keyword spelling on this jax
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=check,
            )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


def make_mesh(
    axis_sizes: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a mesh; default is a 1-D data mesh over all devices."""
    devices = list(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(
            f"mesh {dict(axis_sizes)} needs {total} devices, have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(sizes)
    # baseline per-device HBM gauges at mesh build (no-op on statless
    # backends): the run report's memory section starts from what the
    # fleet already held before training allocated anything
    from photon_ml_tpu.telemetry import memory as telemetry_memory

    telemetry_memory.record_device_memory(devices)
    return Mesh(arr, names)


def shard_rows(batch: SparseBatch, num_shards: int) -> SparseBatch:
    """Host-side: split a batch into ``num_shards`` equal row blocks with
    LOCAL row indices, stacked on a new leading axis.

    The result's leaves have shape [num_shards, ...]; feed it to shard_map
    with in_specs P(axis) (the leading axis is consumed by the mesh), or
    vmap for testing. Row blocks are contiguous (rows are already sorted),
    nnz is padded to the max shard nnz.
    """
    import jax.numpy as jnp

    n = batch.num_rows
    rows_per = _round_up(n, num_shards) // num_shards
    rows_np = np.asarray(batch.rows)
    vals_np = np.asarray(batch.values)
    cols_np = np.asarray(batch.cols)

    # valid (non-padding) nnz mask: padding points at last row with value 0
    shard_of_nnz = np.minimum(rows_np // rows_per, num_shards - 1)

    shards = []
    for s in range(num_shards):
        sel = (shard_of_nnz == s) & (vals_np != 0)
        local_rows = rows_np[sel] - s * rows_per
        lo, hi = s * rows_per, min((s + 1) * rows_per, n)
        count = max(hi - lo, 0)

        def pad_to(a, total, fill=0.0):
            out = np.full((total,), fill, dtype=np.asarray(a).dtype)
            out[: len(a)] = np.asarray(a)
            return out

        labels = pad_to(np.asarray(batch.labels)[lo:hi], rows_per)
        offsets = pad_to(np.asarray(batch.offsets)[lo:hi], rows_per)
        weights = pad_to(np.asarray(batch.weights)[lo:hi], rows_per)
        shards.append(
            dict(
                values=vals_np[sel],
                rows=local_rows,
                cols=cols_np[sel],
                labels=labels,
                offsets=offsets,
                weights=weights,
            )
        )

    nnz_max = max(len(s["values"]) for s in shards)
    nnz_max = max(nnz_max, 1)

    stacked = {}
    for key, fill in (
        ("values", 0.0),
        ("rows", None),
        ("cols", 0),
        ("labels", 0.0),
        ("offsets", 0.0),
        ("weights", 0.0),
    ):
        parts = []
        for s in shards:
            a = s[key]
            if key in ("values", "rows", "cols"):
                f = rows_per - 1 if key == "rows" else (fill or 0)
                out = np.full((nnz_max,), f, dtype=a.dtype if len(a) else np.int64)
                out[: len(a)] = a
                parts.append(out)
            else:
                parts.append(a)
        stacked[key] = np.stack(parts)

    return SparseBatch(
        values=jnp.asarray(stacked["values"], batch.dtype),
        rows=jnp.asarray(stacked["rows"], jnp.int32),
        cols=jnp.asarray(stacked["cols"], jnp.int32),
        labels=jnp.asarray(stacked["labels"], batch.dtype),
        offsets=jnp.asarray(stacked["offsets"], batch.dtype),
        weights=jnp.asarray(stacked["weights"], batch.dtype),
        num_features=batch.num_features,
    )


def put_sharded(stacked, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a host-stacked batch (any layout pytree with a leading shard
    axis on every leaf) so shard i's block lives on device i."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)
