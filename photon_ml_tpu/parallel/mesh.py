"""Device-mesh construction and batch sharding helpers.

The framework's mesh vocabulary (SURVEY.md §2.f):
  - axis ``data``:   examples sharded for fixed-effect (DP) training
  - axis ``entity``: per-entity problem batches sharded for random-effect
                     ("expert-parallel"-like) training
Both can coexist in a 2-D mesh on larger slices; collectives ride ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.sparse import SparseBatch, _round_up

DATA_AXIS = "data"
ENTITY_AXIS = "entity"


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across jax versions: newer jax exposes it at the
    top level with ``check_vma``; older releases only ship
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Every
    framework shard_map goes through here so the distributed solvers run
    on both."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check,
            )
        except TypeError:  # older keyword spelling on this jax
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=check,
            )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


def make_mesh(
    axis_sizes: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a mesh; default is a 1-D data mesh over all devices."""
    devices = list(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(
            f"mesh {dict(axis_sizes)} needs {total} devices, have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(sizes)
    # baseline per-device HBM gauges at mesh build (no-op on statless
    # backends): the run report's memory section starts from what the
    # fleet already held before training allocated anything
    from photon_ml_tpu.telemetry import memory as telemetry_memory

    telemetry_memory.record_device_memory(devices)
    return Mesh(arr, names)


def shard_rows(batch: SparseBatch, num_shards: int) -> SparseBatch:
    """Host-side: split a batch into ``num_shards`` equal row blocks with
    LOCAL row indices, stacked on a new leading axis.

    The result's leaves have shape [num_shards, ...]; feed it to shard_map
    with in_specs P(axis) (the leading axis is consumed by the mesh), or
    vmap for testing. Row blocks are contiguous (rows are already sorted),
    nnz is padded to the max shard nnz.
    """
    import jax.numpy as jnp

    n = batch.num_rows
    rows_per = _round_up(n, num_shards) // num_shards
    rows_np = np.asarray(batch.rows)
    vals_np = np.asarray(batch.values)
    cols_np = np.asarray(batch.cols)

    # valid (non-padding) nnz mask: padding points at last row with value 0
    shard_of_nnz = np.minimum(rows_np // rows_per, num_shards - 1)

    shards = []
    for s in range(num_shards):
        sel = (shard_of_nnz == s) & (vals_np != 0)
        local_rows = rows_np[sel] - s * rows_per
        lo, hi = s * rows_per, min((s + 1) * rows_per, n)
        count = max(hi - lo, 0)

        def pad_to(a, total, fill=0.0):
            out = np.full((total,), fill, dtype=np.asarray(a).dtype)
            out[: len(a)] = np.asarray(a)
            return out

        labels = pad_to(np.asarray(batch.labels)[lo:hi], rows_per)
        offsets = pad_to(np.asarray(batch.offsets)[lo:hi], rows_per)
        weights = pad_to(np.asarray(batch.weights)[lo:hi], rows_per)
        shards.append(
            dict(
                values=vals_np[sel],
                rows=local_rows,
                cols=cols_np[sel],
                labels=labels,
                offsets=offsets,
                weights=weights,
            )
        )

    nnz_max = max(len(s["values"]) for s in shards)
    nnz_max = max(nnz_max, 1)

    stacked = {}
    for key, fill in (
        ("values", 0.0),
        ("rows", None),
        ("cols", 0),
        ("labels", 0.0),
        ("offsets", 0.0),
        ("weights", 0.0),
    ):
        parts = []
        for s in shards:
            a = s[key]
            if key in ("values", "rows", "cols"):
                f = rows_per - 1 if key == "rows" else (fill or 0)
                out = np.full((nnz_max,), f, dtype=a.dtype if len(a) else np.int64)
                out[: len(a)] = a
                parts.append(out)
            else:
                parts.append(a)
        stacked[key] = np.stack(parts)

    return SparseBatch(
        values=jnp.asarray(stacked["values"], batch.dtype),
        rows=jnp.asarray(stacked["rows"], jnp.int32),
        cols=jnp.asarray(stacked["cols"], jnp.int32),
        labels=jnp.asarray(stacked["labels"], batch.dtype),
        offsets=jnp.asarray(stacked["offsets"], batch.dtype),
        weights=jnp.asarray(stacked["weights"], batch.dtype),
        num_features=batch.num_features,
    )


def put_sharded(stacked, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a host-stacked batch (any layout pytree with a leading shard
    axis on every leaf) so shard i's block lives on device i."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)


def shard_tiles(tiled, num_shards: int):
    """Host-side: split a TiledBatch into ``num_shards`` contiguous tile
    groups stacked on a new leading axis (the tiled analog of shard_rows —
    tiles are independent, so any contiguous grouping is a valid row shard).

    Tile count is padded to a multiple of ``num_shards`` with empty tiles
    (vals 0, hi = num_blocks sentinel so gathers contribute nothing,
    weights 0).
    """
    import jax.numpy as jnp

    from photon_ml_tpu.ops.tiled import TiledBatch

    T = tiled.num_tiles
    Tp = _round_up(T, num_shards)
    per = Tp // num_shards

    def stack(x, fill):
        a = np.asarray(x)
        if Tp != T:
            pad = np.full((Tp - T,) + a.shape[1:], fill, a.dtype)
            a = np.concatenate([a, pad], axis=0)
        return jnp.asarray(a.reshape((num_shards, per) + a.shape[1:]))

    return TiledBatch(
        vals=stack(tiled.vals, 0.0),
        hi=stack(tiled.hi, tiled.num_blocks),
        lo=stack(tiled.lo, 0),
        rlo=stack(tiled.rlo, 0),
        labels3=stack(tiled.labels3, 0.0),
        offsets3=stack(tiled.offsets3, 0.0),
        weights3=stack(tiled.weights3, 0.0),
        num_features=tiled.num_features,
    )
