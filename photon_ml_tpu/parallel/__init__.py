from photon_ml_tpu.parallel.distributed import (  # noqa: F401
    distributed_solve,
    distributed_value_and_grad,
)
from photon_ml_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ENTITY_AXIS,
    make_mesh,
    put_sharded,
    shard_rows,
)
from photon_ml_tpu.parallel.multihost import (  # noqa: F401
    DistributedConfig,
    gather_to_host,
    global_mesh,
    host_local_array,
    initialize,
    is_multiprocess,
    process_slice,
)
