from photon_ml_tpu.parallel.distributed import (  # noqa: F401
    distributed_solve,
    distributed_value_and_grad,
    gspmd_solve,
)
from photon_ml_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ENTITY_AXIS,
    make_mesh,
    put_sharded,
    shard_rows,
)
from photon_ml_tpu.parallel.sharding import (  # noqa: F401
    BATCH_AXIS,
    MODEL_AXIS,
    batch_sharding,
    data_axis,
    entity_sharding,
    model_axis,
    place_batch,
    place_entities,
    replicated,
)
from photon_ml_tpu.parallel.multihost import (  # noqa: F401
    DistributedConfig,
    gather_to_host,
    global_mesh,
    host_local_array,
    initialize,
    is_multiprocess,
    process_slice,
)
