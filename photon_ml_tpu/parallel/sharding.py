"""Reusable GSPMD sharding primitives: named axes, placement helpers, and
entity sharding for coefficient tables.

The framework's modern mesh vocabulary (ROADMAP item 1; SNIPPETS [3] shows
the pattern):

  - axis ``batch``: examples sharded for data-parallel fixed-effect
    training — the tiled design and margins carry
    ``NamedSharding(mesh, P("batch", ...))`` and ``jax.jit`` inserts the
    psums (GSPMD), replacing per-solve ``shard_map`` plumbing;
  - axis ``model``: per-entity state (random-effect coefficient tables,
    streamed entity chunks) sharded so table capacity scales with devices.

The legacy 1-D axis names ``data``/``entity`` (parallel.mesh) resolve to
the same roles, so older meshes keep working. This module is a LIBRARY
surface: online serving (ROADMAP item 4) reuses :func:`entity_sharding`
for mesh-spanning model state, so keep it free of training-only concerns.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"
MODEL_AXIS = "model"

#: Axis names recognized as the example/row (data-parallel) axis, most
#: preferred first. "data" is the legacy 1-D spelling.
_DATA_AXES = (BATCH_AXIS, "data")
#: Axis names recognized as the per-entity (model-parallel) axis.
_MODEL_AXES = (MODEL_AXIS, "entity")


def data_axis(mesh: Mesh) -> Optional[str]:
    """The mesh's example-sharding axis name (``batch``/legacy ``data``),
    or None when the mesh has no such axis (an entity-only mesh)."""
    for name in _DATA_AXES:
        if name in mesh.axis_names:
            return name
    return None


def model_axis(mesh: Mesh) -> Optional[str]:
    """The mesh's entity-sharding axis name (``model``/legacy ``entity``),
    or None when the mesh has no such axis (a batch-only mesh)."""
    for name in _MODEL_AXES:
        if name in mesh.axis_names:
            return name
    return None


def axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape[axis])


def batch_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Sharding for per-row arrays ([n] labels/offsets/weights, [T, ...]
    tile grids, [nnz] COO slots): leading dim split over the batch axis,
    everything else replicated. ``P(axis)`` is a prefix spec, so one
    sharding serves every rank."""
    axis = axis or data_axis(mesh)
    if axis is None:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no batch/data axis to shard rows "
            "over"
        )
    return NamedSharding(mesh, P(axis))


def entity_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Sharding for per-entity state ([E, K] coefficient tables, [E, ...]
    chunk batches): the leading entity dim split over the model axis.

    This is the ONE definition of how entity state spans the mesh —
    the streaming coefficient table, the RE bucket solves, and (ROADMAP
    item 4) sharded serving all place through it, so their shards line up
    with no resharding between training and serving."""
    axis = axis or model_axis(mesh)
    if axis is None:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no model/entity axis to shard "
            "entities over"
        )
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement (broadcast analog) on ``mesh``."""
    return NamedSharding(mesh, P())


def pad_count(n: int, shards: int) -> int:
    """Smallest multiple of ``shards`` that is >= ``n``."""
    return -(-int(n) // int(shards)) * int(shards)


def place_entities(tree, mesh: Mesh, axis: Optional[str] = None):
    """Place every leaf of an entity-leading pytree ([E, ...] per leaf)
    with :func:`entity_sharding`. E must be a multiple of the axis size
    (see :func:`pad_count` / game.coordinates._pad_entities)."""
    sharding = entity_sharding(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def place_replicated(tree, mesh: Mesh):
    """Replicate every leaf of a pytree across the whole mesh."""
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


# ---------------------------------------------------------------------------
# batch placement: flat (non-stacked) designs onto the batch axis
# ---------------------------------------------------------------------------


def pad_batch_rows(batch, shards: int):
    """Host-side: pad a batch's row structure so every leading dim divides
    over ``shards`` — the flat-GSPMD analog of parallel.mesh.shard_rows
    (which additionally re-stacks; GSPMD needs no stacking).

    SparseBatch: pads rows (weight 0 -> inert) and nnz slots (value 0,
    row = last row -> inert). TiledBatch: pads whole tiles (weights 0,
    ``hi`` = num_blocks sentinel so gathers contribute nothing).
    """
    import jax.numpy as jnp

    from photon_ml_tpu.ops.sparse import SparseBatch
    from photon_ml_tpu.ops.tiled import TiledBatch

    if isinstance(batch, TiledBatch):
        T = batch.num_tiles
        Tp = pad_count(T, shards)
        if Tp == T:
            return batch

        def pad_tiles(x, fill):
            a = np.asarray(x)
            pad = np.full((Tp - T,) + a.shape[1:], fill, a.dtype)
            return jnp.asarray(np.concatenate([a, pad], axis=0))

        return TiledBatch(
            vals=pad_tiles(batch.vals, 0.0),
            hi=pad_tiles(batch.hi, batch.num_blocks),
            lo=pad_tiles(batch.lo, 0),
            rlo=pad_tiles(batch.rlo, 0),
            labels3=pad_tiles(batch.labels3, 0.0),
            offsets3=pad_tiles(batch.offsets3, 0.0),
            weights3=pad_tiles(batch.weights3, 0.0),
            num_features=batch.num_features,
        )
    if isinstance(batch, SparseBatch):
        n, nnz = batch.num_rows, batch.nnz
        n_p, nnz_p = pad_count(n, shards), pad_count(nnz, shards)
        if n_p == n and nnz_p == nnz:
            return batch

        def pad_to(x, total, fill):
            a = np.asarray(x)
            out = np.full((total,) + a.shape[1:], fill, a.dtype)
            out[: a.shape[0]] = a
            return jnp.asarray(out)

        return SparseBatch(
            values=pad_to(batch.values, nnz_p, 0.0),
            rows=pad_to(batch.rows, nnz_p, n_p - 1),
            cols=pad_to(batch.cols, nnz_p, 0),
            labels=pad_to(batch.labels, n_p, 0.0),
            offsets=pad_to(batch.offsets, n_p, 0.0),
            weights=pad_to(batch.weights, n_p, 0.0),
            num_features=batch.num_features,
        )
    raise TypeError(f"cannot pad batch type {type(batch).__name__}")


def place_batch(batch, mesh: Mesh, axis: Optional[str] = None):
    """Pad (:func:`pad_batch_rows`) and upload a flat design so its rows
    live sharded over the batch axis: every leaf gets
    ``NamedSharding(mesh, P(axis))`` on its leading dim. The returned
    batch feeds :func:`photon_ml_tpu.parallel.distributed.gspmd_solve`
    directly — the whole optimizer while-loop then runs under one jit with
    GSPMD-inserted psums."""
    axis = axis or data_axis(mesh)
    sharding = batch_sharding(mesh, axis)
    padded = pad_batch_rows(batch, axis_size(mesh, axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), padded)
