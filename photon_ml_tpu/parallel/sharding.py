"""Reusable GSPMD sharding primitives: named axes, placement helpers, and
entity sharding for coefficient tables.

The framework's modern mesh vocabulary (ROADMAP item 1; SNIPPETS [3] shows
the pattern):

  - axis ``batch``: examples sharded for data-parallel fixed-effect
    training — the tiled design and margins carry
    ``NamedSharding(mesh, P("batch", ...))`` and ``jax.jit`` inserts the
    psums (GSPMD), replacing per-solve ``shard_map`` plumbing;
  - axis ``model``: per-entity state (random-effect coefficient tables,
    streamed entity chunks) sharded so table capacity scales with devices.

The legacy 1-D axis names ``data``/``entity`` (parallel.mesh) resolve to
the same roles, so older meshes keep working. This module is a LIBRARY
surface: online serving (ROADMAP item 4) reuses :func:`entity_sharding`
for mesh-spanning model state, so keep it free of training-only concerns.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"
MODEL_AXIS = "model"

#: Axis names recognized as the example/row (data-parallel) axis, most
#: preferred first. "data" is the legacy 1-D spelling.
_DATA_AXES = (BATCH_AXIS, "data")
#: Axis names recognized as the per-entity (model-parallel) axis.
_MODEL_AXES = (MODEL_AXIS, "entity")


def data_axis(mesh: Mesh) -> Optional[str]:
    """The mesh's example-sharding axis name (``batch``/legacy ``data``),
    or None when the mesh has no such axis (an entity-only mesh)."""
    for name in _DATA_AXES:
        if name in mesh.axis_names:
            return name
    return None


def model_axis(mesh: Mesh) -> Optional[str]:
    """The mesh's entity-sharding axis name (``model``/legacy ``entity``),
    or None when the mesh has no such axis (a batch-only mesh)."""
    for name in _MODEL_AXES:
        if name in mesh.axis_names:
            return name
    return None


def axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape[axis])


def batch_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Sharding for per-row arrays ([n] labels/offsets/weights, [T, ...]
    tile grids, [nnz] COO slots): leading dim split over the batch axis,
    everything else replicated. ``P(axis)`` is a prefix spec, so one
    sharding serves every rank."""
    axis = axis or data_axis(mesh)
    if axis is None:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no batch/data axis to shard rows "
            "over"
        )
    return NamedSharding(mesh, P(axis))


def entity_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Sharding for per-entity state ([E, K] coefficient tables, [E, ...]
    chunk batches): the leading entity dim split over the model axis.

    This is the ONE definition of how entity state spans the mesh —
    the streaming coefficient table, the RE bucket solves, and (ROADMAP
    item 4) sharded serving all place through it, so their shards line up
    with no resharding between training and serving."""
    axis = axis or model_axis(mesh)
    if axis is None:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no model/entity axis to shard "
            "entities over"
        )
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement (broadcast analog) on ``mesh``."""
    return NamedSharding(mesh, P())


def pad_count(n: int, shards: int) -> int:
    """Smallest multiple of ``shards`` that is >= ``n``."""
    return -(-int(n) // int(shards)) * int(shards)


def place_entities(tree, mesh: Mesh, axis: Optional[str] = None):
    """Place every leaf of an entity-leading pytree ([E, ...] per leaf)
    with :func:`entity_sharding`. E must be a multiple of the axis size
    (see :func:`pad_count` / game.coordinates._pad_entities)."""
    sharding = entity_sharding(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def place_replicated(tree, mesh: Mesh):
    """Replicate every leaf of a pytree across the whole mesh."""
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


class ElasticPlacementError(ValueError):
    """The TARGET topology cannot hold this table (entity count does not
    divide over the mesh's model axis) — a configuration error, distinct
    from checkpoint corruption: restore must surface it, never skip past
    valid checkpoints because of it."""


def valid_entity_axis_sizes(num_entities: int) -> list[int]:
    """The axis sizes ``num_entities`` divides over, capped at the device
    count — the LEGAL topologies an operator can actually pick."""
    return [
        d for d in range(1, min(int(num_entities), jax.device_count()) + 1)
        if num_entities % d == 0
    ]


def entity_axis_mismatch(
    num_entities: int, axis: str, size: int, what: str = "re-place elastically"
) -> ElasticPlacementError:
    """The ONE formatting of the indivisible-entity-axis error: an operator
    picking a mesh (elastic restore after host loss, a serving mesh) needs
    the valid sizes listed, not a modulus. Shared by checkpoint restore
    (:func:`place_entity_rows`) and the sharded serving engine."""
    return ElasticPlacementError(
        f"num_entities={num_entities} must divide over the "
        f"{size}-device '{axis}' axis to {what}; valid "
        f"target axis sizes for this table: "
        f"{valid_entity_axis_sizes(num_entities)}"
    )


# ---------------------------------------------------------------------------
# serving-fleet ownership: entity code -> owning member, pure math
# ---------------------------------------------------------------------------

#: An upper bound on how many valid fleet sizes get LISTED in the
#: indivisible-fleet error (the sizes themselves are unbounded).
_FLEET_SIZE_LISTING_CAP = 64


def valid_fleet_sizes(num_entities: int) -> list[int]:
    """Fleet sizes ``num_entities`` divides over — the serving analog of
    :func:`valid_entity_axis_sizes`, deliberately NOT capped at the
    device count: fleet members are processes (often hosts), and the
    whole point of the fleet is holding a table no one device set can."""
    n = int(num_entities)
    return [
        d for d in range(1, min(n, _FLEET_SIZE_LISTING_CAP) + 1)
        if n % d == 0
    ]


def fleet_size_mismatch(
    num_entities: int, num_members: int, what: str = "slice the serving fleet"
) -> ElasticPlacementError:
    """The indivisible-fleet error, formatted like
    :func:`entity_axis_mismatch`: the operator picking a fleet size needs
    the sizes that CAN hold the table, not a modulus."""
    return ElasticPlacementError(
        f"num_entities={num_entities} must divide over a "
        f"{num_members}-member serving fleet to {what}; valid "
        f"fleet sizes for this table: {valid_fleet_sizes(num_entities)}"
    )


def member_row_range(
    num_entities: int, member: int, num_members: int
) -> tuple[int, int]:
    """The contiguous entity-code block ``[lo, hi)`` serving-fleet member
    ``member`` of ``num_members`` owns — a pure function of the fleet
    size alone (the ``plans_for_host`` discipline): every member and the
    router compute the SAME ownership from ``(num_entities,
    num_members)`` with no coordination, and a resize is just re-running
    it at the new size. Contiguous blocks line up with the streamed
    checkpoint's row ranges, so a member restore is one
    ``read_rows(lo, hi)`` over the mmap'd shards."""
    num_entities, num_members = int(num_entities), int(num_members)
    if num_members < 1:
        raise ValueError(f"num_members must be >= 1, got {num_members}")
    if not 0 <= int(member) < num_members:
        raise ValueError(
            f"member {member} outside fleet of {num_members}"
        )
    if num_entities % num_members:
        raise fleet_size_mismatch(num_entities, num_members)
    per = num_entities // num_members
    return int(member) * per, (int(member) + 1) * per


def owner_of_row(num_entities: int, row: int, num_members: int) -> int:
    """The member owning entity code ``row`` — the router-side inverse of
    :func:`member_row_range` (same divisibility contract)."""
    num_entities, num_members = int(num_entities), int(num_members)
    if num_entities % num_members:
        raise fleet_size_mismatch(num_entities, num_members)
    if not 0 <= int(row) < num_entities:
        raise ValueError(
            f"entity code {row} outside table of {num_entities}"
        )
    return int(row) // (num_entities // num_members)


def place_entity_rows(
    read_rows,
    num_entities: int,
    tail_shape: tuple,
    dtype,
    mesh: Optional[Mesh] = None,
    axis: Optional[str] = None,
):
    """Build an entity-sharded ``[E, *tail_shape]`` array from a
    row-range reader WITHOUT materializing the full table on any host.

    ``read_rows(lo, hi)`` returns host rows ``[lo, hi)`` (e.g. slices of
    memory-mapped checkpoint shard files). With a mesh, each device's
    shard is requested independently through
    ``jax.make_array_from_callback`` — peak host residency is one device
    shard, which is what makes ELASTIC checkpoint restore (written on an
    8-device mesh, restored onto 4, or 1) safe for tables that only fit
    sharded. Without a mesh the whole range is read and placed on the
    default device (the caller asserted it fits).

    This is the restore-side complement of :func:`entity_sharding`:
    row ranges re-slice over whatever model axis the TARGET mesh has, so
    a checkpoint's provenance mesh never constrains where it can resume.
    """
    shape = (int(num_entities),) + tuple(int(d) for d in tail_shape)
    # Two aliasing hazards on this path, both host-copy lessons from the
    # ingest uploader. (1) ``read_rows`` serves views of MEMORY-MAPPED
    # checkpoint files, and CPU device_put MAY zero-copy an aligned host
    # array — so every placement gets a fresh owned ndarray, never a
    # mapped view. (2) Even that owned copy is only BORROWED by jax:
    # device_put/make_array_from_callback keep the numpy buffer rather
    # than copying into an XLA-owned allocation. A downstream DONATED
    # update (ShardedCoefficientTable chunk writes) then aliases borrowed
    # memory that is freed when the donated input dies — one device's
    # shard turns into freed-heap garbage, timing-dependent (reproduced
    # under the warm persistent compile cache). ``_owned_copy`` launders
    # the result through a non-donating jitted copy, whose outputs XLA
    # allocates and owns, before anything can donate it.
    if mesh is None:
        import jax.numpy as jnp

        return _owned_copy(
            jnp.asarray(
                np.array(read_rows(0, shape[0]), dtype=dtype, copy=True)
            )
        )
    sharding = entity_sharding(mesh, axis)
    if shape[0] % axis_size(mesh, sharding.spec[0]):
        raise entity_axis_mismatch(
            shape[0], sharding.spec[0],
            axis_size(mesh, sharding.spec[0]),
        )

    def callback(index):
        row_slice = index[0]
        lo = row_slice.start or 0
        hi = shape[0] if row_slice.stop is None else row_slice.stop
        chunk = np.asarray(read_rows(lo, hi))
        return np.array(
            chunk[(slice(None),) + tuple(index[1:])], dtype=dtype,
            copy=True,
        )

    return _owned_copy(
        jax.make_array_from_callback(shape, sharding, callback)
    )


def _owned_copy(array):
    """Copy ``array`` into buffers XLA allocated and owns (sharding
    preserved — the copy is per-device, no cross-device traffic). Without
    donation an executable's outputs can never alias its inputs, so the
    result is safe to hand to donating updates no matter where the input
    buffers came from.

    This function (with :func:`place_entity_rows`) is a registered L017
    SANITIZER: the dataflow gate treats its result as owned and stops
    tracking borrowed host memory through it. Renaming it fails the gate
    with W002 (``tools/analysis/dataflow.py::COPY_SANITIZERS``) rather
    than silently laundering nothing."""
    from photon_ml_tpu import telemetry  # lazy: keep sharding importable solo

    global _OWNED_COPY_JIT
    if _OWNED_COPY_JIT is None:
        import jax.numpy as jnp

        # multi_shape: one executable per (table shape, sharding) by
        # design — placements are once-per-restore, not hot
        _OWNED_COPY_JIT = telemetry.instrumented_jit(
            jnp.copy, name="place_entity_rows_copy", multi_shape=True
        )
    return _OWNED_COPY_JIT(array)


_OWNED_COPY_JIT = None


# ---------------------------------------------------------------------------
# batch placement: flat (non-stacked) designs onto the batch axis
# ---------------------------------------------------------------------------


def pad_batch_rows(batch, shards: int):
    """Host-side: pad a batch's row structure so every leading dim divides
    over ``shards`` — the flat-GSPMD analog of parallel.mesh.shard_rows
    (which additionally re-stacks; GSPMD needs no stacking).

    SparseBatch: pads rows (weight 0 -> inert) and nnz slots (value 0,
    row = last row -> inert). TiledBatch: pads whole tiles (weights 0,
    ``hi`` = num_blocks sentinel so gathers contribute nothing).
    """
    import jax.numpy as jnp

    from photon_ml_tpu.ops.sparse import SparseBatch
    from photon_ml_tpu.ops.tiled import TiledBatch

    if isinstance(batch, TiledBatch):
        T = batch.num_tiles
        Tp = pad_count(T, shards)
        if Tp == T:
            return batch

        def pad_tiles(x, fill):
            a = np.asarray(x)
            pad = np.full((Tp - T,) + a.shape[1:], fill, a.dtype)
            return jnp.asarray(np.concatenate([a, pad], axis=0))

        return TiledBatch(
            vals=pad_tiles(batch.vals, 0.0),
            hi=pad_tiles(batch.hi, batch.num_blocks),
            lo=pad_tiles(batch.lo, 0),
            rlo=pad_tiles(batch.rlo, 0),
            labels3=pad_tiles(batch.labels3, 0.0),
            offsets3=pad_tiles(batch.offsets3, 0.0),
            weights3=pad_tiles(batch.weights3, 0.0),
            num_features=batch.num_features,
        )
    if isinstance(batch, SparseBatch):
        n, nnz = batch.num_rows, batch.nnz
        n_p, nnz_p = pad_count(n, shards), pad_count(nnz, shards)
        if n_p == n and nnz_p == nnz:
            return batch

        def pad_to(x, total, fill):
            a = np.asarray(x)
            out = np.full((total,) + a.shape[1:], fill, a.dtype)
            out[: a.shape[0]] = a
            return jnp.asarray(out)

        return SparseBatch(
            values=pad_to(batch.values, nnz_p, 0.0),
            rows=pad_to(batch.rows, nnz_p, n_p - 1),
            cols=pad_to(batch.cols, nnz_p, 0),
            labels=pad_to(batch.labels, n_p, 0.0),
            offsets=pad_to(batch.offsets, n_p, 0.0),
            weights=pad_to(batch.weights, n_p, 0.0),
            num_features=batch.num_features,
        )
    raise TypeError(f"cannot pad batch type {type(batch).__name__}")


def place_batch(batch, mesh: Mesh, axis: Optional[str] = None):
    """Pad (:func:`pad_batch_rows`) and upload a flat design so its rows
    live sharded over the batch axis: every leaf gets
    ``NamedSharding(mesh, P(axis))`` on its leading dim. The returned
    batch feeds :func:`photon_ml_tpu.parallel.distributed.gspmd_solve`
    directly — the whole optimizer while-loop then runs under one jit with
    GSPMD-inserted psums."""
    axis = axis or data_axis(mesh)
    sharding = batch_sharding(mesh, axis)
    padded = pad_batch_rows(batch, axis_size(mesh, axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), padded)
