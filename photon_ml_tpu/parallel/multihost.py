"""Multi-host (multi-process) execution: jax.distributed wiring and
per-process sharded data placement.

Reference analog: the reference's defining trait is spanning a *cluster of
machines* — ``SparkContextConfiguration.asYarnClient`` provisions a YARN
app over many hosts (/root/reference/photon-api/src/main/scala/com/linkedin/
photon/ml/SparkContextConfiguration.scala:40-107), and the "hundreds of
billions of coefficients" claim (/root/reference/README.md:73) only fits in
many machines' memory. The TPU-native answer:

  - ONE JAX process per host, connected through jax.distributed's
    coordination service (the GRPC analog of the Spark driver<->executor
    control plane). On a TPU pod slice, ``jax.distributed.initialize()``
    auto-detects everything from the TPU environment; off-pod (CPU fleets,
    tests) the coordinator address / process count / process id come from
    :class:`DistributedConfig` or ``PHOTON_ML_*`` env vars.
  - A GLOBAL :class:`~jax.sharding.Mesh` spans every process's devices
    (``jax.devices()`` is process-major). Collectives ride ICI inside a
    slice and DCN across slices — XLA picks the transport; nothing in the
    framework changes between one host and many.
  - Each process ingests and uploads ONLY its own row/entity range
    (:func:`process_slice`, :func:`host_local_array` — built on
    ``jax.make_array_from_process_local_data``). That is the analog of the
    reference's executor-local partition reads + the bin-packing
    entity->partition placement (RandomEffectDataSetPartitioner.scala:42-148):
    entity ranges are contiguous per process, so a process's table shard is
    co-located with the data it ingested, and per-entity solves stay
    collective-free.

Tested without TPU hardware by ``__graft_entry__.dryrun_multichip``: two
OS processes x four virtual CPU devices each form one 8-device global mesh
(gloo CPU collectives), and the streamed sharded-table fit matches the
single-process 8-device run bit-for-tolerance.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu import faults

logger = logging.getLogger("photon_ml_tpu.parallel.multihost")

_ENV_COORDINATOR = "PHOTON_ML_COORDINATOR"
_ENV_NUM_PROCESSES = "PHOTON_ML_NUM_PROCESSES"
_ENV_PROCESS_ID = "PHOTON_ML_PROCESS_ID"
_ENV_AUTO = "PHOTON_ML_AUTO_DISTRIBUTED"
_ENV_INIT_RETRIES = "PHOTON_ML_INIT_RETRIES"

_initialized = False

# fleet fault seams (photon_ml_tpu.faults): distributed init (an `exit`
# rule here is a member preempted before it ever joined; `raise`/`io`
# rules are the flaky-gloo shape the bounded retry absorbs) and the
# heartbeat touch (an `exit` rule is a member dying between collectives —
# the supervisor sees the stale proc-<i>.alive file, not an exit hook)
_FP_INIT = faults.register_point(
    "multihost.init", distributed=True,
    description="jax.distributed.initialize attempt (retried with backoff)",
)
_FP_HEARTBEAT = faults.register_point(
    "fleet.heartbeat", distributed=True,
    description="one liveness-file touch by the heartbeat writer thread",
)


class FleetInitError(RuntimeError):
    """jax.distributed initialization failed every attempt; carries the
    coordinator address so the operator knows WHICH rendezvous died."""

    def __init__(self, coordinator: Optional[str], attempts: int, last: Exception):
        self.coordinator = coordinator
        super().__init__(
            f"could not join the fleet at coordinator "
            f"{coordinator or '<auto-detected>'} after {attempts} "
            f"attempt(s): {last}"
        )


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Where this process sits in the fleet.

    Three modes:
      - all fields default: no-op — single host, nothing to join;
      - ``auto=True``: ``jax.distributed.initialize()`` with no arguments —
        the TPU-pod path, where topology/coordinator come from the TPU
        runtime environment;
      - explicit ``coordinator_address`` + ``num_processes`` +
        ``process_id``: CPU/GPU fleets and multi-process tests.
    """

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    local_device_ids: Optional[tuple[int, ...]] = None
    auto: bool = False  # TPU-pod auto-detection
    #: bounded retry around flaky gloo/grpc rendezvous: total attempts =
    #: 1 + init_retries, exponential backoff starting at init_backoff_s
    init_retries: int = 3
    init_backoff_s: float = 0.5

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        addr = os.environ.get(_ENV_COORDINATOR)
        nproc = os.environ.get(_ENV_NUM_PROCESSES)
        pid = os.environ.get(_ENV_PROCESS_ID)
        auto = os.environ.get(_ENV_AUTO, "").lower() in ("1", "true", "yes")
        retries = os.environ.get(_ENV_INIT_RETRIES)
        return cls(
            coordinator_address=addr,
            num_processes=int(nproc) if nproc else None,
            process_id=int(pid) if pid else None,
            auto=auto,
            init_retries=int(retries) if retries else 3,
        )

    @property
    def is_explicit(self) -> bool:
        return self.coordinator_address is not None

    def validate(self) -> None:
        if self.auto and self.is_explicit:
            raise ValueError(
                "auto=True (pod auto-detection) conflicts with an explicit "
                "coordinator_address"
            )
        if self.is_explicit:
            if self.num_processes is None or self.process_id is None:
                raise ValueError(
                    "distributed config with a coordinator_address needs "
                    "num_processes and process_id too"
                )
            if not (0 <= self.process_id < self.num_processes):
                raise ValueError(
                    f"process_id {self.process_id} out of range for "
                    f"{self.num_processes} processes"
                )
        elif self.num_processes is not None and self.num_processes > 1:
            raise ValueError(
                "num_processes > 1 needs either a coordinator_address "
                "(explicit fleet) or auto=True (TPU pod)"
            )


def _init_attempts(cfg: DistributedConfig, attempt_fn) -> None:
    """Bounded-retry driver around one initialize attempt: transient
    rendezvous failures (grpc refused, gloo handshake flakes — surfaced
    by jax as RuntimeError/OSError) back off exponentially and count
    ``multihost.init_retries``; exhaustion raises the typed
    :class:`FleetInitError` naming the coordinator."""
    from photon_ml_tpu import telemetry

    attempts = max(int(cfg.init_retries), 0) + 1
    last: Optional[Exception] = None
    for attempt in range(attempts):
        if attempt:
            telemetry.counter("multihost.init_retries").inc()
            backoff = cfg.init_backoff_s * (2 ** (attempt - 1))
            logger.warning(
                "distributed init failed (%s); retry %d/%d in %.2fs",
                last, attempt, attempts - 1, backoff,
            )
            time.sleep(backoff)
        try:
            faults.fault_point(_FP_INIT)
            attempt_fn()
            return
        except (RuntimeError, OSError, ConnectionError, TimeoutError) as e:
            last = e
    assert last is not None
    raise FleetInitError(cfg.coordinator_address, attempts, last)


def initialize(config: Optional[DistributedConfig] = None) -> None:
    """Connect this process to the fleet (idempotent).

    Must run before the first jax computation. Single-process callers may
    skip it entirely; :func:`global_mesh` works either way. Transient
    rendezvous failures are retried ``config.init_retries`` times with
    exponential backoff (``multihost.init_retries`` counted); exhaustion
    raises :class:`FleetInitError` naming the coordinator address.
    """
    global _initialized
    if _initialized:
        return
    cfg = config if config is not None else DistributedConfig.from_env()
    cfg.validate()
    if cfg.is_explicit:
        # explicit fleets are CPU/GPU hosts; the CPU backend only executes
        # cross-process programs (GSPMD collectives) through gloo, and the
        # default is "none" — without this every multi-process CPU
        # computation dies with "Multiprocess computations aren't
        # implemented on the CPU backend"
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — unknown option on other jax versions
            pass
        _init_attempts(
            cfg,
            lambda: jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                local_device_ids=cfg.local_device_ids,
            ),
        )
        _initialized = True
    elif cfg.auto:
        # TPU pod: topology/coordinator come from the TPU runtime env.
        _init_attempts(cfg, jax.distributed.initialize)
        _initialized = True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def global_mesh(
    axis_sizes: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh over ALL processes' devices, process-major on the leading axis.

    ``jax.devices()`` orders devices by process index, so a 1-D mesh (or the
    leading axis of a 2-D one) assigns each process a CONTIGUOUS block of
    that axis — the property :func:`process_slice` relies on for co-locating
    entity table shards with per-process ingestion.
    """
    from photon_ml_tpu.parallel.mesh import make_mesh

    return make_mesh(axis_sizes, devices=devices)


def process_slice(total: int, mesh: Mesh, axis: str) -> tuple[int, int]:
    """[lo, hi) range of global rows this process owns when an array of
    ``total`` rows is sharded evenly over ``axis`` of ``mesh``.

    Requires the mesh's ``axis`` to be process-major (true for
    :func:`global_mesh`) and ``total`` divisible by the axis size.
    """
    axis_size = mesh.shape[axis]
    if total % axis_size:
        raise ValueError(
            f"total={total} must divide over the {axis_size}-device "
            f"'{axis}' axis"
        )
    per_device = total // axis_size
    # devices along `axis` for fixed other-axis coordinates; process-major
    axes = list(mesh.axis_names)
    dev_grid = np.moveaxis(mesh.devices, axes.index(axis), 0)
    dev_line = dev_grid.reshape(dev_grid.shape[0], -1)[:, 0]
    mine = [i for i, d in enumerate(dev_line) if d.process_index == jax.process_index()]
    if not mine:
        return (0, 0)
    if mine != list(range(mine[0], mine[-1] + 1)):
        raise ValueError(
            f"devices of process {jax.process_index()} are not contiguous "
            f"along axis '{axis}'; use global_mesh() ordering"
        )
    return (mine[0] * per_device, (mine[-1] + 1) * per_device)


def host_local_array(
    local: np.ndarray,
    mesh: Mesh,
    spec: P,
    global_shape: Optional[tuple[int, ...]] = None,
) -> jax.Array:
    """Assemble a global sharded array from this process's LOCAL rows.

    ``local`` holds only the rows this process owns (its
    :func:`process_slice` of the leading axis). Single-process, this is just
    a sharded device_put. Multi-process, no host ever materializes the
    global array — the Spark-free analog of an RDD whose partitions live
    where they were read.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(
        sharding, local, global_shape=global_shape
    )


def replicate_to_all(value: np.ndarray, mesh: Mesh) -> jax.Array:
    """Replicate a host value identically across every process's devices
    (broadcast analog). All processes must pass the same value."""
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(value), global_shape=np.shape(value)
    )


# ---------------------------------------------------------------------------
# fleet liveness: heartbeat files + supervisor-side staleness detection
# ---------------------------------------------------------------------------

#: heartbeat file name for one fleet member
def heartbeat_path(directory: str, process_id: int) -> str:
    return os.path.join(directory, f"proc-{int(process_id)}.alive")


class HeartbeatWriter:
    """Touch ``proc-<i>.alive`` on a cadence from a daemon thread.

    The liveness signal is the file's MTIME, so detection needs only a
    shared filesystem — no RPC with a process that may already be dead.
    ``os._exit`` (a real preemption, or the ``fleet.heartbeat`` exit
    rule) kills this thread with the process, and the file goes stale;
    a supervisor reading :func:`dead_peers` sees the member as dead once
    staleness exceeds its deadline. Python-thread cadence jitter is why
    deadlines should be several intervals long.
    """

    def __init__(self, directory: str, process_id: int,
                 interval_s: float = 1.0):
        if interval_s <= 0:
            raise ValueError("heartbeat interval_s must be > 0")
        self.path = heartbeat_path(directory, process_id)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """One touch (also called inline by the worker loop so a BLOCKED
        main thread with a live writer thread still counts as alive —
        liveness means "the process exists", progress is telemetry's
        job)."""
        faults.fault_point(_FP_HEARTBEAT)
        with open(self.path, "a"):
            os.utime(self.path, None)

    def start(self) -> "HeartbeatWriter":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.beat()
        self._thread = threading.Thread(
            target=self._run, name="fleet-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError as e:  # a torn-down workdir must not kill the run
                logger.warning("heartbeat touch failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 4)


def dead_peers(
    directory: str,
    num_processes: int,
    deadline_s: float,
    now: Optional[float] = None,
) -> list[int]:
    """Process ids whose heartbeat file is STALE beyond ``deadline_s``.

    A missing file does NOT count dead — the member may not have reached
    its first beat yet (the supervisor pairs this with exit-code
    watching, which catches members that die before beating)."""
    # wall clock by necessity: staleness is measured against file MTIMES,
    # which are wall-clock — monotonic time has no common epoch with them
    now = time.time() if now is None else now  # photon: noqa[L006]
    dead = []
    for pid in range(int(num_processes)):
        try:
            mtime = os.path.getmtime(heartbeat_path(directory, pid))
        except OSError:
            continue
        if now - mtime > deadline_s:
            dead.append(pid)
    return dead


@contextmanager
def collective_wait(label: str):
    """Time this process's blocking entry into a cross-process collective
    and record it as collective-WAIT telemetry: a ``collective_wait`` span
    (attrs ``label``/``wait_s``) plus the ``comms.wait_s`` histogram and
    ``comms.wait_calls``/``comms.wait_seconds_total`` counters.

    The point is fleet attribution, not bandwidth: at a barrier the LAST
    member to arrive waits ~zero while everyone else's clock runs — so
    the member whose total wait is near zero is the straggler the rest of
    the fleet stood around for (telemetry.fleet_report names it from
    exactly these counters). Single-process, the context is a no-op:
    there is nobody to wait for, and recording zeros would pollute the
    comms accounting.

    Honesty limits (README "Fleet observability"): the window covers the
    host-side dispatch of the collective program; where jax dispatches
    asynchronously the enqueue returns early and the residue lands on the
    next blocking fetch. The per-boundary ``fleet_any`` stop collective —
    which ends in a host fetch — is always a true barrier measurement.
    """
    if jax.process_count() == 1:
        yield
        return
    from photon_ml_tpu import telemetry

    t0 = time.monotonic()
    with telemetry.span("collective_wait", label=label) as s:
        try:
            yield
        finally:
            wait = time.monotonic() - t0
            s.set_attr(wait_s=round(wait, 6))
            telemetry.histogram("comms.wait_s").observe(wait)
            telemetry.counter("comms.wait_calls").inc()
            telemetry.counter("comms.wait_seconds_total").inc(wait)


def fleet_any(flag: bool, mesh: Optional[Mesh] = None,
              axis: Optional[str] = None) -> bool:
    """Fleet-consistent OR of a per-process bool — the agreement that
    makes boundary stops CLEAN across a fleet.

    A stop request (SIGTERM) lands on ONE member; if each member read
    only its local flag, the signaled member would stop at boundary K
    while a peer that checked a moment earlier sails into chunk K+1's
    collective and blocks forever against a stopped partner. Reducing
    the flag through a tiny mesh collective makes every member see the
    SAME verdict at the SAME boundary (SPMD programs run in lockstep),
    so all members stop — and write their coordinated final checkpoint —
    together. Single-process (or no mesh): just the local flag."""
    if mesh is None or jax.process_count() == 1:
        return bool(flag)
    from photon_ml_tpu.parallel import sharding as psharding

    resolved = axis or psharding.model_axis(mesh) or psharding.data_axis(mesh)
    if resolved is None:
        resolved = mesh.axis_names[0]
    n = psharding.axis_size(mesh, resolved)
    lo, hi = process_slice(n, mesh, resolved)
    local = np.full((hi - lo,), 1.0 if flag else 0.0, np.float32)
    arr = host_local_array(local, mesh, P(resolved), global_shape=(n,))
    # the stop collective is the fleet's per-boundary barrier: the fetch
    # below blocks until EVERY member has contributed its flag, so the
    # elapsed time is this member's true wait on its slowest peer — the
    # straggler-attribution signal the fleet report aggregates
    with collective_wait("fleet_any"):
        reduced = _fleet_any_program(mesh)(arr)
        value = float(np.asarray(reduced.addressable_data(0)))
    return bool(value > 0.0)


_FLEET_ANY_CACHE: dict = {}


def _fleet_any_program(mesh: Mesh):
    prog = _FLEET_ANY_CACHE.get(mesh)
    if prog is None:
        import jax.numpy as jnp

        prog = jax.jit(
            jnp.max, out_shardings=NamedSharding(mesh, P())
        )
        _FLEET_ANY_CACHE[mesh] = prog
    return prog


def gather_to_host(arr: jax.Array) -> np.ndarray:
    """Fetch a (possibly cross-process) sharded array fully to every host.

    Single-process this is a plain np.asarray. Multi-process it reshards to
    fully-replicated (XLA all-gather over ICI/DCN) and reads the local
    copy, so use it for summaries/models, not bulk data.
    """
    if jax.process_count() == 1 or getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    mesh = arr.sharding.mesh
    replicated = jax.jit(
        lambda x: x, out_shardings=NamedSharding(mesh, P())
    )(arr)
    return np.asarray(replicated.addressable_data(0))
