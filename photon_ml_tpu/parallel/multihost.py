"""Multi-host (multi-process) execution: jax.distributed wiring and
per-process sharded data placement.

Reference analog: the reference's defining trait is spanning a *cluster of
machines* — ``SparkContextConfiguration.asYarnClient`` provisions a YARN
app over many hosts (/root/reference/photon-api/src/main/scala/com/linkedin/
photon/ml/SparkContextConfiguration.scala:40-107), and the "hundreds of
billions of coefficients" claim (/root/reference/README.md:73) only fits in
many machines' memory. The TPU-native answer:

  - ONE JAX process per host, connected through jax.distributed's
    coordination service (the GRPC analog of the Spark driver<->executor
    control plane). On a TPU pod slice, ``jax.distributed.initialize()``
    auto-detects everything from the TPU environment; off-pod (CPU fleets,
    tests) the coordinator address / process count / process id come from
    :class:`DistributedConfig` or ``PHOTON_ML_*`` env vars.
  - A GLOBAL :class:`~jax.sharding.Mesh` spans every process's devices
    (``jax.devices()`` is process-major). Collectives ride ICI inside a
    slice and DCN across slices — XLA picks the transport; nothing in the
    framework changes between one host and many.
  - Each process ingests and uploads ONLY its own row/entity range
    (:func:`process_slice`, :func:`host_local_array` — built on
    ``jax.make_array_from_process_local_data``). That is the analog of the
    reference's executor-local partition reads + the bin-packing
    entity->partition placement (RandomEffectDataSetPartitioner.scala:42-148):
    entity ranges are contiguous per process, so a process's table shard is
    co-located with the data it ingested, and per-entity solves stay
    collective-free.

Tested without TPU hardware by ``__graft_entry__.dryrun_multichip``: two
OS processes x four virtual CPU devices each form one 8-device global mesh
(gloo CPU collectives), and the streamed sharded-table fit matches the
single-process 8-device run bit-for-tolerance.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_ENV_COORDINATOR = "PHOTON_ML_COORDINATOR"
_ENV_NUM_PROCESSES = "PHOTON_ML_NUM_PROCESSES"
_ENV_PROCESS_ID = "PHOTON_ML_PROCESS_ID"
_ENV_AUTO = "PHOTON_ML_AUTO_DISTRIBUTED"

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Where this process sits in the fleet.

    Three modes:
      - all fields default: no-op — single host, nothing to join;
      - ``auto=True``: ``jax.distributed.initialize()`` with no arguments —
        the TPU-pod path, where topology/coordinator come from the TPU
        runtime environment;
      - explicit ``coordinator_address`` + ``num_processes`` +
        ``process_id``: CPU/GPU fleets and multi-process tests.
    """

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    local_device_ids: Optional[tuple[int, ...]] = None
    auto: bool = False  # TPU-pod auto-detection

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        addr = os.environ.get(_ENV_COORDINATOR)
        nproc = os.environ.get(_ENV_NUM_PROCESSES)
        pid = os.environ.get(_ENV_PROCESS_ID)
        auto = os.environ.get(_ENV_AUTO, "").lower() in ("1", "true", "yes")
        return cls(
            coordinator_address=addr,
            num_processes=int(nproc) if nproc else None,
            process_id=int(pid) if pid else None,
            auto=auto,
        )

    @property
    def is_explicit(self) -> bool:
        return self.coordinator_address is not None

    def validate(self) -> None:
        if self.auto and self.is_explicit:
            raise ValueError(
                "auto=True (pod auto-detection) conflicts with an explicit "
                "coordinator_address"
            )
        if self.is_explicit:
            if self.num_processes is None or self.process_id is None:
                raise ValueError(
                    "distributed config with a coordinator_address needs "
                    "num_processes and process_id too"
                )
            if not (0 <= self.process_id < self.num_processes):
                raise ValueError(
                    f"process_id {self.process_id} out of range for "
                    f"{self.num_processes} processes"
                )
        elif self.num_processes is not None and self.num_processes > 1:
            raise ValueError(
                "num_processes > 1 needs either a coordinator_address "
                "(explicit fleet) or auto=True (TPU pod)"
            )


def initialize(config: Optional[DistributedConfig] = None) -> None:
    """Connect this process to the fleet (idempotent).

    Must run before the first jax computation. Single-process callers may
    skip it entirely; :func:`global_mesh` works either way.
    """
    global _initialized
    if _initialized:
        return
    cfg = config if config is not None else DistributedConfig.from_env()
    cfg.validate()
    if cfg.is_explicit:
        # explicit fleets are CPU/GPU hosts; the CPU backend only executes
        # cross-process programs (GSPMD collectives) through gloo, and the
        # default is "none" — without this every multi-process CPU
        # computation dies with "Multiprocess computations aren't
        # implemented on the CPU backend"
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — unknown option on other jax versions
            pass
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
            local_device_ids=cfg.local_device_ids,
        )
        _initialized = True
    elif cfg.auto:
        # TPU pod: topology/coordinator come from the TPU runtime env.
        jax.distributed.initialize()
        _initialized = True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def global_mesh(
    axis_sizes: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh over ALL processes' devices, process-major on the leading axis.

    ``jax.devices()`` orders devices by process index, so a 1-D mesh (or the
    leading axis of a 2-D one) assigns each process a CONTIGUOUS block of
    that axis — the property :func:`process_slice` relies on for co-locating
    entity table shards with per-process ingestion.
    """
    from photon_ml_tpu.parallel.mesh import make_mesh

    return make_mesh(axis_sizes, devices=devices)


def process_slice(total: int, mesh: Mesh, axis: str) -> tuple[int, int]:
    """[lo, hi) range of global rows this process owns when an array of
    ``total`` rows is sharded evenly over ``axis`` of ``mesh``.

    Requires the mesh's ``axis`` to be process-major (true for
    :func:`global_mesh`) and ``total`` divisible by the axis size.
    """
    axis_size = mesh.shape[axis]
    if total % axis_size:
        raise ValueError(
            f"total={total} must divide over the {axis_size}-device "
            f"'{axis}' axis"
        )
    per_device = total // axis_size
    # devices along `axis` for fixed other-axis coordinates; process-major
    axes = list(mesh.axis_names)
    dev_grid = np.moveaxis(mesh.devices, axes.index(axis), 0)
    dev_line = dev_grid.reshape(dev_grid.shape[0], -1)[:, 0]
    mine = [i for i, d in enumerate(dev_line) if d.process_index == jax.process_index()]
    if not mine:
        return (0, 0)
    if mine != list(range(mine[0], mine[-1] + 1)):
        raise ValueError(
            f"devices of process {jax.process_index()} are not contiguous "
            f"along axis '{axis}'; use global_mesh() ordering"
        )
    return (mine[0] * per_device, (mine[-1] + 1) * per_device)


def host_local_array(
    local: np.ndarray,
    mesh: Mesh,
    spec: P,
    global_shape: Optional[tuple[int, ...]] = None,
) -> jax.Array:
    """Assemble a global sharded array from this process's LOCAL rows.

    ``local`` holds only the rows this process owns (its
    :func:`process_slice` of the leading axis). Single-process, this is just
    a sharded device_put. Multi-process, no host ever materializes the
    global array — the Spark-free analog of an RDD whose partitions live
    where they were read.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(
        sharding, local, global_shape=global_shape
    )


def replicate_to_all(value: np.ndarray, mesh: Mesh) -> jax.Array:
    """Replicate a host value identically across every process's devices
    (broadcast analog). All processes must pass the same value."""
    sharding = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(value), global_shape=np.shape(value)
    )


def gather_to_host(arr: jax.Array) -> np.ndarray:
    """Fetch a (possibly cross-process) sharded array fully to every host.

    Single-process this is a plain np.asarray. Multi-process it reshards to
    fully-replicated (XLA all-gather over ICI/DCN) and reads the local
    copy, so use it for summaries/models, not bulk data.
    """
    if jax.process_count() == 1 or getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    mesh = arr.sharding.mesh
    replicated = jax.jit(
        lambda x: x, out_shardings=NamedSharding(mesh, P())
    )(arr)
    return np.asarray(replicated.addressable_data(0))
