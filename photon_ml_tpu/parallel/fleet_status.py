"""Live fleet status: the JSON snapshot an operator polls during a pod run.

The supervisor (tools/fleet.py) already KNOWS the fleet's state — exit
codes, heartbeat-file mtimes, deaths, relaunch generation — but until now
that state lived in one Python loop's locals and was only readable post
mortem. :class:`FleetStatusWriter` publishes it on a cadence:

- ``--status-file``: one atomic JSON snapshot (write-tmp-then-rename via
  ``utils.atomic`` — a poller must never read a torn file, per the L008
  discipline), refreshed every ``interval_s``;
- ``--status-port``: the same snapshot served over HTTP
  (``GET /statusz``), computed fresh per request;
- member liveness comes from the heartbeat-file mtimes
  (``proc-<i>.alive`` — the ``multihost.HeartbeatWriter`` protocol), and
  each member's last progress fields from the tail of its telemetry
  stream (``telemetry.progress.tail_heartbeat_fields``, which REQUIRES
  the ``proc`` attribution field so a mis-pointed file reads as silence,
  not as another member's progress).

Failure semantics: a status write is OBSERVABILITY, never control — an
unwritable status file (disk full, torn-down workdir, or the
``fleet.status_write`` fault seam's ``io`` rule) logs, counts
``fleet.status_write_errors``, and the supervisor keeps supervising.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import threading
from typing import Any, Optional

from photon_ml_tpu import faults

logger = logging.getLogger("photon_ml_tpu.parallel.fleet_status")

__all__ = ["FleetStatusWriter", "DEFAULT_STATUS_INTERVAL_S"]

DEFAULT_STATUS_INTERVAL_S = 1.0

# Observability seam: one status-snapshot write by the supervisor's
# status thread. An `io` rule here is the disk-full/torn-workdir shape
# the writer must absorb (status is never control); `raise` is surfaced
# to the caller of write_once for the unit seam test. NOT write_path
# (the single-process crash matrix arms a training worker, which never
# runs a supervisor) and NOT distributed (the distributed matrix arms a
# fleet MEMBER; this seam fires in the supervisor process).
_FP_STATUS_WRITE = faults.register_point(
    "fleet.status_write",
    description="one supervisor status-snapshot write (file and/or the "
    "HTTP cache refresh)",
)


class FleetStatusWriter:
    """Publish the supervisor's fleet view on a cadence (daemon thread).

    ``update(...)`` is the supervisor's push side (generation, exit
    codes, deaths, relaunches); liveness and per-member heartbeat fields
    are pulled from the shared filesystem at snapshot time, so the
    status stays truthful even while the supervisor loop is blocked in a
    wait. Use as a context manager or ``start()``/``stop()``.
    """

    def __init__(
        self,
        fleet_dir: str,
        num_processes: int,
        heartbeat_deadline_s: float,
        status_file: Optional[str] = None,
        port: Optional[int] = None,
        telemetry_out: Optional[str] = None,
        interval_s: float = DEFAULT_STATUS_INTERVAL_S,
    ):
        if interval_s <= 0:
            raise ValueError("status interval_s must be > 0")
        self.fleet_dir = fleet_dir
        self.status_file = status_file
        self.telemetry_out = telemetry_out
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._server_thread: Optional[threading.Thread] = None
        self._requested_port = port
        self.port: Optional[int] = None
        # supervisor-pushed state: written by the supervisor loop via
        # update() (public API) and read by the status thread AND the
        # HTTP handler threads — every access sits under the lock (L015)
        self._lock = threading.Lock()
        self._state: dict[str, Any] = {
            "generation": 0,
            "num_processes": int(num_processes),
            "heartbeat_deadline_s": float(heartbeat_deadline_s),
            "deaths": [],
            # cumulative across relaunches: per-generation `deaths` is
            # reset when a survivor fleet launches, but the run's loss
            # record must survive in the snapshot (an operator reading
            # the final status of a recovered run needs to see the loss)
            "death_history": [],
            "relaunches": 0,
            "rcs": {},
            "outcome": None,
            "telemetry_out": telemetry_out,
            # supervisor-pushed per-member facts beyond liveness: a
            # SERVING fleet's owned entity ranges, model version, and
            # router's-eye requests/s land here (keyed by process id) and
            # merge into each member's snapshot entry
            "member_extras": {},
        }

    # -- supervisor push side ------------------------------------------------

    def update(self, **fields: Any) -> None:
        """Merge supervisor-side facts (generation, rcs, deaths,
        relaunches, outcome, num_processes) into the next snapshot."""
        with self._lock:
            self._state.update(fields)

    # -- snapshot ------------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """One JSON-safe status document, computed from the pushed state
        plus the live filesystem (heartbeat mtimes, telemetry tails)."""
        import time

        from photon_ml_tpu.parallel import multihost
        from photon_ml_tpu.telemetry import identity
        from photon_ml_tpu.telemetry.progress import tail_heartbeat_fields

        with self._lock:
            state = dict(self._state)
        # already a float (ctor/update coerce); float() here would read
        # as a device sync to the L013 walk this function is seeded into
        deadline_s = state["heartbeat_deadline_s"]
        # wall clock by necessity: liveness is measured against heartbeat
        # file MTIMES (same contract as multihost.dead_peers)
        now = time.time()  # photon: noqa[L006]
        members: dict[str, Any] = {}
        for pid in range(int(state["num_processes"])):
            entry: dict[str, Any] = {
                "rc": state["rcs"].get(pid, state["rcs"].get(str(pid))),
                "lost": pid in (state.get("deaths") or []),
            }
            try:
                mtime = os.path.getmtime(
                    multihost.heartbeat_path(self.fleet_dir, pid)
                )
            except OSError:
                entry["alive"] = False
                entry["heartbeat_age_s"] = None
            else:
                age = max(now - mtime, 0.0)
                entry["heartbeat_age_s"] = round(age, 3)
                entry["alive"] = age <= deadline_s and entry["rc"] is None
            telemetry_out = state.get("telemetry_out")
            if telemetry_out is not None:
                fields = tail_heartbeat_fields(
                    identity.member_artifact_path(telemetry_out, pid),
                    expect_proc=pid,
                )
                if fields is not None:
                    entry["last_heartbeat"] = fields
            extras = state.get("member_extras") or {}
            extra = extras.get(pid, extras.get(str(pid)))
            if extra:
                entry.update(extra)
                if extra.get("degraded"):
                    # the router cannot reach this member: whatever the
                    # heartbeat file says, its shard is NOT serving —
                    # render it lost so an operator sees the shed
                    entry["lost"] = True
            members[str(pid)] = entry
        doc: dict[str, Any] = {
            "type": "fleet_status",
            "wall_time": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "generation": state["generation"],
            "num_processes": state["num_processes"],
            "deaths": state.get("deaths") or [],
            "death_history": state.get("death_history") or [],
            "deaths_total": len(state.get("death_history") or []),
            "relaunches": state.get("relaunches", 0),
            "outcome": state.get("outcome"),
            "alive_members": sorted(
                int(p) for p, e in members.items() if e.get("alive")
            ),
            "members": members,
        }
        return doc

    def write_once(self) -> Optional[dict[str, Any]]:
        """One snapshot -> status file (atomic). Returns the snapshot, or
        None when the write failed (logged + counted, never fatal)."""
        from photon_ml_tpu import telemetry

        snap = self.snapshot()
        if self.status_file is None:
            return snap
        from photon_ml_tpu.utils.atomic import atomic_write_json

        try:
            faults.fault_point(_FP_STATUS_WRITE)
            atomic_write_json(
                self.status_file, snap, indent=2, sort_keys=True,
                default=str,
            )
        except OSError as e:
            # InjectedIOError lands here too: status is observability,
            # not control — the supervisor must keep supervising
            telemetry.counter("fleet.status_write_errors").inc()
            logger.warning("fleet status write failed: %s", e)
            return None
        telemetry.counter("fleet.status_writes").inc()
        return snap

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetStatusWriter":
        if self._thread is not None:
            return self  # idempotent
        if self._requested_port is not None:
            self._start_server(self._requested_port)
        if self.status_file is None:
            # HTTP-only mode: every request computes its own fresh
            # snapshot in the handler — a cadence thread would stat and
            # tail every member's files each interval just to discard it
            return self
        self.write_once()  # first snapshot immediately, then the cadence
        self._thread = threading.Thread(
            target=self._run, name="fleet-status", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write_once()
            except Exception:  # noqa: BLE001 — never kill supervision
                logger.debug("fleet status probe failed", exc_info=True)

    def _start_server(self, port: int) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        writer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path not in ("/", "/statusz"):
                    self.send_error(404)
                    return
                try:
                    body = json.dumps(
                        writer.snapshot(), indent=2, sort_keys=True,
                        default=str,
                    ).encode("utf-8")
                except Exception as e:  # noqa: BLE001
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: operators poll this
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._server_thread = threading.Thread(
            target=self._server.serve_forever,
            name="fleet-status-http",
            daemon=True,
        )
        self._server_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
            self._server = None
        if self._server_thread is not None:
            self._server_thread.join(timeout=5.0)
            self._server_thread = None
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, self.interval_s * 4))
            self._thread = None
        self.write_once()  # final state (outcome/rcs) lands on disk

    def __enter__(self) -> "FleetStatusWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
