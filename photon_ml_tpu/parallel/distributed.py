"""Data-parallel GLM solving over a device mesh.

The entire optimizer while-loop runs INSIDE a ``shard_map`` over the data
axis: coefficients and optimizer state are computed redundantly on every
device (replicated), the batch rows are device-local shards, and every data
sum in the objective/line-search psums over ICI. One jit program per solve —
the reference's per-iteration driver<->executor broadcast/treeAggregate round
trips (SURVEY.md §3.4) are gone entirely.

The compiled solver is cached per (config, mesh, axis, arg-structure) so a
lambda sweep re-invoking ``distributed_solve`` with new regularization
weights (traced leaves of the objective) hits the jit cache instead of
recompiling — the on-device analog of the reference's mutable
``updateRegularizationWeight`` warm-start loop
(DistributedOptimizationProblem.scala:60-71).

Reference analog: DistributedGLMLossFunction + DistributedOptimizationProblem
(photon-api function/glm/DistributedGLMLossFunction.scala:49-169,
optimization/DistributedOptimizationProblem.scala:42-195).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.telemetry.xla import instrumented_jit, record_collective
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.common import BoxConstraints, SolveResult
from photon_ml_tpu.optim.factory import OptimizerConfig, build_objective, dispatch_solve
from photon_ml_tpu.parallel.mesh import DATA_AXIS, shard_map_compat

Array = jax.Array


@lru_cache(maxsize=64)
def _build_solver(config: OptimizerConfig, mesh: Mesh, axis: str):
    """Compile-once solver factory. All dynamic values (objective leaves —
    including the l2 weight —, l1 weight, batch shards, w0, constraints,
    warm-start anchors) are traced arguments; the cache key carries only
    program-structure statics. The config in the key has its
    regularization_weight canonicalized to 0.0 by the caller so lambda
    sweeps share one entry."""

    def local_solve(obj, batch_shard, w0, l1, constraints, init_value, init_grad_norm):
        # shard_map delivers leaves with a leading [1, ...] block — squeeze.
        batch_local = jax.tree.map(lambda x: x[0], batch_shard)
        adapter = glm_adapter(obj, batch_local, axis_name=axis)
        return dispatch_solve(
            adapter,
            w0,
            config,
            l1,
            constraints=constraints,
            init_value=init_value,
            init_grad_norm=init_grad_norm,
        )

    def wrapped(obj, stacked_batch, w0, l1, constraints, init_value, init_grad_norm):
        batch_specs = jax.tree.map(lambda _: P(axis), stacked_batch)
        rep_tree = lambda t: jax.tree.map(lambda _: P(), t)
        return shard_map_compat(
            local_solve,
            mesh=mesh,
            in_specs=(
                rep_tree(obj),
                batch_specs,
                P(),
                P(),
                rep_tree(constraints),
                rep_tree(init_value),
                rep_tree(init_grad_norm),
            ),
            out_specs=P(),
            check=False,  # psum'd outputs are replicated by construction
        )(obj, stacked_batch, w0, l1, constraints, init_value, init_grad_norm)

    return instrumented_jit(wrapped, name="distributed_solve")


def distributed_solve(
    loss_name: str,
    stacked_batch: SparseBatch,
    config: OptimizerConfig,
    w0: Array,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    constraints: Optional[BoxConstraints] = None,
    factors: Optional[Array] = None,
    shifts: Optional[Array] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
    extra_l2: float = 0.0,
) -> SolveResult:
    """Solve a GLM with examples sharded over ``axis`` of ``mesh``.

    ``stacked_batch`` leaves carry a leading [num_shards, ...] axis with
    LOCAL row indices per shard (see parallel.mesh.shard_rows).
    ``extra_l2`` adds damping on top of the configured regularization (the
    guarded-solve retry path, optim.guard) — a traced objective leaf, so
    damped retries hit the same compiled program.
    """
    import dataclasses as _dc

    from photon_ml_tpu.optim.guard import damped_objective

    config.validate(loss_name)
    obj = damped_objective(
        build_objective(loss_name, config, factors=factors, shifts=shifts),
        extra_l2,
    )
    l1 = jnp.float32(config.regularization.l1_weight(config.regularization_weight))
    key_config = _dc.replace(config, regularization_weight=0.0)
    solver = _build_solver(key_config, mesh, axis)
    # static comms estimate (telemetry.xla): each data pass psums one [d]
    # gradient + a scalar objective value over the ring; max_iterations
    # bounds the pass count (line-search extra evals are not counted —
    # README "comms methodology" documents the limits)
    record_collective(
        "distributed_solve",
        "psum",
        int(mesh.shape[axis]),
        int(w0.nbytes) + 4,
        count=max(int(config.max_iterations), 1),
    )
    return solver(
        obj, stacked_batch, w0, l1, constraints, init_value, init_grad_norm
    )


@lru_cache(maxsize=64)
def _build_sharded_eval(mesh: Mesh, axis: str, method_name: str):
    """Sharded evaluation of one GLMObjective method (value_and_grad /
    hessian_diagonal / ...): per-shard partial sums psum'd over ``axis``."""

    def f(obj_in, w_in, b):
        b = jax.tree.map(lambda x: x[0], b)
        return getattr(obj_in, method_name)(w_in, b, axis_name=axis)

    def wrapped(obj, w, stacked_batch):
        batch_specs = jax.tree.map(lambda _: P(axis), stacked_batch)
        return shard_map_compat(
            f,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(), obj), P(), batch_specs),
            out_specs=P(),
            check=False,
        )(obj, w, stacked_batch)

    return instrumented_jit(wrapped, name=f"distributed_{method_name}")


def distributed_value_and_grad(
    obj: GLMObjective,
    w: Array,
    stacked_batch: SparseBatch,
    mesh: Mesh,
    axis: str = DATA_AXIS,
) -> tuple[Array, Array]:
    """Standalone sharded objective evaluation (diagnostics / evaluators)."""
    record_collective(
        "distributed_value_and_grad", "psum", int(mesh.shape[axis]),
        int(w.nbytes) + 4,
    )
    return _build_sharded_eval(mesh, axis, "value_and_grad")(obj, w, stacked_batch)


def distributed_hessian_diagonal(
    obj: GLMObjective,
    w: Array,
    stacked_batch: SparseBatch,
    mesh: Mesh,
    axis: str = DATA_AXIS,
) -> Array:
    """Sharded diag H(w), for coefficient variances
    (DistributedOptimizationProblem.scala computeVariances analog)."""
    record_collective(
        "distributed_hessian_diagonal", "psum", int(mesh.shape[axis]),
        int(w.nbytes),
    )
    return _build_sharded_eval(mesh, axis, "hessian_diagonal")(obj, w, stacked_batch)
