"""Data-parallel GLM solving over a device mesh — GSPMD, not shard_map.

The entire optimizer while-loop runs inside ONE ``jax.jit``: batch rows are
committed with ``NamedSharding(mesh, P("batch"))`` (parallel.sharding),
coefficients/optimizer state are replicated, and the XLA compiler (GSPMD)
inserts the psums at every data sum in the objective/line-search — the
Spark ``treeAggregate`` -> psum-over-ICI mapping of PAPER.md with no
hand-rolled SPMD plumbing. One jit program per solve; the reference's
per-iteration driver<->executor broadcast/treeAggregate round trips
(SURVEY.md §3.4) are gone entirely.

Two entry points share one compiled-solver core:

- :func:`gspmd_solve` — the product path: a FLAT design (SparseBatch or
  TiledBatch) placed by ``parallel.sharding.place_batch``; rows/tiles carry
  the batch-axis sharding directly, no host restacking.
- :func:`distributed_solve` — the stacked-layout compat surface (leaves
  carry a leading [num_shards, ...] axis with LOCAL row indices, see
  parallel.mesh.shard_rows): the stack is flattened back to the global
  design INSIDE the jit (a sharded reshape, no data movement) and solved by
  the same GSPMD program. Multi-host callers keep feeding process-local
  stacked shards via ``make_array_from_process_local_data``.

The compiled solver is cached per (config, mesh, axis, arg-structure) so a
lambda sweep re-invoking a solve with new regularization weights (traced
leaves of the objective) hits the jit cache instead of recompiling — the
on-device analog of the reference's mutable ``updateRegularizationWeight``
warm-start loop (DistributedOptimizationProblem.scala:60-71).

Reference analog: DistributedGLMLossFunction + DistributedOptimizationProblem
(photon-api function/glm/DistributedGLMLossFunction.scala:49-169,
optimization/DistributedOptimizationProblem.scala:42-195).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu import faults
from photon_ml_tpu.telemetry.xla import instrumented_jit, record_collective
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.common import BoxConstraints, SolveResult
from photon_ml_tpu.optim.factory import OptimizerConfig, build_objective, dispatch_solve
from photon_ml_tpu.parallel.mesh import DATA_AXIS

Array = jax.Array

# Fleet fault seam: the last host-side instruction before this process
# commits to a cross-process collective program. A member hard-killed
# here is the worst-case partial failure — its peers enter the
# collective and block against a partner that is never coming, so
# recovery is the SUPERVISOR's job (liveness detection + boundary stop +
# survivor relaunch), not an exception handler's. Hit by the GSPMD solve
# dispatch below and by the streamed chunk solve (game/streaming.py).
FP_COLLECTIVE_ENTRY = faults.register_point(
    "parallel.collective.entry", distributed=True,
    description="host-side entry into a multi-process collective program "
    "(gspmd/distributed solve dispatch, streamed chunk solves)",
)


def _unstack_batch(stacked: SparseBatch) -> SparseBatch:
    """Flatten a shard-stacked COO batch ([S, ...] leaves, LOCAL row
    indices — the parallel.mesh.shard_rows layout) back to the flat global
    design INSIDE jit. The leading stacked axis is sharded, so the merge
    is a sharded reshape — GSPMD keeps the blocks where they live; only
    the row indices gain their block offset. (Tiled designs never stack:
    the flat-GSPMD path places them directly, parallel.sharding.)"""
    num_shards, rows_per = stacked.labels.shape
    block = (
        jnp.arange(num_shards, dtype=stacked.rows.dtype) * rows_per
    )[:, None]
    return SparseBatch(
        values=stacked.values.reshape(-1),
        rows=(stacked.rows + block).reshape(-1),
        cols=stacked.cols.reshape(-1),
        labels=stacked.labels.reshape(-1),
        offsets=stacked.offsets.reshape(-1),
        weights=stacked.weights.reshape(-1),
        num_features=stacked.num_features,
    )


@lru_cache(maxsize=64)
def _build_solver(
    config: OptimizerConfig, mesh: Mesh, axis: str, stacked: bool
):
    """Compile-once GSPMD solver factory. All dynamic values (objective
    leaves — including the l2 weight —, l1 weight, the batch, w0,
    constraints, warm-start anchors) are traced arguments; the cache key
    carries only program-structure statics. The config in the key has its
    regularization_weight canonicalized to 0.0 by the caller so lambda
    sweeps share one entry."""
    row_sharding = NamedSharding(mesh, P(axis))

    def run(obj, batch, w0, l1, constraints, init_value, init_grad_norm):
        if stacked:
            batch = _unstack_batch(batch)
        adapter = glm_adapter(obj, batch, row_sharding=row_sharding)
        return dispatch_solve(
            adapter,
            w0,
            config,
            l1,
            constraints=constraints,
            init_value=init_value,
            init_grad_norm=init_grad_norm,
        )

    # coefficients and solve telemetry are replicated by construction
    # (every data sum all-reduces); pin that so callers always receive
    # fully-replicated results regardless of GSPMD's propagation choices
    return instrumented_jit(
        run,
        name="distributed_solve" if stacked else "gspmd_solve",
        multi_shape=True,  # one solver serves every dataset shape
        out_shardings=NamedSharding(mesh, P()),
    )


def _solve_common(
    loss_name: str,
    batch,
    config: OptimizerConfig,
    w0: Array,
    mesh: Mesh,
    axis: str,
    stacked: bool,
    constraints,
    factors,
    shifts,
    init_value,
    init_grad_norm,
    extra_l2: float,
    label: str,
) -> SolveResult:
    import dataclasses as _dc

    from photon_ml_tpu.optim.guard import damped_objective

    config.validate(loss_name)
    obj = damped_objective(
        build_objective(loss_name, config, factors=factors, shifts=shifts),
        extra_l2,
    )
    l1 = jnp.float32(config.regularization.l1_weight(config.regularization_weight))
    key_config = _dc.replace(config, regularization_weight=0.0)
    solver = _build_solver(key_config, mesh, axis, stacked)
    # static comms estimate (telemetry.xla): each data pass all-reduces one
    # [d] gradient + a scalar objective value over the ring (GSPMD lowers
    # them to the same ring psum shard_map spelled by hand); max_iterations
    # bounds the pass count (line-search extra evals are not counted —
    # README "comms methodology" documents the limits)
    record_collective(
        label,
        "psum",
        int(mesh.shape[axis]),
        int(w0.nbytes) + 4,
        count=max(int(config.max_iterations), 1),
    )
    faults.fault_point(FP_COLLECTIVE_ENTRY)
    # collective-wait attribution (multi-process only): how long THIS
    # member spent dispatching into the cross-process program — the
    # per-member signal the fleet report ranks stragglers by
    from photon_ml_tpu.parallel.multihost import collective_wait

    with collective_wait(label):
        return solver(
            obj, batch, w0, l1, constraints, init_value, init_grad_norm
        )


def gspmd_solve(
    loss_name: str,
    batch,
    config: OptimizerConfig,
    w0: Array,
    mesh: Mesh,
    axis: Optional[str] = None,
    constraints: Optional[BoxConstraints] = None,
    factors: Optional[Array] = None,
    shifts: Optional[Array] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
    extra_l2: float = 0.0,
) -> SolveResult:
    """Solve a GLM whose FLAT design is row-sharded over ``axis``.

    ``batch`` is a SparseBatch/TiledBatch placed by
    ``parallel.sharding.place_batch(batch, mesh, axis)`` (leaves committed
    with ``NamedSharding(mesh, P(axis))``). ``extra_l2`` adds damping on
    top of the configured regularization (the guarded-solve retry path,
    optim.guard) — a traced objective leaf, so damped retries hit the same
    compiled program.
    """
    from photon_ml_tpu.parallel.sharding import batch_sharding

    # batch_sharding resolves the axis and raises the clear "no batch/data
    # axis" ValueError (instead of a KeyError deep in the comms estimate)
    axis = axis or batch_sharding(mesh).spec[0]
    return _solve_common(
        loss_name, batch, config, w0, mesh, axis, False, constraints,
        factors, shifts, init_value, init_grad_norm, extra_l2,
        label="gspmd_solve",
    )


def distributed_solve(
    loss_name: str,
    stacked_batch: SparseBatch,
    config: OptimizerConfig,
    w0: Array,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    constraints: Optional[BoxConstraints] = None,
    factors: Optional[Array] = None,
    shifts: Optional[Array] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
    extra_l2: float = 0.0,
) -> SolveResult:
    """Solve a GLM fed in the stacked shard layout (compat surface).

    ``stacked_batch`` leaves carry a leading [num_shards, ...] axis with
    LOCAL row indices per shard (see parallel.mesh.shard_rows) — the
    layout multi-host workers assemble from process-local rows. The solve
    itself is the same GSPMD program as :func:`gspmd_solve`; the stack is
    flattened inside the jit.
    """
    return _solve_common(
        loss_name, stacked_batch, config, w0, mesh, axis, True, constraints,
        factors, shifts, init_value, init_grad_norm, extra_l2,
        label="distributed_solve",
    )


@lru_cache(maxsize=64)
def _build_sharded_eval(mesh: Mesh, axis: str, method_name: str):
    """Sharded evaluation of one GLMObjective method (value_and_grad /
    hessian_diagonal / ...) over the stacked layout: the stack flattens
    inside jit and GSPMD all-reduces the data sums."""

    def wrapped(obj, w, stacked_batch):
        return getattr(obj, method_name)(w, _unstack_batch(stacked_batch))

    return instrumented_jit(
        wrapped,
        name=f"distributed_{method_name}",
        multi_shape=True,
        out_shardings=NamedSharding(mesh, P()),
    )


def distributed_value_and_grad(
    obj: GLMObjective,
    w: Array,
    stacked_batch: SparseBatch,
    mesh: Mesh,
    axis: str = DATA_AXIS,
) -> tuple[Array, Array]:
    """Standalone sharded objective evaluation (diagnostics / evaluators)."""
    record_collective(
        "distributed_value_and_grad", "psum", int(mesh.shape[axis]),
        int(w.nbytes) + 4,
    )
    return _build_sharded_eval(mesh, axis, "value_and_grad")(obj, w, stacked_batch)


def distributed_hessian_diagonal(
    obj: GLMObjective,
    w: Array,
    stacked_batch: SparseBatch,
    mesh: Mesh,
    axis: str = DATA_AXIS,
) -> Array:
    """Sharded diag H(w), for coefficient variances
    (DistributedOptimizationProblem.scala computeVariances analog)."""
    record_collective(
        "distributed_hessian_diagonal", "psum", int(mesh.shape[axis]),
        int(w.nbytes),
    )
    return _build_sharded_eval(mesh, axis, "hessian_diagonal")(obj, w, stacked_batch)
