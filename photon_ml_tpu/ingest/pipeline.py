"""The staged, threaded ingest pipeline: ``ChunkStream``.

Stages, each its own thread(s), connected by bounded hand-offs:

  decode workers (N)  -- fill staging buffers from block ranges
        |  deterministic reorder (chunks re-sequence to plan order)
  uploader (1)        -- ``device_put`` of chunk K+1 while chunk K solves
        |  bounded output queue (``prefetch_depth``)
  consumer            -- the training loop, iterating DeviceChunks

Backpressure is structural: decode blocks on the buffer ring, the
uploader blocks on the output queue, and every wait has a stall timeout
that raises a typed :class:`~photon_ml_tpu.ingest.errors.IngestStall`
instead of hanging. Ordering is deterministic — chunks leave the
pipeline in plan order no matter which worker finished first — so a
checkpoint resume (``start_chunk=K``) replays the exact remaining
stream, and the stream-global id-column interning is reproducible.

Telemetry: ``ingest.rows`` / ``ingest.chunks`` / ``ingest.stalls`` /
``ingest.buffer_growths`` counters, ``ingest.queue_depth`` /
``ingest.staging_bytes`` / ``ingest.rows_per_sec`` gauges, an
``ingest.solve_wait_s`` histogram plus ``ingest.solve_waits`` (how often
the SOLVE waited on data after warm-up — the number the RunReport
"Ingestion" section is built around), and per-stage spans.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import threading
import time
from typing import Mapping, Optional, Sequence

import jax
import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.ingest.buffers import BufferRing, StagingBuffer
from photon_ml_tpu.ingest.decode import (
    DecodeContext,
    build_decode_context,
    decode_chunk,
)
from photon_ml_tpu.ingest.errors import (
    ChunkDecodeError,
    IngestConfigError,
    IngestStall,
    PipelineClosed,
)
from photon_ml_tpu.ingest.planner import ChunkPlan, plan_chunks
from photon_ml_tpu.ops.sparse import SparseBatch

_END = object()

# Injection seam on the uploader's per-chunk device_put: a firing rule is
# the uploader thread dying mid-stream (the consumer must surface it as a
# typed error, not a silent hang).
_FP_UPLOAD_CHUNK = faults.register_point(
    "ingest.upload.chunk",
    description="uploader device_put of one device-ready chunk",
)


@dataclasses.dataclass(frozen=True)
class IngestSpec:
    """Tuning knobs of one ingest pipeline.

    ``workers=0`` means one decode worker per host core.
    ``prefetch_depth`` bounds how many device-ready chunks may wait ahead
    of the solve (the double-buffer depth). ``ring_slots=0`` sizes the
    staging ring to ``workers + prefetch_depth + 1``.
    ``resident_budget_mb`` caps the HOST-resident staging memory: the
    ring shrinks to fit (never below 2 slots — below that the pipeline
    cannot overlap, and the spec is rejected with the sizing math).
    ``read_retries`` bounds how many times ONE chunk's decode is retried
    after a transient ``OSError`` (flaky network filesystem read) before
    the error propagates and kills the stream; retries back off
    ``retry_backoff_s * 2**attempt`` and are surfaced in
    :class:`IngestStats` / ``ingest.read_retries``.
    """

    workers: int = 0
    prefetch_depth: int = 2
    chunk_rows: int = 65536
    nnz_per_row_hint: int = 32
    ring_slots: int = 0
    resident_budget_mb: Optional[float] = None
    stall_timeout_s: float = 600.0
    read_retries: int = 2
    retry_backoff_s: float = 0.05

    def __post_init__(self):
        if self.workers < 0:
            raise IngestConfigError("ingest workers must be >= 0")
        if self.read_retries < 0:
            raise IngestConfigError("read_retries must be >= 0")
        if self.retry_backoff_s < 0:
            raise IngestConfigError("retry_backoff_s must be >= 0")
        if self.prefetch_depth < 1:
            raise IngestConfigError("prefetch_depth must be >= 1")
        if self.chunk_rows < 1:
            raise IngestConfigError("chunk_rows must be >= 1")
        if self.nnz_per_row_hint < 1:
            raise IngestConfigError("nnz_per_row_hint must be >= 1")
        if self.ring_slots < 0:
            raise IngestConfigError("ring_slots must be >= 0")
        if self.stall_timeout_s <= 0:
            raise IngestConfigError("stall_timeout_s must be > 0")
        if (
            self.resident_budget_mb is not None
            and self.resident_budget_mb <= 0
        ):
            raise IngestConfigError("resident_budget_mb must be > 0")

    def resolved_workers(self) -> int:
        return self.workers or max(os.cpu_count() or 1, 1)

    @staticmethod
    def from_config(obj) -> "IngestSpec":
        """Config value -> spec: ``true`` means defaults, a dict overrides
        fields; unknown keys are a typed error (a silently ignored knob
        is worse than a refusal)."""
        if obj is True:
            return IngestSpec()
        if not isinstance(obj, Mapping):
            raise IngestConfigError(
                f"ingest config must be true or an object, got {obj!r}"
            )
        fields = {f.name for f in dataclasses.fields(IngestSpec)}
        unknown = set(obj) - fields
        if unknown:
            raise IngestConfigError(
                f"unknown ingest config keys: {sorted(unknown)} "
                f"(known: {sorted(fields)})"
            )
        return IngestSpec(**obj)


@dataclasses.dataclass
class DeviceChunk:
    """One device-ready chunk, in deterministic stream order.

    ``shards`` hold padded SparseBatches with DEVICE leaves (uniform
    ``rows_cap`` rows; nnz capacity may step up once if the hint was
    low). ``labels``/``offsets``/``weights`` are exact f64 HOST copies of
    the real rows (assemblers and evaluators want unpadded host
    scalars); ``id_codes`` are stream-GLOBAL interned entity codes.
    """

    index: int
    row_start: int
    rows: int
    shards: dict[str, SparseBatch]
    nnz_used: dict[str, int]
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    id_codes: dict[str, np.ndarray]

    @property
    def batch(self) -> SparseBatch:
        """The single-shard convenience view (GLM flows)."""
        if len(self.shards) != 1:
            raise ValueError(
                f"chunk has {len(self.shards)} shards; name one explicitly"
            )
        return next(iter(self.shards.values()))


@dataclasses.dataclass
class IngestStats:
    rows: int = 0
    chunks: int = 0
    stalls: int = 0
    solve_waits: int = 0
    solve_wait_s: float = 0.0
    buffer_growths: int = 0
    staging_bytes: int = 0
    rows_per_sec: float = 0.0
    #: transient-read retries that succeeded on a later attempt — a
    #: nonzero value means the storage layer flaked and the bounded
    #: retry absorbed it (RunReport "Ingestion" surfaces this)
    read_retries: int = 0


class ChunkStream:
    """Iterator of :class:`DeviceChunk`, fed by the threaded pipeline.

    Use as an iterator or a context manager; ``close()`` tears the
    threads down early (abandoning a stream mid-run is legal — resume
    later with ``start_chunk``).
    """

    def __init__(
        self,
        paths: Sequence[str],
        feature_shards: Optional[Mapping[str, Sequence[str]]] = None,
        index_maps: Optional[Mapping] = None,
        id_columns: Sequence[str] = (),
        add_intercept: bool = True,
        is_response_required: bool = True,
        spec: Optional[IngestSpec] = None,
        placement=None,
        start_chunk: int = 0,
        id_vocabularies: Optional[Mapping[str, Sequence]] = None,
    ):
        from photon_ml_tpu.data.avro import _as_paths

        if index_maps is None:
            raise IngestConfigError(
                "the ingest pipeline needs index_maps up front (build or "
                "load them first — data.avro.build_index_maps_from_avro "
                "does a cheap vocab-only scan); an out-of-core stream "
                "cannot discover the feature space as it goes"
            )
        self.spec = spec or IngestSpec()
        feature_shards = dict(feature_shards or {"features": ("features",)})
        file_list = _as_paths(list(paths))
        self.metas, all_plans = plan_chunks(file_list, self.spec.chunk_rows)
        if start_chunk < 0 or start_chunk > len(all_plans):
            raise IngestConfigError(
                f"start_chunk={start_chunk} out of range for "
                f"{len(all_plans)} planned chunks"
            )
        self.plans = all_plans  # full deterministic plan (for resume math)
        self._todo = all_plans[start_chunk:]
        self.total_rows = sum(p.n_rows for p in all_plans)
        self._ctx: DecodeContext = build_decode_context(
            self.metas, feature_shards, index_maps, id_columns,
            add_intercept, is_response_required,
        )
        self.shard_names = self._ctx.shard_names
        self.num_features = {
            s: len(index_maps[s]) for s in self.shard_names
        }
        self._placement = placement
        self.rows_cap = max((p.n_rows for p in all_plans), default=1)
        self._intercept = any(c >= 0 for c in self._ctx.intercept_cols)

        n_workers = min(self.spec.resolved_workers(),
                        max(len(self._todo), 1))
        ring = self._build_ring(n_workers, len(feature_shards),
                                len(id_columns))
        self._ring = ring
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._work_i = 0
        self._pending: dict[int, StagingBuffer] = {}
        # per-shard stream-global raw-nnz capacity (monotone; workers grow
        # free buffers up to it at acquire time, the uploader normalizes
        # in-flight stragglers, so chunk shapes stay uniform)
        self._raw_caps = [self._init_raw_cap] * len(self.shard_names)
        self._out: "queue.Queue" = queue.Queue(
            maxsize=self.spec.prefetch_depth
        )
        # stream-global id interning. NOTE the resume caveat: interned
        # codes are first-seen IN STREAM ORDER, so a stream started at
        # chunk K assigns different codes than the full stream unless the
        # caller seeds it with the original run's vocabularies
        # (`id_vocabularies`, e.g. persisted next to a checkpoint via
        # `id_vocabulary()`); chunk ordering and array contents are
        # start-chunk-independent either way.
        self._interns: list[dict] = []
        for col in id_columns:
            seed = (id_vocabularies or {}).get(col, ())
            self._interns.append({v: i for i, v in enumerate(seed)})
        self._stats = IngestStats(staging_bytes=ring.nbytes)
        self._t0 = time.monotonic()
        self._got_first = False
        self._done = False
        self._threads = [
            threading.Thread(
                target=self._decode_loop, name=f"ingest-decode-{i}",
                daemon=True,
            )
            for i in range(n_workers)
        ]
        self._threads.append(
            threading.Thread(
                target=self._upload_loop, name="ingest-upload", daemon=True
            )
        )
        for t in self._threads:
            t.start()

    # -- sizing --------------------------------------------------------------

    def _build_ring(
        self, n_workers: int, n_shards: int, n_ids: int
    ) -> BufferRing:
        spec = self.spec
        self._init_raw_cap = max(
            self.rows_cap * spec.nnz_per_row_hint, 1
        )
        probe = StagingBuffer(
            self.rows_cap, self._init_raw_cap, n_shards, n_ids,
            self._intercept,
        )
        slot_bytes = probe.nbytes
        want = spec.ring_slots or (
            n_workers + spec.prefetch_depth + 1
        )
        if spec.resident_budget_mb is not None:
            budget = int(spec.resident_budget_mb * 2**20)
            fit = max(budget // max(slot_bytes, 1), 0)
            if fit < 2:
                raise IngestConfigError(
                    f"resident_budget_mb={spec.resident_budget_mb:g} fits "
                    f"{fit} staging slot(s) of {slot_bytes / 2**20:.1f} MB "
                    f"(rows_cap={self.rows_cap}, nnz_per_row_hint="
                    f"{spec.nnz_per_row_hint}); the pipeline needs >= 2 — "
                    "raise the budget or lower chunk_rows/nnz_per_row_hint"
                )
            want = min(want, fit)
        slots = [probe] + [
            StagingBuffer(
                self.rows_cap, self._init_raw_cap, n_shards, n_ids,
                self._intercept,
            )
            for _ in range(want - 1)
        ]
        return BufferRing(slots, spec.stall_timeout_s)

    # -- worker side ---------------------------------------------------------

    def _grow(
        self, buf: StagingBuffer, si: int, needed: int, preserve: int
    ) -> None:
        with self._lock:
            if needed > self._raw_caps[si]:
                new_cap = max(self._raw_caps[si] * 2, needed)
                self._raw_caps[si] = new_cap
                telemetry.counter("ingest.buffer_growths").inc()
                self._stats.buffer_growths += 1
            target = self._raw_caps[si]
        buf.shards[si].grow(target, self.rows_cap, self._intercept,
                            preserve=preserve)

    def _decode_with_retry(self, plan: ChunkPlan, buf: StagingBuffer) -> None:
        """One chunk's decode, retried past transient ``OSError``s.

        A flaky read from a network filesystem must not kill the whole
        stream on its first occurrence: up to ``spec.read_retries``
        re-reads with exponential backoff, each starting the chunk over
        (``decode_chunk`` re-initializes the buffer, so a partial first
        attempt leaves no residue). Deterministic failures — a
        :class:`ChunkDecodeError` from corrupt bytes or a schema
        violation — propagate immediately: re-reading corrupt data
        produces the same corrupt data."""
        for attempt in range(self.spec.read_retries + 1):
            try:
                decode_chunk(self._ctx, plan, buf, self._grow)
                return
            except ChunkDecodeError:
                raise
            except OSError as e:
                if attempt >= self.spec.read_retries:
                    raise
                telemetry.counter("ingest.read_retries").inc()
                with self._lock:
                    self._stats.read_retries += 1
                delay = self.spec.retry_backoff_s * (2 ** attempt)
                logging.getLogger("photon_ml_tpu.ingest").warning(
                    "transient read failure on chunk %d of %s (attempt "
                    "%d/%d, retrying in %.2fs): %s", plan.index, plan.path,
                    attempt + 1, self.spec.read_retries + 1, delay, e,
                )
                if self._stop.wait(delay):
                    raise PipelineClosed(
                        "stream closed during a read-retry backoff"
                    ) from None

    def _next_plan(self) -> Optional[ChunkPlan]:
        with self._lock:
            if self._work_i >= len(self._todo):
                return None
            plan = self._todo[self._work_i]
            self._work_i += 1
            return plan

    def _decode_loop(self) -> None:
        try:
            while not self._stop.is_set():
                plan = self._next_plan()
                if plan is None:
                    return
                buf = self._ring.acquire()
                # converge lagging slots to the stream-global capacity
                # while the buffer is provably free
                with self._lock:
                    caps = list(self._raw_caps)
                for si, cap in enumerate(caps):
                    buf.shards[si].grow(cap, self.rows_cap, self._intercept)
                with telemetry.span(
                    "ingest_decode", chunk=plan.index, rows=plan.n_rows,
                    bytes=plan.nbytes,
                ):
                    self._decode_with_retry(plan, buf)
                with self._cv:
                    self._pending[plan.index] = buf
                    self._cv.notify_all()
        except PipelineClosed:
            pass
        except BaseException as e:  # surface worker deaths to the consumer
            self._fail(e)

    # -- uploader ------------------------------------------------------------

    def _normalized_shard_arrays(self, buf: StagingBuffer, si: int):
        """Pad a straggler (pre-growth) slot's final arrays up to the
        stream-global capacity — rare, only right after a growth, and
        it keeps every chunk batch the same shape."""
        st = buf.shards[si]
        with self._lock:
            target_raw = self._raw_caps[si]
        target = target_raw + (self.rows_cap if self._intercept else 0)
        vals, rws, cls = st.values, st.rows, st.cols
        if len(vals) < target:
            extra = target - len(vals)
            vals = np.concatenate(
                [vals, np.zeros(extra, np.float32)]
            )
            rws = np.concatenate(
                [rws, np.full(extra, self.rows_cap - 1, np.int32)]
            )
            cls = np.concatenate([cls, np.zeros(extra, np.int32)])
        return vals, rws, cls

    def _put_out(self, item) -> None:
        deadline = time.monotonic() + self.spec.stall_timeout_s
        while True:
            if self._stop.is_set():
                raise PipelineClosed("stream closed while uploading")
            try:
                self._out.put(item, timeout=0.25)
                telemetry.gauge("ingest.queue_depth").set(
                    self._out.qsize()
                )
                return
            except queue.Full:
                if time.monotonic() > deadline:
                    telemetry.counter("ingest.stalls").inc()
                    with self._lock:
                        self._stats.stalls += 1
                    raise IngestStall(
                        "upload", self.spec.stall_timeout_s,
                        "output queue stayed full (consumer stopped?)",
                    ) from None

    def _upload_one(self, plan: ChunkPlan, buf: StagingBuffer) -> DeviceChunk:
        import jax.numpy as jnp

        placement = self._placement

        def put(x):
            # The copy is load-bearing: device_put MAY zero-copy an
            # aligned host array (measured on CPU even under explicit
            # shardings), silently aliasing the staging buffer this ring
            # is about to recycle. Default path: jnp.array(copy=True) is
            # one guaranteed-copy hop (on TPU the copy IS the H2D
            # transfer). Placement path: commit a FRESH host copy — the
            # buffer may alias that never-mutated temp all it wants, and
            # a host memcpy is cheaper than a post-hoc device reshard.
            if placement is None:
                return jnp.array(x, copy=True)
            return jax.device_put(np.array(x), placement)
        n = plan.n_rows
        shards: dict[str, SparseBatch] = {}
        nnz_used: dict[str, int] = {}
        labels_d = put(buf.labels)
        offsets_d = put(buf.offsets)
        weights_d = put(buf.weights)
        for si, name in enumerate(self.shard_names):
            vals, rws, cls = self._normalized_shard_arrays(buf, si)
            shards[name] = SparseBatch(
                values=put(vals),
                rows=put(rws),
                cols=put(cls),
                labels=labels_d,
                offsets=offsets_d,
                weights=weights_d,
                num_features=self.num_features[name],
            )
            nnz_used[name] = buf.shards[si].nnz_used
        # exact f64 host copies of the real rows (the staging buffer is
        # about to be recycled)
        labels = buf.scratch_labels[:n].copy()
        offsets = buf.scratch_offsets[:n].copy()
        weights = buf.scratch_weights[:n].copy()
        id_codes: dict[str, np.ndarray] = {}
        for ci, col in enumerate(self._ctx.id_columns):
            table = self._interns[ci]
            vocab = buf.id_vocabs[ci]
            remap = np.empty(len(vocab), np.int64)
            for i, key in enumerate(vocab):
                code = table.get(key)
                if code is None:
                    code = len(table)
                    table[key] = code
                remap[i] = code
            local = buf.id_codes[ci][:n]
            id_codes[col] = remap[local] if len(local) else local.copy()
        # wait for the H2D copies before recycling the staging buffer —
        # the transfer source must not be overwritten mid-flight
        leaves = [labels_d, offsets_d, weights_d]
        for b in shards.values():
            leaves += [b.values, b.rows, b.cols]
        leaves = jax.block_until_ready(leaves)
        return DeviceChunk(
            index=plan.index,
            row_start=plan.row_start,
            rows=n,
            shards=shards,
            nnz_used=nnz_used,
            labels=labels,
            offsets=offsets,
            weights=weights,
            id_codes=id_codes,
        )

    def _upload_loop(self) -> None:
        try:
            for plan in self._todo:
                with self._cv:
                    ok = self._cv.wait_for(
                        lambda: plan.index in self._pending
                        or self._stop.is_set(),
                        timeout=self.spec.stall_timeout_s,
                    )
                    if self._stop.is_set():
                        return
                    if not ok:
                        telemetry.counter("ingest.stalls").inc()
                        self._stats.stalls += 1
                        raise IngestStall(
                            "upload", self.spec.stall_timeout_s,
                            f"chunk {plan.index} never arrived from decode",
                        )
                    buf = self._pending.pop(plan.index)
                with telemetry.span(
                    "ingest_upload", chunk=plan.index, rows=plan.n_rows
                ):
                    faults.fault_point(_FP_UPLOAD_CHUNK)
                    chunk = self._upload_one(plan, buf)
                self._ring.release(buf)
                telemetry.counter("ingest.rows").inc(chunk.rows)
                telemetry.counter("ingest.chunks").inc()
                with self._lock:
                    self._stats.rows += chunk.rows
                    self._stats.chunks += 1
                self._put_out(chunk)
            self._put_out(_END)
        except PipelineClosed:
            pass
        except BaseException as e:
            self._fail(e)

    # -- failure / shutdown --------------------------------------------------

    def _fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = exc
        self._stop.set()
        self._ring.close()
        with self._cv:
            self._cv.notify_all()

    def close(self) -> None:
        """Tear the pipeline down (idempotent)."""
        self._stop.set()
        self._ring.close()
        with self._cv:
            self._cv.notify_all()
        # unblock a put-blocked uploader
        while True:
            try:
                self._out.get_nowait()
            except queue.Empty:
                break
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self) -> "ChunkStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- consumer side -------------------------------------------------------

    def __iter__(self) -> "ChunkStream":
        return self

    def __next__(self) -> DeviceChunk:
        if self._done:
            raise StopIteration
        t0 = time.monotonic()
        while True:
            with self._lock:
                if self._error is not None:
                    self._done = True
                    raise self._error
            try:
                item = self._out.get(timeout=0.25)
                break
            except queue.Empty:
                if time.monotonic() - t0 > self.spec.stall_timeout_s:
                    self._done = True
                    telemetry.counter("ingest.stalls").inc()
                    with self._lock:
                        self._stats.stalls += 1
                    raise IngestStall(
                        "consume", self.spec.stall_timeout_s,
                        "no chunk arrived (decode starved or a worker "
                        "died silently)",
                    ) from None
        telemetry.gauge("ingest.queue_depth").set(self._out.qsize())
        if item is _END:
            self._done = True
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            with self._lock:
                self._stats.rows_per_sec = self._stats.rows / elapsed
            if self._stats.rows:
                telemetry.gauge("ingest.rows_per_sec").set(
                    self._stats.rows_per_sec
                )
            raise StopIteration
        waited = time.monotonic() - t0
        if self._got_first:
            # warm-up excluded: the FIRST chunk always waits for the
            # pipeline to fill; steady-state waits mean the solve is
            # ingest-bound (the RunReport "Ingestion" headline)
            telemetry.histogram("ingest.solve_wait_s").observe(waited)
            if waited > 0.002:
                telemetry.counter("ingest.solve_waits").inc()
                with self._lock:
                    self._stats.solve_waits += 1
                    self._stats.solve_wait_s += waited
        self._got_first = True
        return item

    @property
    def using_native_decoder(self) -> bool:
        """Whether chunks decode through the native C++ interpreter (False
        = the pure-Python fallback workers, identical arrays)."""
        return self._ctx.use_native

    def stats(self) -> IngestStats:
        with self._lock:
            return dataclasses.replace(self._stats)

    def id_vocabulary(self, column: str) -> np.ndarray:
        """The stream-global first-seen vocabulary of an id column
        (complete once the stream is exhausted)."""
        ci = self._ctx.id_columns.index(column)
        return np.asarray(list(self._interns[ci]))
