"""Typed ingest-pipeline errors: the stall/backpressure protocol.

Every failure mode the pipeline can hit has a distinct type, so callers
(and tests) can tell a configuration problem from corrupt input from a
wedged stage — a generic ``queue.Empty`` deep inside a worker thread
tells an operator nothing.
"""

from __future__ import annotations


class IngestError(RuntimeError):
    """Base class for ingest-pipeline failures."""


class IngestConfigError(IngestError, ValueError):
    """An :class:`~photon_ml_tpu.ingest.pipeline.IngestSpec` that cannot
    work: zero/negative depths, a resident budget too small for even a
    minimal ring, a staging capacity the data overflows."""


class IngestStall(IngestError):
    """A pipeline stage waited longer than ``stall_timeout_s`` for its
    neighbor — the typed form of "the pipeline is wedged".

    ``stage`` names the waiting side: ``"decode"`` (no free staging
    buffer — the consumer stopped draining), ``"upload"`` (the bounded
    output queue stayed full), ``"consume"`` (the solve waited on data
    past the timeout — decode cannot keep up, or a worker died silently).
    """

    def __init__(self, stage: str, waited_s: float, detail: str = ""):
        self.stage = stage
        self.waited_s = waited_s
        msg = f"ingest pipeline stalled in stage '{stage}' after {waited_s:.1f}s"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class PipelineClosed(IngestError):
    """The stream was consumed after :meth:`ChunkStream.close` (or after a
    prior error already tore the pipeline down)."""


class ChunkDecodeError(IngestError):
    """A chunk's bytes could not be decoded (corrupt block, record
    missing a required label or id column). Carries the file path and
    chunk index so the bad shard is nameable."""

    def __init__(self, path: str, chunk_index: int, reason: str):
        self.path = path
        self.chunk_index = chunk_index
        super().__init__(f"{path} (chunk {chunk_index}): {reason}")
