"""File-split planner: Avro container files -> deterministic chunk plans.

Avro object-container blocks are sync-delimited and self-describing
(``[count varint, byte-size varint, payload, 16-byte sync]``), so a file
splits into independently decodable byte ranges without reading any
payload — the scan below touches only the two varints per block and
seeks past the rest. The reference reads per-partition on executors
(AvroDataReader.scala:87-237); here the same split boundaries feed a
thread pool on one host.

Determinism contract: ``plan_chunks`` over the same file list with the
same ``chunk_rows`` always yields the same chunk sequence — same indices,
same byte ranges, same global row offsets. Checkpoint resume relies on
this: replaying a stream from chunk K re-decodes exactly the rows the
interrupted run would have, in the same order.
"""

from __future__ import annotations

import dataclasses
import os
from typing import BinaryIO, Iterator, Sequence

_MAGIC = b"Obj\x01"
_SYNC_LEN = 16


@dataclasses.dataclass(frozen=True)
class FileMeta:
    """Header facts of one Avro container file (no payload read)."""

    path: str
    schema_json: str
    codec: str  # "null" | "deflate"
    sync: bytes  # the file's 16-byte block delimiter
    header_end: int  # byte offset of the first block
    file_bytes: int


@dataclasses.dataclass(frozen=True)
class BlockInfo:
    """One sync-delimited block: ``[offset, offset + nbytes)`` holds the
    count/size varints, the payload, and the trailing sync marker."""

    offset: int
    n_records: int
    nbytes: int


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """One unit of decode work: a run of whole blocks inside one file.

    ``index`` is the chunk's position in the global deterministic order;
    ``row_start`` its global row offset (rows of all earlier chunks, in
    order). Chunks never span files — a decode worker reads exactly
    ``[byte_start, byte_end)`` of ``path``.
    """

    index: int
    path: str
    byte_start: int
    byte_end: int
    n_rows: int
    row_start: int
    n_blocks: int

    @property
    def nbytes(self) -> int:
        return self.byte_end - self.byte_start


def _read_varint_long(f: BinaryIO, path: str) -> int:
    """One zigzag varint from the file cursor (raises on EOF)."""
    shift = 0
    acc = 0
    while True:
        b = f.read(1)
        if not b:
            raise ValueError(f"{path}: truncated varint (unexpected EOF)")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not v & 0x80:
            return (acc >> 1) ^ -(acc & 1)
        shift += 7


def _read_exact(f: BinaryIO, n: int, path: str) -> bytes:
    out = f.read(n)
    if len(out) != n:
        raise ValueError(f"{path}: truncated read ({len(out)}/{n} bytes)")
    return out


def read_file_meta(path: str) -> FileMeta:
    """Parse the container header only: magic, metadata map, sync marker.

    Reads exactly the header bytes — an out-of-core planner must not pull
    whole multi-GB shards through host RAM just to learn their schema.
    """
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if _read_exact(f, 4, path) != _MAGIC:
            raise ValueError(f"{path} is not an Avro container file")
        meta: dict[str, bytes] = {}
        while True:
            n = _read_varint_long(f, path)
            if n == 0:
                break
            if n < 0:  # block with byte-size prefix
                n = -n
                _read_varint_long(f, path)
            for _ in range(n):
                klen = _read_varint_long(f, path)
                key = _read_exact(f, klen, path).decode("utf-8")
                vlen = _read_varint_long(f, path)
                meta[key] = _read_exact(f, vlen, path)
        sync = _read_exact(f, _SYNC_LEN, path)
        header_end = f.tell()
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"{path}: unsupported codec '{codec}'")
    if "avro.schema" not in meta:
        raise ValueError(f"{path}: header lacks avro.schema")
    return FileMeta(
        path=path,
        schema_json=meta["avro.schema"].decode(),
        codec=codec,
        sync=sync,
        header_end=header_end,
        file_bytes=size,
    )


def scan_blocks(meta: FileMeta) -> Iterator[BlockInfo]:
    """Walk the block index of one file: two varints + a seek per block.

    Verifies every trailing sync marker — a corrupt block surfaces at
    PLAN time with its byte offset, not as garbage rows mid-stream.
    """
    with open(meta.path, "rb") as f:
        f.seek(meta.header_end)
        pos = meta.header_end
        while pos < meta.file_bytes:
            n_records = _read_varint_long(f, meta.path)
            payload = _read_varint_long(f, meta.path)
            if n_records < 0 or payload < 0:
                raise ValueError(
                    f"{meta.path}: negative block header at byte {pos}"
                )
            f.seek(payload, os.SEEK_CUR)
            if _read_exact(f, _SYNC_LEN, meta.path) != meta.sync:
                raise ValueError(
                    f"{meta.path}: sync marker mismatch after block at "
                    f"byte {pos} (corrupt block)"
                )
            end = f.tell()
            yield BlockInfo(offset=pos, n_records=n_records,
                            nbytes=end - pos)
            pos = end


def plan_chunks(
    paths: Sequence[str], chunk_rows: int
) -> tuple[list[FileMeta], list[ChunkPlan]]:
    """Assign whole-block runs of ``paths`` (in order) to chunks of at
    least ``chunk_rows`` rows (the last chunk of each file may be
    smaller). Returns ``(file metas, plans)``; plan order IS the stream
    order and is a pure function of the inputs.
    """
    if chunk_rows < 1:
        raise ValueError("chunk_rows must be >= 1")
    metas: list[FileMeta] = []
    plans: list[ChunkPlan] = []
    row_start = 0
    for path in paths:
        meta = read_file_meta(path)
        metas.append(meta)
        start = None
        rows = 0
        blocks = 0
        end = meta.header_end
        for blk in scan_blocks(meta):
            if blk.n_records == 0:
                continue  # empty block: nothing to decode, skip entirely
            if start is None:
                start = blk.offset
            rows += blk.n_records
            blocks += 1
            end = blk.offset + blk.nbytes
            if rows >= chunk_rows:
                plans.append(
                    ChunkPlan(
                        index=len(plans),
                        path=path,
                        byte_start=start,
                        byte_end=end,
                        n_rows=rows,
                        row_start=row_start,
                        n_blocks=blocks,
                    )
                )
                row_start += rows
                start, rows, blocks = None, 0, 0
        if start is not None:
            plans.append(
                ChunkPlan(
                    index=len(plans),
                    path=path,
                    byte_start=start,
                    byte_end=end,
                    n_rows=rows,
                    row_start=row_start,
                    n_blocks=blocks,
                )
            )
            row_start += rows
    return metas, plans


def total_rows(plans: Sequence[ChunkPlan]) -> int:
    return sum(p.n_rows for p in plans)


def plans_for_host(
    plans: Sequence[ChunkPlan], process_id: int, num_processes: int
) -> list[ChunkPlan]:
    """The deterministic per-host slice of a global chunk plan: chunk
    ``i`` belongs to host ``i % num_processes`` (round-robin over the
    global order, so host loads stay balanced whatever the file sizes).

    This is a pure function of ``(plans, num_processes)`` — no
    coordination state — which is what makes SURVIVOR-ELASTIC resume
    work: when a fleet member dies and the fit relaunches on fewer
    hosts, every survivor recomputes the split for the new fleet size
    and the dead host's chunks land on survivors automatically. Replay
    from a checkpoint's ``next_chunk`` then re-decodes exactly the rows
    the old fleet would have, in the same global order.
    """
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"process_id {process_id} out of range for "
            f"{num_processes} host(s)"
        )
    return [p for p in plans if p.index % num_processes == process_id]
