"""Out-of-core GameDataset assembly from a :class:`ChunkStream`.

The host only ever holds the staging ring; the feature payload (COO
values/rows/cols — the bytes that dwarf everything else) accumulates
DEVICE-side, written chunk-by-chunk into growable HBM buffers with
donated ``dynamic_update_slice`` programs and trimmed to the exact nnz at
the end. Because chunks arrive in deterministic plan order and each
chunk's padded tail is overwritten by its successor (capacities are
monotone along the stream), the assembled arrays are BIT-IDENTICAL to
what the one-shot in-core reader produces — an out-of-core fit matches
the in-core fit's loss because it trains on the same arrays.

Row scalars (response/offset/weight, exact f64) and id-column codes are
tiny (tens of bytes/row vs the feature payload) and stay host-side, which
is what GameDataset wants anyway.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.ingest.pipeline import ChunkStream, IngestSpec
from photon_ml_tpu.ops.sparse import SparseBatch


@lru_cache(maxsize=2)
def _chunk_writer(donate: bool):
    def write(bv, br, bc, v, r, c, off, base):
        bv = jax.lax.dynamic_update_slice(bv, v, (off,))
        br = jax.lax.dynamic_update_slice(br, r + base, (off,))
        bc = jax.lax.dynamic_update_slice(bc, c, (off,))
        return bv, br, bc

    # multi_shape: buffer sizes step geometrically and chunk capacities
    # may step once after a growth — a small, by-design signature set
    return telemetry.instrumented_jit(
        write,
        name="ingest_assemble_write",
        multi_shape=True,
        donate_argnums=(0, 1, 2) if donate else (),
    )


class ShardAssembler:
    """Accumulate one feature shard's COO on device, chunk by chunk."""

    def __init__(self, num_features: int, initial_nnz: int,
                 donate: bool = True):
        self.num_features = int(num_features)
        cap = max(int(initial_nnz), 1)
        self._v = jnp.zeros(cap, jnp.float32)
        self._r = jnp.zeros(cap, jnp.int32)
        self._c = jnp.zeros(cap, jnp.int32)
        self._nnz = 0
        self._donate = donate

    def _ensure(self, need: int) -> None:
        cap = self._v.shape[0]
        if need <= cap:
            return
        new_cap = max(cap * 2, need)
        extra = new_cap - cap
        # growth is rare (geometric) — the eager concatenate's copy is
        # acceptable off the critical path
        self._v = jnp.concatenate([self._v, jnp.zeros(extra, jnp.float32)])
        self._r = jnp.concatenate([self._r, jnp.zeros(extra, jnp.int32)])
        self._c = jnp.concatenate([self._c, jnp.zeros(extra, jnp.int32)])

    def add(self, batch: SparseBatch, nnz_used: int, row_start: int) -> None:
        """Write one chunk's padded arrays at the running nnz offset; the
        padded tail is overwritten by the next chunk (or trimmed)."""
        self._ensure(self._nnz + batch.nnz)
        self._v, self._r, self._c = _chunk_writer(self._donate)(
            self._v, self._r, self._c,
            batch.values, batch.rows, batch.cols,
            jnp.int32(self._nnz), jnp.int32(row_start),
        )
        self._nnz += int(nnz_used)

    def finish(self, labels: np.ndarray) -> SparseBatch:
        """Trim to the exact nnz and attach the row scalars with the
        in-core reader's ``from_coo`` contract: labels real, per-shard
        offsets/weights at their defaults (zeros/ones) — the REAL
        offset/weight columns live on the GameDataset and are attached
        by ``batch_for`` at solve time, identically for both readers."""
        total = self._nnz
        n = len(labels)
        return SparseBatch(
            values=self._v[:total],
            rows=self._r[:total],
            cols=self._c[:total],
            labels=labels.astype(np.float32),
            offsets=np.zeros(n, np.float32),
            weights=np.ones(n, np.float32),
            num_features=self.num_features,
        )


def read_game_dataset_streamed(
    paths,
    feature_shards: Optional[Mapping[str, Sequence[str]]] = None,
    index_maps: Optional[Mapping] = None,
    id_columns: Sequence[str] = (),
    add_intercept: bool = True,
    is_response_required: bool = True,
    spec: Optional[IngestSpec] = None,
    placement=None,
    return_index_maps: bool = False,
):
    """The out-of-core counterpart of ``read_game_dataset_from_avro``.

    Streams the shard set through a :class:`ChunkStream` (parallel block
    decode into the staging ring, double-buffered upload) and assembles a
    GameDataset whose feature payload lives on DEVICE; arrays are
    bit-identical to the in-core reader's. ``index_maps`` are built with
    the cheap vocab-only scan when absent (an out-of-core stream cannot
    discover the feature space as it goes).
    """
    from photon_ml_tpu.data.avro import (
        _as_paths,
        build_index_maps_from_avro,
    )
    from photon_ml_tpu.game.dataset import GameDataset, IdColumn

    feature_shards = dict(feature_shards or {"features": ("features",)})
    file_list = _as_paths(paths)
    if index_maps is None:
        index_maps = build_index_maps_from_avro(
            file_list, feature_shards, add_intercept=add_intercept
        )
    stream = ChunkStream(
        file_list,
        feature_shards=feature_shards,
        index_maps=index_maps,
        id_columns=id_columns,
        add_intercept=add_intercept,
        is_response_required=is_response_required,
        spec=spec,
        placement=placement,
    )
    n = stream.total_rows
    if n == 0:
        stream.close()
        raise ValueError(f"no records in {file_list}")
    labels = np.empty(n, np.float64)
    offsets = np.empty(n, np.float64)
    weights = np.empty(n, np.float64)
    codes = {c: np.empty(n, np.int64) for c in id_columns}
    est = n * (spec.nnz_per_row_hint if spec else 32)
    asms = {
        name: ShardAssembler(len(index_maps[name]), est)
        for name in feature_shards
    }
    with telemetry.span(
        "ingest_assemble", rows=n, chunks=len(stream.plans)
    ), stream:
        for chunk in stream:
            sl = slice(chunk.row_start, chunk.row_start + chunk.rows)
            labels[sl] = chunk.labels
            offsets[sl] = chunk.offsets
            weights[sl] = chunk.weights
            for col in id_columns:
                codes[col][sl] = chunk.id_codes[col]
            for name, asm in asms.items():
                asm.add(
                    chunk.shards[name], chunk.nnz_used[name],
                    chunk.row_start,
                )
    shards = {name: asm.finish(labels) for name, asm in asms.items()}
    # id codes: sort the stream-global vocab and rank-remap, exactly like
    # the in-core reader (models score via searchsorted over sorted vocab)
    id_cols = {}
    for col in id_columns:
        vocab = stream.id_vocabulary(col)
        order = np.argsort(vocab)
        rank = np.empty(len(order), np.int64)
        rank[order] = np.arange(len(order))
        raw = codes[col]
        id_cols[col] = IdColumn(
            codes=rank[raw] if len(raw) else raw, vocab=vocab[order]
        )
    ds = GameDataset(
        response=labels,
        offset=offsets,
        weight=weights,
        feature_shards=shards,
        id_columns=id_cols,
    )
    return (ds, index_maps) if return_index_maps else ds
