"""Per-chunk block-range decoding into staging buffers.

One chunk = a run of whole Avro blocks inside one file
(:class:`~photon_ml_tpu.ingest.planner.ChunkPlan`). The worker reads
exactly those bytes, decodes them with the native C++ interpreter
(``native/avro_decode.cpp`` via :mod:`photon_ml_tpu.data.avro_native`)
when available and with the pure-Python schema walker otherwise, and
writes the result DIRECTLY into a pre-allocated
:class:`~photon_ml_tpu.ingest.buffers.StagingBuffer` in padded
SparseBatch layout. Both paths produce bit-identical arrays — the
pipeline degrades to Python decode workers, it never crashes for lack of
a toolchain (set ``PHOTON_NO_NATIVE=1`` to force the fallback).

The finalize step is shared: label presence check, f64->f32 casts into
the padded layout, and the sorted per-row intercept interleave — the
same O(nnz) merge the one-shot reader uses, so a streamed dataset is
byte-for-byte the in-core dataset.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Callable, Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu import faults
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, feature_key
from photon_ml_tpu.ingest.buffers import StagingBuffer
from photon_ml_tpu.ingest.errors import ChunkDecodeError
from photon_ml_tpu.ingest.planner import ChunkPlan, FileMeta

# Injection seam on the chunk file read — an `io` rule here raises an
# InjectedIOError (an OSError), exactly the transient flaky-read shape the
# pipeline's bounded per-chunk retry exists for.
_FP_DECODE_READ = faults.register_point(
    "ingest.decode.read",
    description="chunk byte-range read (io action = transient flaky read)",
)

#: grow callback: (buffer, shard index, needed raw nnz, preserve) -> None;
#: ``preserve`` is how many already-written scratch entries must survive
#: the reallocation (the python decoder grows mid-fill)
GrowFn = Callable[[StagingBuffer, int, int, int], None]


@dataclasses.dataclass
class DecodeContext:
    """Everything a decode worker needs, built ONCE per stream.

    ``use_native`` is decided up front for the whole stream (native
    library present, every file's schema compiles to a program, index
    maps enumerable) so chunk decode is branch-free; either path fills
    the same staging layout.
    """

    metas: Mapping[str, FileMeta]
    shard_names: tuple[str, ...]
    feature_shards: Mapping[str, tuple[str, ...]]
    index_maps: Mapping[str, Mapping[str, int]]
    id_columns: tuple[str, ...]
    add_intercept: bool
    is_response_required: bool
    intercept_cols: tuple[int, ...]  # per shard; -1 = no intercept slot
    use_native: bool
    # native-path artifacts (None on the python path)
    programs: Optional[Mapping[str, np.ndarray]] = None  # path -> program
    feat_bytes: Optional[np.ndarray] = None
    feat_offs: Optional[np.ndarray] = None
    feat_ids: Optional[np.ndarray] = None
    shard_key_counts: Optional[np.ndarray] = None
    id_blob: Optional[np.ndarray] = None
    id_offs: Optional[np.ndarray] = None
    # python-path artifacts
    schemas: Optional[Mapping[str, dict]] = None  # path -> parsed schema
    named: Optional[Mapping[str, dict]] = None  # path -> named-type table


def build_decode_context(
    metas: Sequence[FileMeta],
    feature_shards: Mapping[str, Sequence[str]],
    index_maps: Mapping[str, Mapping[str, int]],
    id_columns: Sequence[str] = (),
    add_intercept: bool = True,
    is_response_required: bool = True,
) -> DecodeContext:
    from photon_ml_tpu.data.avro_native import (
        _concat_strs,
        _lib,
        compile_program,
        index_map_blobs,
    )

    shard_names = tuple(feature_shards)
    feature_shards = {s: tuple(feature_shards[s]) for s in shard_names}
    intercept_cols = tuple(
        index_maps[s].get(INTERCEPT_KEY) if add_intercept else -1
        for s in shard_names
    )
    ctx = DecodeContext(
        metas={m.path: m for m in metas},
        shard_names=shard_names,
        feature_shards=feature_shards,
        index_maps=dict(index_maps),
        id_columns=tuple(id_columns),
        add_intercept=bool(add_intercept),
        is_response_required=bool(is_response_required),
        intercept_cols=intercept_cols,
        use_native=False,
    )

    lib = _lib()
    blobs = index_map_blobs(list(shard_names), index_maps) if lib else None
    programs: dict[str, np.ndarray] = {}
    if lib is not None and blobs is not None:
        prog_cache: dict[str, Optional[np.ndarray]] = {}
        for m in metas:
            prog = prog_cache.get(m.schema_json)
            if prog is None and m.schema_json not in prog_cache:
                prog = compile_program(
                    json.loads(m.schema_json), feature_shards, id_columns
                )
                prog_cache[m.schema_json] = prog
            if prog is None:
                programs = {}
                break
            programs[m.path] = prog
    if programs:
        id_blob, id_offs = _concat_strs(list(id_columns))
        ctx.use_native = True
        ctx.programs = programs
        (ctx.feat_bytes, ctx.feat_offs, ctx.feat_ids,
         ctx.shard_key_counts) = blobs
        ctx.id_blob, ctx.id_offs = id_blob, id_offs
    else:
        from photon_ml_tpu.data.avro import _collect_named

        schemas: dict[str, dict] = {}
        named: dict[str, dict] = {}
        for m in metas:
            schema = json.loads(m.schema_json)
            schemas[m.path] = schema
            table: dict = {}
            _collect_named(schema, table)
            named[m.path] = table
        ctx.schemas = schemas
        ctx.named = named
    return ctx


def _read_range(plan: ChunkPlan) -> bytes:
    faults.fault_point(_FP_DECODE_READ)
    with open(plan.path, "rb") as f:
        f.seek(plan.byte_start)
        raw = f.read(plan.nbytes)
    if len(raw) != plan.nbytes:
        raise ChunkDecodeError(
            plan.path, plan.index,
            f"short read ({len(raw)}/{plan.nbytes} bytes) — file changed "
            "since planning?",
        )
    return raw


def decode_chunk(
    ctx: DecodeContext, plan: ChunkPlan, buf: StagingBuffer, grow: GrowFn
) -> None:
    """Decode ``plan``'s byte range into ``buf`` (padded, finalized)."""
    raw = _read_range(plan)
    if ctx.use_native:
        raw_nnz = _decode_native(ctx, plan, raw, buf, grow)
    else:
        raw_nnz = _decode_python(ctx, plan, raw, buf, grow)
    _finalize(ctx, plan, buf, raw_nnz)
    buf.plan = plan


# ---------------------------------------------------------------------------
# native path
# ---------------------------------------------------------------------------


def _decode_native(
    ctx: DecodeContext, plan: ChunkPlan, raw: bytes, buf: StagingBuffer,
    grow: GrowFn,
) -> list[int]:
    from photon_ml_tpu.data.avro_native import _decode_vocab, _lib

    lib = _lib()
    meta = ctx.metas[plan.path]
    data = np.frombuffer(raw, np.uint8)
    sync = np.frombuffer(meta.sync, np.uint8)
    handle = lib.avro_parse(
        data, len(data), 0, sync,
        1 if meta.codec == "deflate" else 0,
        ctx.programs[plan.path], len(ctx.programs[plan.path]),
        len(ctx.shard_names),
        ctx.feat_bytes, ctx.feat_offs, ctx.feat_ids, ctx.shard_key_counts,
        len(ctx.id_columns), ctx.id_blob, ctx.id_offs,
        1,  # parallelism lives ACROSS workers; one thread per chunk
    )
    if not handle:
        raise ChunkDecodeError(
            plan.path, plan.index, lib.avro_last_error().decode()
        )
    try:
        n = int(lib.avro_rows(handle))
        if n != plan.n_rows:
            raise ChunkDecodeError(
                plan.path, plan.index,
                f"decoded {n} rows but the plan promised {plan.n_rows}",
            )
        buf.reset_rows(n)
        lib.avro_fill_scalars(
            handle, buf.scratch_labels, buf.scratch_offsets,
            buf.scratch_weights, buf.label_seen,
        )
        raw_nnz: list[int] = []
        for si in range(len(ctx.shard_names)):
            nnz = int(lib.avro_shard_nnz(handle, si))
            if nnz > buf.shards[si].raw_cap:
                grow(buf, si, nnz, 0)
            st = buf.shards[si]
            lib.avro_fill_coo(
                handle, si, st.scratch_vals[:nnz], st.scratch_rows[:nnz],
                st.scratch_cols[:nnz],
            )
            raw_nnz.append(nnz)
        buf.id_vocabs = []
        for ci in range(len(ctx.id_columns)):
            codes = buf.id_codes[ci][:n]
            nb = lib.avro_id_vocab_bytes(handle, ci)
            nv = lib.avro_id_vocab_size(handle, ci)
            blob = np.empty(nb, np.uint8)
            offs = np.empty(nv + 1, np.int64)
            lib.avro_fill_ids(handle, ci, codes, blob, offs)
            if np.any(codes < 0):
                bad = int(np.argmax(codes < 0))
                raise ChunkDecodeError(
                    plan.path, plan.index,
                    f"record {bad} lacks id column "
                    f"'{ctx.id_columns[ci]}' (top-level field or "
                    "metadataMap entry)",
                )
            buf.id_vocabs.append(_decode_vocab(blob, offs))
    finally:
        lib.avro_free(handle)
    return raw_nnz


# ---------------------------------------------------------------------------
# pure-python fallback path
# ---------------------------------------------------------------------------


def _decode_python(
    ctx: DecodeContext, plan: ChunkPlan, raw: bytes, buf: StagingBuffer,
    grow: GrowFn,
) -> list[int]:
    from photon_ml_tpu.data.avro import _Reader, _decode

    meta = ctx.metas[plan.path]
    schema = ctx.schemas[plan.path]
    named = ctx.named[plan.path]
    imaps = [ctx.index_maps[s] for s in ctx.shard_names]
    bags = [ctx.feature_shards[s] for s in ctx.shard_names]

    buf.reset_rows(plan.n_rows)
    cursors = [0] * len(ctx.shard_names)
    interns: list[dict] = [{} for _ in ctx.id_columns]
    row = 0
    r = _Reader(raw)
    while r.pos < len(raw):
        n_block = r.read_long()
        size = r.read_long()
        payload = r.read_fixed(size)
        if meta.codec == "deflate":
            payload = zlib.decompress(payload, -15)
        if r.read_fixed(16) != meta.sync:
            raise ChunkDecodeError(
                plan.path, plan.index, "sync marker mismatch (corrupt block)"
            )
        br = _Reader(payload)
        for _ in range(n_block):
            if row >= plan.n_rows:
                raise ChunkDecodeError(
                    plan.path, plan.index,
                    f"more rows than the plan's {plan.n_rows}",
                )
            rec = _decode(br, schema, named)
            label = rec.get("label")
            buf.label_seen[row] = 0 if label is None else 1
            buf.scratch_labels[row] = 0.0 if label is None else float(label)
            off = rec.get("offset")
            buf.scratch_offsets[row] = 0.0 if off is None else float(off)
            wgt = rec.get("weight")  # explicit 0.0 weights must survive
            buf.scratch_weights[row] = 1.0 if wgt is None else float(wgt)
            meta_map = rec.get("metadataMap") or {}
            for ci, c in enumerate(ctx.id_columns):
                v = rec.get(c)
                if v is None:  # absent/null top-level field -> metadataMap
                    v = meta_map.get(c)
                if v is None:
                    raise ChunkDecodeError(
                        plan.path, plan.index,
                        f"record {row} lacks id column '{c}' (top-level "
                        "field or metadataMap entry)",
                    )
                table = interns[ci]
                code = table.get(v)
                if code is None:
                    code = len(table)
                    table[v] = code
                buf.id_codes[ci, row] = code
            for si, shard_bags in enumerate(bags):
                st = buf.shards[si]
                cur = cursors[si]
                imap = imaps[si]
                for bag in shard_bags:
                    for f in rec.get(bag) or ():
                        idx = imap.get(feature_key(f["name"], f["term"]))
                        if idx >= 0:
                            if cur >= st.raw_cap:
                                grow(buf, si, cur + 1, cur)
                                st = buf.shards[si]
                            st.scratch_vals[cur] = float(f["value"])
                            st.scratch_rows[cur] = row
                            st.scratch_cols[cur] = idx
                            cur += 1
                cursors[si] = cur
            row += 1
    if row != plan.n_rows:
        raise ChunkDecodeError(
            plan.path, plan.index,
            f"decoded {row} rows but the plan promised {plan.n_rows}",
        )
    buf.id_vocabs = [
        np.asarray(list(table)) for table in interns
    ]
    return cursors


# ---------------------------------------------------------------------------
# shared finalize: casts + intercept interleave into the padded layout
# ---------------------------------------------------------------------------


def _interleave_intercept_into(
    vals: np.ndarray, rws: np.ndarray, cls: np.ndarray, nnz: int, n: int,
    icept: int, out_v: np.ndarray, out_r: np.ndarray, out_c: np.ndarray,
) -> int:
    """The one-shot reader's O(nnz) sorted intercept merge, writing into
    pre-allocated f32/i32 output arrays: one intercept nnz lands right
    after each row's features, so the result STAYS row-sorted."""
    dest = np.arange(nnz) + rws[:nnz]
    out_v[dest] = vals[:nnz]
    out_r[dest] = rws[:nnz]
    out_c[dest] = cls[:nnz]
    idest = (
        np.searchsorted(rws[:nnz], np.arange(n), side="right") + np.arange(n)
    )
    out_v[idest] = 1.0
    out_r[idest] = np.arange(n)
    out_c[idest] = icept
    return nnz + n


def _finalize(
    ctx: DecodeContext, plan: ChunkPlan, buf: StagingBuffer,
    raw_nnz: Sequence[int],
) -> None:
    n = plan.n_rows
    if ctx.is_response_required:
        missing = buf.label_seen[:n] == 0
        if np.any(missing):
            bad = int(np.argmax(missing))
            raise ChunkDecodeError(
                plan.path, plan.index,
                f"record {bad} of the chunk (global row "
                f"{plan.row_start + bad}) has no label",
            )
    # f64 scratch -> padded f32 layout (casting assignment, no alloc)
    buf.labels[:n] = buf.scratch_labels[:n]
    buf.offsets[:n] = buf.scratch_offsets[:n]
    buf.weights[:n] = buf.scratch_weights[:n]
    for si, nnz in enumerate(raw_nnz):
        st = buf.shards[si]
        icept = ctx.intercept_cols[si]
        if icept >= 0:
            used = _interleave_intercept_into(
                st.scratch_vals, st.scratch_rows, st.scratch_cols, nnz, n,
                icept, st.values, st.rows, st.cols,
            )
        else:
            st.values[:nnz] = st.scratch_vals[:nnz]
            st.rows[:nnz] = st.scratch_rows[:nnz]
            st.cols[:nnz] = st.scratch_cols[:nnz]
            used = nnz
        # padded tail: value 0 (inert everywhere), rows at the last padded
        # row (keeps `rows` non-decreasing), col 0 — SparseBatch convention
        st.values[used:] = 0.0
        st.rows[used:] = buf.rows_cap - 1
        st.cols[used:] = 0
        st.nnz_used = used
