"""Device-speed ingestion: a staged, threaded pipeline that turns a
directory of Avro shards into a backpressured stream of device-ready
chunks.

The solvers eat 8M+ rows/s/chip while the one-shot Avro reader delivers
~66-128K rows/s (BENCH_r04/r05 ``avro_ingest_*``) — any real end-to-end
fit was ~60x ingest-bound (ROADMAP item 2). This package is the subsystem
between the block-parallel decoder (``data/avro_native.py``) and the
device:

- :mod:`.planner` — a file-split planner that assigns sync-delimited Avro
  block ranges to decode workers with DETERMINISTIC chunk ordering
  (stable across runs, so a checkpoint resume replays the same stream
  from the next chunk boundary).
- :mod:`.buffers` — a ring of pre-allocated staging buffers that decode
  workers fill directly in the padded :class:`~photon_ml_tpu.ops.sparse.
  SparseBatch` layout: no per-chunk re-allocation and no COO->padded
  rebuild on the critical path.
- :mod:`.decode` — the per-chunk block-range decoder: the native C++
  interpreter when available, the pure-Python schema walker otherwise
  (identical arrays either way — the pipeline degrades, never crashes).
- :mod:`.pipeline` — :class:`ChunkStream`: decode workers -> deterministic
  reorder -> an async double-buffered uploader that ``device_put``s chunk
  N+1 while chunk N's solve runs, with bounded queues and a typed
  stall/backpressure protocol (:class:`IngestStall`).
- :mod:`.assemble` — :func:`read_game_dataset_streamed`: an out-of-core
  GameDataset build; the host only ever holds the staging ring while the
  feature payload accumulates device-side, bit-identical to the in-core
  reader's arrays.
- :mod:`.prefetch` — :func:`double_buffered`, the generic bounded
  background feeder adopted by ``game/streaming.py`` (its inline feeding
  loop is gone; the trainer is a consumer now).

Telemetry: ``ingest.rows`` / ``ingest.chunks`` / ``ingest.stalls`` /
``ingest.queue_depth`` / ``ingest.solve_waits`` plus per-stage spans, all
surfaced in the heartbeat and the RunReport "Ingestion" section — the
report shows whether the solve ever waited on data.
"""

from photon_ml_tpu.ingest.errors import (  # noqa: F401
    ChunkDecodeError,
    IngestConfigError,
    IngestError,
    IngestStall,
    PipelineClosed,
)
from photon_ml_tpu.ingest.planner import (  # noqa: F401
    ChunkPlan,
    FileMeta,
    plan_chunks,
    plans_for_host,
    read_file_meta,
    scan_blocks,
)
from photon_ml_tpu.ingest.pipeline import (  # noqa: F401
    ChunkStream,
    DeviceChunk,
    IngestSpec,
)
from photon_ml_tpu.ingest.assemble import (  # noqa: F401
    read_game_dataset_streamed,
)
from photon_ml_tpu.ingest.prefetch import double_buffered  # noqa: F401

__all__ = [
    "ChunkDecodeError",
    "ChunkPlan",
    "ChunkStream",
    "DeviceChunk",
    "FileMeta",
    "IngestConfigError",
    "IngestError",
    "IngestSpec",
    "IngestStall",
    "PipelineClosed",
    "double_buffered",
    "plan_chunks",
    "plans_for_host",
    "read_file_meta",
    "read_game_dataset_streamed",
    "scan_blocks",
]
