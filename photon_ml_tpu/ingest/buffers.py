"""Pre-allocated staging buffers for decode workers.

A decode worker writes a chunk DIRECTLY into the padded
:class:`~photon_ml_tpu.ops.sparse.SparseBatch` layout the solvers consume
(f32 values, i32 rows/cols, f32 labels/offsets/weights), plus the f64/i64
scratch views the native decoder fills — no per-chunk allocation and no
COO->padded rebuild on the critical path. Buffers live in a bounded ring:
decode blocks when the consumer stops draining (backpressure), and the
ring size IS the pipeline's host-resident budget.

Capacity: row capacity is fixed by the plan (``chunk_rows`` + the largest
block's worth of slack); nnz capacity starts at ``rows_cap *
nnz_per_row_hint`` and grows geometrically when a chunk overflows it
(``ingest.buffer_growths`` counts these). Growth is coordinated by the
pipeline so every buffer converges to one stream-global capacity — chunk
batches keep ONE jit signature and the device assembler's sequential
overwrite stays exact.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.ingest.errors import IngestStall, PipelineClosed
from photon_ml_tpu.ingest.planner import ChunkPlan

# Injection seam on the staging-ring hand-off: a firing rule here is a
# decode worker failing BETWEEN chunks (buffer acquired but never filled
# is impossible — the fault fires before the pop).
_FP_RING_ACQUIRE = faults.register_point(
    "ingest.ring.acquire",
    description="staging-ring buffer acquisition by a decode worker",
)


class ShardStage:
    """Per-feature-shard staging arrays of one buffer."""

    __slots__ = (
        "raw_cap", "nnz_cap", "values", "rows", "cols",
        "scratch_vals", "scratch_rows", "scratch_cols", "nnz_used",
    )

    def __init__(self, raw_cap: int, rows_cap: int, intercept: bool):
        self.nnz_used = 0
        self._alloc(raw_cap, rows_cap, intercept)

    def _alloc(self, raw_cap: int, rows_cap: int, intercept: bool) -> None:
        self.raw_cap = int(raw_cap)
        # final layout holds raw nnz + one optional intercept nnz per row
        self.nnz_cap = self.raw_cap + (rows_cap if intercept else 0)
        self.values = np.zeros(self.nnz_cap, np.float32)
        self.rows = np.full(self.nnz_cap, rows_cap - 1, np.int32)
        self.cols = np.zeros(self.nnz_cap, np.int32)
        self.scratch_vals = np.empty(self.raw_cap, np.float64)
        self.scratch_rows = np.empty(self.raw_cap, np.int64)
        self.scratch_cols = np.empty(self.raw_cap, np.int64)

    def grow(
        self, raw_cap: int, rows_cap: int, intercept: bool,
        preserve: int = 0,
    ) -> None:
        """Reallocate to ``raw_cap``, keeping the first ``preserve``
        scratch entries (the python decoder grows MID-fill)."""
        if raw_cap <= self.raw_cap:
            return
        old = (self.scratch_vals, self.scratch_rows, self.scratch_cols)
        self._alloc(raw_cap, rows_cap, intercept)
        if preserve:
            self.scratch_vals[:preserve] = old[0][:preserve]
            self.scratch_rows[:preserve] = old[1][:preserve]
            self.scratch_cols[:preserve] = old[2][:preserve]

    @property
    def nbytes(self) -> int:
        return sum(
            a.nbytes
            for a in (self.values, self.rows, self.cols, self.scratch_vals,
                      self.scratch_rows, self.scratch_cols)
        )


class StagingBuffer:
    """One ring slot: padded batch arrays + decoder scratch for a chunk."""

    def __init__(
        self,
        rows_cap: int,
        raw_nnz_cap: int,
        n_shards: int,
        n_id_columns: int,
        intercept: bool,
    ):
        self.rows_cap = int(rows_cap)
        self.intercept = bool(intercept)
        self.shards = [
            ShardStage(raw_nnz_cap, rows_cap, intercept)
            for _ in range(n_shards)
        ]
        self.labels = np.zeros(rows_cap, np.float32)
        self.offsets = np.zeros(rows_cap, np.float32)
        self.weights = np.zeros(rows_cap, np.float32)
        self.scratch_labels = np.empty(rows_cap, np.float64)
        self.scratch_offsets = np.empty(rows_cap, np.float64)
        self.scratch_weights = np.empty(rows_cap, np.float64)
        self.label_seen = np.empty(rows_cap, np.uint8)
        self.id_codes = np.empty((n_id_columns, rows_cap), np.int64)
        # -- fill state (set by the decode worker, read downstream) --------
        self.plan: Optional[ChunkPlan] = None
        self.rows_used = 0
        self.id_vocabs: list[np.ndarray] = []

    @property
    def nbytes(self) -> int:
        return (
            sum(s.nbytes for s in self.shards)
            + self.labels.nbytes * 3
            + self.scratch_labels.nbytes * 3
            + self.label_seen.nbytes
            + self.id_codes.nbytes
        )

    def reset_rows(self, n: int) -> None:
        """Start a fill of ``n`` rows: clear the padded row region so a
        previous chunk's tail can never leak into this one (padded rows
        MUST have weight 0 — the loss-parity invariant)."""
        self.rows_used = int(n)
        self.labels[n:] = 0.0
        self.offsets[n:] = 0.0
        self.weights[n:] = 0.0


class BufferRing:
    """Bounded free-list of staging buffers with a condition variable.

    ``acquire`` blocks until a buffer is free — this is the backpressure
    edge between decode and the consumer — and raises a typed
    :class:`IngestStall` after ``stall_timeout_s`` so a wedged pipeline
    fails loudly instead of hanging a training job forever.
    """

    def __init__(self, buffers: Sequence[StagingBuffer],
                 stall_timeout_s: float):
        self._cv = threading.Condition()
        self._free: deque[StagingBuffer] = deque(buffers)
        self._all = tuple(buffers)
        self._closed = False
        self._stall_timeout_s = float(stall_timeout_s)
        telemetry.gauge("ingest.staging_bytes").set(self.nbytes)

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self._all)

    @property
    def capacity(self) -> int:
        return len(self._all)

    def acquire(self) -> StagingBuffer:
        # a registered L017 BORROWED-memory source: the returned slot is
        # recycled the moment release() runs, so its arrays must never
        # reach a donated jit argument un-laundered (the dataflow gate
        # tracks this; renaming acquire fails the gate with W002 —
        # tools/analysis/dataflow.py::RING_SOURCES)
        faults.fault_point(_FP_RING_ACQUIRE)
        with self._cv:
            waited = self._cv.wait_for(
                lambda: self._free or self._closed,
                timeout=self._stall_timeout_s,
            )
            if self._closed:
                raise PipelineClosed("buffer ring closed")
            if not waited:
                telemetry.counter("ingest.stalls").inc()
                raise IngestStall(
                    "decode", self._stall_timeout_s,
                    "no free staging buffer (consumer not draining?)",
                )
            return self._free.popleft()

    def release(self, buf: StagingBuffer) -> None:
        with self._cv:
            buf.plan = None
            self._free.append(buf)
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
