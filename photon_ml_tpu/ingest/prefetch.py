"""``double_buffered``: the generic bounded background feeder.

The inline feeding loop ``game/streaming.py`` used to carry (enqueue
chunk i+1's host->device transfer, then consume chunk i) is a pipeline
pattern, not a trainer concern — this is its one shared home. A worker
thread runs ``feed(item)`` up to ``depth`` items ahead of the consumer
behind a bounded queue; the consumer iterates ``(item, fed)`` pairs in
order. Feeding in a real thread (instead of relying purely on async
dispatch) also overlaps HOST-side feed work — decode, pinning, retry
sleeps — with the solve, which async dispatch alone never could.

Stall protocol matches the ingest pipeline: a consumer wait beyond
``stall_timeout_s`` raises :class:`IngestStall` (counter
``ingest.stalls``); feeder exceptions surface on the consumer thread at
the position they occurred, preserving error semantics of the old
inline loop.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Tuple, TypeVar

from photon_ml_tpu import telemetry
from photon_ml_tpu.ingest.errors import IngestStall

T = TypeVar("T")
R = TypeVar("R")

_END = object()


def double_buffered(
    items: Iterable[T],
    feed: Callable[[T], R],
    depth: int = 1,
    stall_timeout_s: float = 600.0,
    name: str = "prefetch",
) -> Iterator[Tuple[T, R]]:
    """Yield ``(item, feed(item))`` in order, feeding up to ``depth``
    items ahead in a background thread.

    ``depth=1`` is classic double buffering: the feeder prepares item
    i+1 while the consumer works on item i. Abandoning the generator
    (break / GeneratorExit) tears the feeder down promptly.
    """
    if depth < 1:
        raise ValueError("double_buffered depth must be >= 1")
    out: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    state_lock = threading.Lock()
    state: dict = {"error": None, "at": None}

    def _run() -> None:
        try:
            for item in items:
                if stop.is_set():
                    return
                with telemetry.span(f"{name}_feed"):
                    fed = feed(item)
                while not stop.is_set():
                    try:
                        out.put((item, fed), timeout=0.25)
                        break
                    except queue.Full:
                        continue
            while not stop.is_set():
                try:
                    out.put(_END, timeout=0.25)
                    return
                except queue.Full:
                    continue
        except BaseException as e:  # surface on the consumer thread
            with state_lock:
                state["error"] = e

    worker = threading.Thread(
        target=_run, name=f"{name}-feeder", daemon=True
    )
    worker.start()
    try:
        while True:
            t0 = time.monotonic()
            while True:
                # drain queued (successfully fed) items BEFORE surfacing
                # a feeder error: the old inline loop solved every chunk
                # fed ahead of the failure, and so must this one —
                # errors surface at the position they occurred
                try:
                    got = out.get_nowait()
                    break
                except queue.Empty:
                    pass
                with state_lock:
                    err = state["error"]
                if err is not None:
                    raise err
                try:
                    got = out.get(timeout=0.25)
                    break
                except queue.Empty:
                    if time.monotonic() - t0 > stall_timeout_s:
                        telemetry.counter("ingest.stalls").inc()
                        raise IngestStall(
                            "consume", stall_timeout_s,
                            f"{name} feeder produced nothing",
                        ) from None
            if got is _END:
                return
            yield got
    finally:
        stop.set()
        # unblock a put-blocked feeder so the join cannot hang
        while True:
            try:
                out.get_nowait()
            except queue.Empty:
                break
        worker.join(timeout=5.0)
