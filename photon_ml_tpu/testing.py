"""Synthetic data generators for tests, benchmarks, and examples.

Reference analog: photon-api util/GameTestUtils.scala:41-311 (factory
methods for datasets/problems/coordinates used across integration tests,
shipped in MAIN source) and photon-test-utils SparkTestUtils' balanced
binary / Poisson / linear draws with controlled sparsity. Everything here
returns plain numpy + framework types so the generators work identically
under CPU test meshes and real TPU benches.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.game.dataset import GameDataset, build_game_dataset
from photon_ml_tpu.ops.sparse import SparseBatch


@dataclasses.dataclass
class GLMProblem:
    """A generated GLM problem with its ground truth."""

    X: np.ndarray
    y: np.ndarray
    w_true: np.ndarray
    batch: SparseBatch


def generate_glm_problem(
    task: str = "logistic",
    n: int = 500,
    d: int = 10,
    density: float = 1.0,
    noise: float = 0.1,
    intercept: bool = False,
    weights: Optional[np.ndarray] = None,
    seed: int = 0,
) -> GLMProblem:
    """Labels drawn FROM the planted model so optimizers do real work
    (SparkTestUtils generateBenignLocalTestData* analog)."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    if density < 1.0:
        X *= rng.random((n, d)) < density
    if intercept:
        X[:, 0] = 1.0
    w = rng.normal(size=d)
    z = X @ w
    if task == "logistic" or task == "smoothed_hinge":
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float64)
    elif task == "squared":
        y = z + noise * rng.normal(size=n)
    elif task == "poisson":
        y = rng.poisson(np.exp(np.clip(0.3 * z, -3, 3))).astype(np.float64)
        w = 0.3 * w
    else:
        raise ValueError(f"unknown task '{task}'")
    batch = SparseBatch.from_dense(X, y, weights=weights)
    return GLMProblem(X=X, y=y, w_true=w, batch=batch)


def generate_game_dataset(
    task: str = "logistic",
    n_users: int = 20,
    rows_per_user: int = 15,
    fe_dim: int = 10,
    re_dim: int = 4,
    noise: float = 0.1,
    seed: int = 0,
) -> tuple[GameDataset, dict]:
    """A GLMix problem: global FE shard + per-user RE shard with planted
    global and per-user coefficients (GameTestUtils generateFixedEffect* /
    generateRandomEffect* analog). Returns (dataset, truth dict)."""
    rng = np.random.default_rng(seed)
    n = n_users * rows_per_user
    users = np.repeat(np.arange(n_users), rows_per_user)
    Xg = rng.normal(size=(n, fe_dim))
    Xu = rng.normal(size=(n, re_dim))
    w_global = rng.normal(size=fe_dim)
    w_users = rng.normal(size=(n_users, re_dim))
    z = Xg @ w_global + np.einsum("nd,nd->n", Xu, w_users[users])
    if task == "logistic":
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float64)
    elif task == "squared":
        y = z + noise * rng.normal(size=n)
    else:
        raise ValueError(f"unknown task '{task}' (logistic|squared)")
    data = build_game_dataset(
        response=y,
        feature_shards={
            "global": SparseBatch.from_dense(Xg, y),
            "user": SparseBatch.from_dense(Xu, y),
        },
        id_columns={"userId": users},
    )
    truth = {
        "w_global": w_global,
        "w_users": w_users,
        "users": users,
        "Xg": Xg,
        "Xu": Xu,
        "z": z,
    }
    return data, truth


def generate_low_rank_game_dataset(
    n_users: int = 40,
    rows_per_user: int = 20,
    d: int = 30,
    latent_dim: int = 2,
    noise: float = 0.05,
    seed: int = 0,
) -> tuple[GameDataset, dict]:
    """Per-user coefficients constrained to a shared latent subspace —
    the factored-random-effect ground truth (w_u = B^T z_u)."""
    rng = np.random.default_rng(seed)
    n = n_users * rows_per_user
    users = np.repeat(np.arange(n_users), rows_per_user)
    X = rng.normal(size=(n, d))
    B = rng.normal(size=(latent_dim, d)) / np.sqrt(d)
    Z = rng.normal(size=(n_users, latent_dim)) * 2.0
    W = Z @ B
    y = np.einsum("nd,nd->n", X, W[users]) + noise * rng.normal(size=n)
    data = build_game_dataset(
        response=y,
        feature_shards={"feats": SparseBatch.from_dense(X, y)},
        id_columns={"userId": users},
    )
    return data, {"B": B, "Z": Z, "W": W, "users": users, "X": X}


def write_libsvm(path: str, X: np.ndarray, y: np.ndarray) -> str:
    """Write (X, y) as LibSVM text (1-based feature ids, zero entries
    skipped) — the a1a-fixture format."""
    lines = []
    for i in range(len(y)):
        feats = " ".join(
            f"{j + 1}:{X[i, j]:.6f}" for j in np.nonzero(X[i])[0]
        )
        lines.append(f"{int(y[i])} {feats}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path
