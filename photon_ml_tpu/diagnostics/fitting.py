"""Fitting (learning-curve) diagnostic: metrics as a function of training
set size, train vs hold-out, with warm-started refits.

Reference analog: photon-diagnostics fitting/FittingDiagnostic.scala:30-131 —
rows are tagged into NUM_TRAINING_PARTITIONS (10) random splits, the last
split is the hold-out, and models are trained on growing prefixes of the
rest with warm starts. TPU-first, "training on a prefix" is a weight mask
over the fixed batch: same shapes every step, so every refit after the
first hits the jit cache.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.evaluation import evaluate
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.optim.factory import OptimizerConfig
from photon_ml_tpu.training import train_glm

NUM_TRAINING_PARTITIONS = 10  # FittingDiagnostic.scala


@dataclasses.dataclass
class FittingReport:
    """Learning curves per regularization weight (FittingReport analog):
    metrics[metric][i] at data portion portions[i]."""

    portions: list[float]  # fraction of rows trained on, ascending
    train_metrics: dict[float, dict[str, list[float]]]  # lambda -> metric -> curve
    test_metrics: dict[float, dict[str, list[float]]]

    def fitting_msg(self) -> str:
        lines = []
        for lam, per_metric in self.test_metrics.items():
            for metric, curve in per_metric.items():
                lines.append(
                    f"lambda={lam} {metric}: "
                    + " -> ".join(f"{v:.4f}" for v in curve)
                )
        return "\n".join(lines)


def fitting_diagnostic(
    batch,
    task: str,
    config: OptimizerConfig,
    lambdas: Sequence[float] = (0.0,),
    num_partitions: int = NUM_TRAINING_PARTITIONS,
    seed: int = 0,
    metrics_fn: Optional[Callable] = None,
    normalization=None,
) -> FittingReport:
    """Train on growing prefixes (1/P, 2/P, ... (P-1)/P of the rows), with
    the final 1/P as hold-out; warm-start each portion from the previous
    portion's models (FittingDiagnostic scanLeft)."""
    if num_partitions < 3:
        raise ValueError("need at least 3 partitions")
    rng = np.random.default_rng(seed)
    base_w = np.asarray(batch.weights)
    tags = rng.integers(0, num_partitions, len(base_w))

    holdout_w = jnp.asarray(
        np.where(tags == num_partitions - 1, base_w, 0.0), jnp.float32
    )
    holdout_batch = dataclasses.replace(batch, weights=holdout_w)

    portions: list[float] = []
    train_metrics: dict[float, dict[str, list[float]]] = {
        float(l): {} for l in lambdas
    }
    test_metrics: dict[float, dict[str, list[float]]] = {
        float(l): {} for l in lambdas
    }

    n_live = max(int((base_w > 0).sum()), 1)
    warm: dict[float, GeneralizedLinearModel] = {}
    for max_tag in range(num_partitions - 1):
        mask = (tags <= max_tag) & (base_w > 0)
        portions.append(float(mask.sum()) / n_live)
        train_w = jnp.asarray(np.where(mask, base_w, 0.0), jnp.float32)
        train_batch = dataclasses.replace(batch, weights=train_w)

        entries = train_glm(
            train_batch,
            task,
            list(lambdas),
            config,
            normalization=normalization,
            initial_model=warm.get(max(lambdas)) if warm else None,
        )
        for e in entries:
            warm[e.reg_weight] = e.model
            fn = metrics_fn if metrics_fn is not None else evaluate
            for which, dest in (
                (train_batch, train_metrics),
                (holdout_batch, test_metrics),
            ):
                for k, v in fn(e.model, which).items():
                    dest[e.reg_weight].setdefault(k, []).append(v)

    return FittingReport(
        portions=portions, train_metrics=train_metrics, test_metrics=test_metrics
    )


def fitting_report_sections(report: FittingReport):
    """Render learning curves as report sections with line plots
    (FittingToPhysicalReportTransformer analog)."""
    from photon_ml_tpu.diagnostics.reporting import LinePlot, Section

    sections = []
    for lam in report.test_metrics:
        plots = []
        for metric, test_curve in report.test_metrics[lam].items():
            train_curve = report.train_metrics[lam].get(metric)
            series = {"holdout": test_curve}
            if train_curve is not None:
                series["train"] = train_curve
            plots.append(
                LinePlot(
                    x=report.portions,
                    series=series,
                    title=f"{metric} (lambda={lam})",
                    x_label="training data portion",
                    y_label=metric,
                )
            )
        sections.append(Section(f"Learning curves (lambda={lam})", plots))
    return sections
