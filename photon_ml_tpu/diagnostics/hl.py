"""Hosmer-Lemeshow goodness-of-fit test for logistic models.

Reference analog: photon-diagnostics hl/ (HosmerLemeshowDiagnostic.scala:
chi-square over predicted-probability bins with expected-vs-observed
positive/negative counts, dof = bins - 2, cutoffs at the standard
confidence levels, minimum expected count warnings;
DefaultPredictedProbabilityVersusObservedFrequencyBinner = equal-count
bins, Fixed... = equal-width bins).
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import chi2 as _chi2

STANDARD_CONFIDENCE_LEVELS = [
    0.000001, 0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
    0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 0.999999,
]  # HosmerLemeshowDiagnostic.scala
MINIMUM_EXPECTED_IN_BUCKET = 5


@dataclasses.dataclass(frozen=True)
class HistogramBin:
    """PredictedProbabilityVersusObservedFrequencyHistogramBin analog.

    ``mean_prob`` carries the weighted mean predicted probability of the
    bin's rows; the reference approximates expectation from the bin
    MIDPOINT (HistogramBin.scala:51-60) — ``expected="midpoint"``
    reproduces that, ``"mean_prob"`` is the classical (unbiased) H-L
    expectation."""

    lower_bound: float
    upper_bound: float
    observed_pos_count: float
    observed_neg_count: float
    mean_prob: float = 0.0
    expected: str = "midpoint"

    @property
    def count(self) -> float:
        return self.observed_pos_count + self.observed_neg_count

    @property
    def expected_pos_count(self) -> float:
        p = (
            0.5 * (self.lower_bound + self.upper_bound)
            if self.expected == "midpoint"
            else self.mean_prob
        )
        return p * self.count

    @property
    def expected_neg_count(self) -> float:
        return self.count - self.expected_pos_count


@dataclasses.dataclass
class HosmerLemeshowReport:
    """Chi^2 + per-bin histogram (HosmerLemeshowReport analog)."""

    bins: list[HistogramBin]
    chi_square: float
    degrees_of_freedom: int
    prob_at_chi_square: float  # P(X^2 <= observed) under H0
    cutoffs: list[tuple[float, float]]  # (confidence level, chi2 cutoff)
    warnings: list[str]

    @property
    def p_value(self) -> float:
        """P(X^2 >= observed): small means poor calibration."""
        return 1.0 - self.prob_at_chi_square

    def to_summary_string(self) -> str:
        lines = [
            f"Hosmer-Lemeshow: chi^2 = {self.chi_square:.4f} "
            f"(dof {self.degrees_of_freedom}), "
            f"P(chi^2 as extreme) = {self.p_value:.4g}"
        ]
        for b in self.bins:
            lines.append(
                f"  [{b.lower_bound:.3f}, {b.upper_bound:.3f}): "
                f"observed +{b.observed_pos_count:.0f}/-{b.observed_neg_count:.0f}, "
                f"expected +{b.expected_pos_count:.1f}/-{b.expected_neg_count:.1f}"
            )
        lines.extend(self.warnings)
        return "\n".join(lines)


def _equal_count_bins(probs: np.ndarray, num_bins: int) -> np.ndarray:
    """Decile-style boundaries (Default binner analog)."""
    qs = np.quantile(probs, np.linspace(0, 1, num_bins + 1))
    qs[0], qs[-1] = 0.0, 1.0
    return np.maximum.accumulate(qs)


def hosmer_lemeshow(
    predicted_probs: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray | None = None,
    num_bins: int = 10,
    binning: str = "equal_count",
    expected: str = "midpoint",
) -> HosmerLemeshowReport:
    """Run the H-L test on predicted probabilities vs binary labels.

    ``expected``: "midpoint" matches the reference's bin-midpoint
    expectation; "mean_prob" uses the weighted mean predicted probability
    per bin (the classical Hosmer-Lemeshow statistic)."""
    if expected not in ("midpoint", "mean_prob"):
        raise ValueError(f"unknown expected mode '{expected}'")
    probs = np.asarray(predicted_probs, np.float64)
    y = np.asarray(labels, np.float64) > 0.5
    w = (
        np.ones_like(probs)
        if weights is None
        else np.asarray(weights, np.float64)
    )
    live = w > 0
    probs, y, w = probs[live], y[live], w[live]
    if len(probs) == 0:
        raise ValueError("no rows with positive weight")

    if binning == "equal_count":
        edges = _equal_count_bins(probs, num_bins)
    elif binning == "equal_width":
        edges = np.linspace(0.0, 1.0, num_bins + 1)
    else:
        raise ValueError(f"unknown binning '{binning}'")

    which = np.clip(np.searchsorted(edges, probs, side="right") - 1, 0, num_bins - 1)
    bins = []
    warnings: list[str] = []
    chi_sq = 0.0
    for i in range(num_bins):
        sel = which == i
        pos = float(np.sum(w[sel] * y[sel]))
        neg = float(np.sum(w[sel] * ~y[sel]))
        wsum = float(np.sum(w[sel]))
        b = HistogramBin(
            lower_bound=float(edges[i]),
            upper_bound=float(edges[i + 1]),
            observed_pos_count=pos,
            observed_neg_count=neg,
            mean_prob=float(np.sum(w[sel] * probs[sel]) / wsum) if wsum else 0.0,
            expected=expected,
        )
        bins.append(b)
        # expected == 0 with observed events means unbounded chi^2; the
        # reference skips the term (HosmerLemeshowDiagnostic.scala deltaNeg
        # guard) — match that but surface a warning so the understated
        # statistic is visible
        for sign, obs, exp in (("positive", pos, b.expected_pos_count),
                               ("negative", neg, b.expected_neg_count)):
            if exp > 0:
                chi_sq += (obs - exp) ** 2 / exp
            elif obs > 0:
                warnings.append(
                    f"bin {i}: observed {sign} events with expected count 0 "
                    "— chi^2 term skipped (statistic is understated)"
                )
            if exp < MINIMUM_EXPECTED_IN_BUCKET:
                warnings.append(
                    f"bin {i}: expected {sign} count {exp:.1f} "
                    "too small for a sound chi^2 estimate"
                )

    dof = max(num_bins - 2, 1)
    dist = _chi2(dof)
    return HosmerLemeshowReport(
        bins=bins,
        chi_square=float(chi_sq),
        degrees_of_freedom=dof,
        prob_at_chi_square=float(dist.cdf(chi_sq)),
        cutoffs=[(c, float(dist.ppf(c))) for c in STANDARD_CONFIDENCE_LEVELS],
        warnings=warnings,
    )
