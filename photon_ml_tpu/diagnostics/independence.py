"""Prediction-error independence analysis via Kendall's tau.

Reference analog: photon-diagnostics independence/ (KendallTauAnalysis.scala
:68-88 — concordant/discordant pair counting, tau-alpha =
(C - D)/(C + D), tau-beta = (C - D)/sqrt(noTiesA * noTiesB), z score and
normal-approximation p-value; PredictionErrorIndependenceDiagnostic pairs
(prediction, error)).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np


@dataclasses.dataclass
class KendallTauReport:
    """KendallTauReport analog."""

    num_samples: int
    num_concordant: int
    num_discordant: int
    effective_pairs: int  # pairs with no tie in either variable
    tau_alpha: float
    tau_beta: float
    z_alpha: float
    p_value: float  # two-sided, normal approximation
    message: str = ""

    def to_summary_string(self) -> str:
        return (
            f"Kendall tau: alpha={self.tau_alpha:.4f} beta={self.tau_beta:.4f} "
            f"z={self.z_alpha:.3f} p={self.p_value:.4g} "
            f"(C={self.num_concordant}, D={self.num_discordant}, "
            f"n={self.num_samples})"
        )


def _pair_counts(a: np.ndarray, b: np.ndarray) -> tuple[int, int, int, int]:
    """Concordant/discordant counts + per-variable untied pair counts.

    O(n^2) on the (possibly subsampled) arrays — exact, like the
    reference's pair enumeration."""
    sa = np.sign(a[:, None] - a[None, :])
    sb = np.sign(b[:, None] - b[None, :])
    upper = np.triu(np.ones((len(a), len(a)), bool), 1)
    prod = sa * sb
    concordant = int(np.sum((prod > 0) & upper))
    discordant = int(np.sum((prod < 0) & upper))
    no_ties_a = int(np.sum((sa != 0) & upper))
    no_ties_b = int(np.sum((sb != 0) & upper))
    return concordant, discordant, no_ties_a, no_ties_b


def kendall_tau_analysis(
    a: np.ndarray,
    b: np.ndarray,
    max_samples: int = 2000,
    seed: int = 0,
) -> KendallTauReport:
    """Test independence of two paired samples via Kendall's tau.

    Pairs beyond ``max_samples`` are uniformly subsampled (pair counting is
    quadratic; the reference operates on collected samples too)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    n_total = len(a)
    if n_total < 2:
        raise ValueError("need at least 2 samples")
    msg = ""
    if n_total > max_samples:
        idx = np.random.default_rng(seed).choice(n_total, max_samples, replace=False)
        a, b = a[idx], b[idx]
        msg = f"subsampled {max_samples} of {n_total} rows"
    n = len(a)

    concordant, discordant, no_ties_a, no_ties_b = _pair_counts(a, b)
    denom = concordant + discordant
    tau_alpha = (concordant - discordant) / denom if denom else 0.0
    tb_denom = math.sqrt(float(no_ties_a) * float(no_ties_b))
    tau_beta = (concordant - discordant) / tb_denom if tb_denom else 0.0

    # var(tau) under H0 ~ 2(2n+5)/(9n(n-1)) (KendallTauAnalysis z score);
    # two-sided p-value from the normal approximation
    d = math.sqrt(2.0 * (2.0 * n + 5.0) / (9.0 * n * (n - 1.0)))
    z_alpha = tau_alpha / d if d else 0.0
    p_value = float(2.0 * (1.0 - _norm_cdf(abs(z_alpha))))
    return KendallTauReport(
        num_samples=n,
        num_concordant=concordant,
        num_discordant=discordant,
        effective_pairs=min(no_ties_a, no_ties_b),
        tau_alpha=tau_alpha,
        tau_beta=tau_beta,
        z_alpha=z_alpha,
        p_value=p_value,
        message=msg,
    )


def _norm_cdf(x: float) -> float:
    return 0.5 * (1.0 + math.erf(x / math.sqrt(2.0)))


def prediction_error_independence(
    predictions: np.ndarray,
    labels: np.ndarray,
    max_samples: int = 2000,
    seed: int = 0,
) -> KendallTauReport:
    """Independence of predictions and errors
    (PredictionErrorIndependenceDiagnostic analog: error = label - score).
    Dependence (small p) indicates structure the model failed to capture."""
    predictions = np.asarray(predictions, np.float64)
    errors = np.asarray(labels, np.float64) - predictions
    return kendall_tau_analysis(
        predictions, errors, max_samples=max_samples, seed=seed
    )
