"""Batch model evaluation: regression + binary-classification metrics,
per-datum log-likelihood, and AIC.

Reference analog: photon-diagnostics Evaluation.scala:31-150 — MAE/MSE/RMSE
for regression facets, AUROC / area-under-PR / peak-F1 for binary
classifiers (Spark MLLIB BinaryClassificationMetrics), per-datum
log-likelihood for logistic (on mean predictions, eps-clamped) and Poisson
(y*wTx - exp(wTx) - logGamma(1+y)), and the small-sample-corrected AIC over
effective (|coef| > 1e-9) parameters. All metric kernels are device code;
the PR/ROC curves are one sort + cumulative sums.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from photon_ml_tpu.evaluation.evaluators import auc as _auc
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import get_loss

Array = jax.Array

MEAN_ABSOLUTE_ERROR = "Mean absolute error"
MEAN_SQUARE_ERROR = "Mean square error"
ROOT_MEAN_SQUARE_ERROR = "Root mean square error"
AREA_UNDER_PRECISION_RECALL = "Area under precision/recall"
AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS = "Area under ROC"
PEAK_F1_SCORE = "Peak F1 score"
DATA_LOG_LIKELIHOOD = "Per-datum log likelihood"
AKAIKE_INFORMATION_CRITERION = "Akaike information criterion"
_EPS = 1e-9


def area_under_pr(scores: Array, labels: Array, weights: Array) -> Array:
    """Weighted area under the precision-recall curve (trapezoidal over
    distinct thresholds, descending score order)."""
    order = jnp.argsort(-scores)
    y = (labels[order] > 0.5).astype(scores.dtype) * weights[order]
    w = weights[order]
    tp = jnp.cumsum(y)
    pp = jnp.cumsum(w)
    total_pos = jnp.maximum(tp[-1], _EPS)
    precision = tp / jnp.maximum(pp, _EPS)
    recall = tp / total_pos
    # prepend (recall 0, precision 1) and integrate
    r = jnp.concatenate([jnp.zeros((1,), recall.dtype), recall])
    p = jnp.concatenate([jnp.ones((1,), precision.dtype), precision])
    return jnp.sum((r[1:] - r[:-1]) * 0.5 * (p[1:] + p[:-1]))


def peak_f1(scores: Array, labels: Array, weights: Array) -> Array:
    """Max F1 over score thresholds (fMeasureByThreshold().max analog)."""
    order = jnp.argsort(-scores)
    y = (labels[order] > 0.5).astype(scores.dtype) * weights[order]
    w = weights[order]
    tp = jnp.cumsum(y)
    pp = jnp.cumsum(w)
    total_pos = jnp.maximum(tp[-1], _EPS)
    precision = tp / jnp.maximum(pp, _EPS)
    recall = tp / total_pos
    f1 = 2.0 * precision * recall / jnp.maximum(precision + recall, _EPS)
    return jnp.max(f1)


def _log_gamma(x: Array) -> Array:
    return jax.lax.lgamma(x)


def evaluate(
    model: GeneralizedLinearModel,
    batch,
) -> dict[str, float]:
    """Full metric map for one GLM on one batch (Evaluation.evaluate).

    ``compute_score`` = Xw + batch.offsets already (SparseBatch.margins
    includes the offset column — computeMeanFunctionWithOffset semantics),
    so nothing is added here."""
    task = get_loss(model.task).name
    margins = model.compute_score(batch)
    means = model.mean_of(margins)
    labels = batch.labels
    weights = batch.weights
    wsum = jnp.maximum(jnp.sum(weights), _EPS)

    metrics: dict[str, float] = {}

    if task in ("squared", "poisson"):  # regression facet
        err = means - labels
        metrics[MEAN_ABSOLUTE_ERROR] = float(
            jnp.sum(weights * jnp.abs(err)) / wsum
        )
        mse = jnp.sum(weights * err * err) / wsum
        metrics[MEAN_SQUARE_ERROR] = float(mse)
        metrics[ROOT_MEAN_SQUARE_ERROR] = float(jnp.sqrt(mse))

    if task in ("logistic", "smoothed_hinge"):  # binary classifier facet
        metrics[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] = float(
            _auc(means, labels, weights)
        )
        metrics[AREA_UNDER_PRECISION_RECALL] = float(
            area_under_pr(means, labels, weights)
        )
        metrics[PEAK_F1_SCORE] = float(peak_f1(means, labels, weights))

    log_lik = None
    if task == "logistic":
        p = jnp.clip(means, _EPS, 1.0 - _EPS)
        ll = labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p)
        log_lik = float(jnp.sum(weights * ll) / wsum)
    elif task == "poisson":
        ll = labels * margins - jnp.exp(margins) - _log_gamma(1.0 + labels)
        log_lik = float(jnp.sum(weights * ll) / wsum)
    if log_lik is not None:
        metrics[DATA_LOG_LIKELIHOOD] = log_lik
        n = float(jnp.sum(weights > 0))
        k = float(
            jnp.sum(jnp.abs(model.coefficients.means) > 1e-9)
        )  # effective parameters
        base_aic = 2.0 * (k - n * log_lik)
        # small-sample correction (Evaluation.scala:114-118)
        metrics[AKAIKE_INFORMATION_CRITERION] = base_aic + 2.0 * k * (k + 1) / max(
            n - k - 1.0, _EPS
        )
    return metrics
