"""Bootstrap training: per-coefficient confidence intervals and metric
distributions from resampled refits.

Reference analog: photon-diagnostics BootstrapTraining.scala:30-181 and
supervised/model/CoefficientSummary.scala. The reference tags rows into
1000 splits and filters RDDs per bootstrap sample; TPU-first, each sample
is a WEIGHT VECTOR (multinomial resample counts over the training portion,
0 on the holdout) and all B refits run as ONE vmapped jit-compiled solve —
same shapes, no data movement, B-way parallel on the MXU.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.diagnostics.evaluation import evaluate
from photon_ml_tpu.models.glm import make_model
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.factory import OptimizerConfig, dispatch_solve

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class CoefficientSummary:
    """Per-scalar accumulation summary (CoefficientSummary.scala analog:
    count/mean/stddev/min/max + quartile estimates)."""

    count: int
    mean: float
    std_dev: float
    min: float
    max: float
    q1: float
    median: float
    q3: float

    @staticmethod
    def of(samples: np.ndarray) -> "CoefficientSummary":
        s = np.asarray(samples, np.float64)
        q1, med, q3 = np.percentile(s, [25, 50, 75])
        return CoefficientSummary(
            count=int(s.size),
            mean=float(s.mean()),
            std_dev=float(s.std(ddof=1)) if s.size > 1 else 0.0,
            min=float(s.min()),
            max=float(s.max()),
            q1=float(q1),
            median=float(med),
            q3=float(q3),
        )

    def contains_zero(self) -> bool:
        return self.min <= 0.0 <= self.max

    def to_summary_string(self) -> str:
        return (
            f"Range: [Min: {self.min:.3f}, Q1: {self.q1:.3f}, "
            f"Med: {self.median:.3f}, Q3: {self.q3:.3f}, Max: {self.max:.3f}) "
            f"Mean: [{self.mean:.3f}], Std. Dev.[{self.std_dev:.3f}], "
            f"# samples = [{self.count}]"
        )


@dataclasses.dataclass
class BootstrapReport:
    """Aggregates over bootstrap refits (BootstrapReport analog)."""

    coefficient_summaries: list[CoefficientSummary]  # 1:1 with coefficients
    metric_summaries: dict[str, CoefficientSummary]
    models: Optional[list] = None  # per-sample GLMs when keep_models

    def significant_coefficients(self) -> np.ndarray:
        """Indices whose bootstrap CI (min..max) excludes zero — the
        'very unlikely to be zero' set the reference doc describes."""
        return np.asarray(
            [i for i, s in enumerate(self.coefficient_summaries)
             if not s.contains_zero()],
            np.int64,
        )


@lru_cache(maxsize=32)
def _bootstrap_solver(config: OptimizerConfig, loss_name: str):
    def solve_one(obj, batch, weights, w0, l1, constraints):
        b = dataclasses.replace(batch, weights=weights)
        return dispatch_solve(
            glm_adapter(obj, b), w0, config, l1, constraints=constraints
        )

    # weights vmap over the sample axis; batch/obj/w0/l1/constraints broadcast
    return telemetry.instrumented_jit(
        jax.vmap(solve_one, in_axes=(None, None, 0, None, None, None)),
        name="bootstrap_glm_solve",
        multi_shape=True,
    )


def bootstrap_train(
    batch,
    task: str,
    config: OptimizerConfig,
    num_samples: int = 16,
    train_portion: float = 0.8,
    seed: int = 0,
    keep_models: bool = False,
    metrics_fn: Optional[Callable] = None,
    normalization=None,
) -> BootstrapReport:
    """Train ``num_samples`` bootstrap refits and aggregate.

    Each sample: rows are split train/holdout at ``train_portion`` (capped
    at 0.9 like the reference's 900/1000 splits), the training rows receive
    multinomial resample counts as weight multipliers (sampling with
    replacement), and the model refits from zero. Holdout metrics feed the
    metric distributions (Evaluation.evaluate per model in the reference).
    """
    if num_samples < 2:
        raise ValueError("num_samples must be at least 2")
    if not 0.0 < train_portion <= 1.0:
        raise ValueError(f"train_portion must be in (0, 1], got {train_portion}")
    train_portion = min(train_portion, 0.9)
    config.validate(task)

    rng = np.random.default_rng(seed)
    base_w = np.asarray(batch.weights)
    n_pad = len(base_w)
    live = base_w > 0
    n_live = int(live.sum())

    sample_weights = np.zeros((num_samples, n_pad))
    holdout_masks = np.zeros((num_samples, n_pad), bool)
    live_idx = np.nonzero(live)[0]
    n_train = max(int(round(train_portion * n_live)), 1)
    for b in range(num_samples):
        perm = rng.permutation(n_live)
        train_rows = live_idx[perm[:n_train]]
        holdout_rows = live_idx[perm[n_train:]]
        counts = rng.multinomial(n_train, np.full(n_train, 1.0 / n_train))
        sample_weights[b, train_rows] = base_w[train_rows] * counts
        holdout_masks[b, holdout_rows] = True

    factors = shifts = None
    if normalization is not None:
        factors, shifts = normalization.factors, normalization.shifts
    obj = make_objective(
        task,
        l2_weight=config.regularization.l2_weight(config.regularization_weight),
        factors=factors,
        shifts=shifts,
    )
    l1 = jnp.float32(config.regularization.l1_weight(config.regularization_weight))
    key_cfg = dataclasses.replace(config, regularization_weight=0.0)
    solver = _bootstrap_solver(key_cfg, task)
    w0 = jnp.zeros((batch.num_features,), jnp.float32)
    constraints = config.build_box_constraints(int(batch.num_features))
    res = solver(
        obj, batch, jnp.asarray(sample_weights, jnp.float32), w0, l1, constraints
    )
    # [B, d] coefficient matrix, fetched ONCE through the accounted
    # crossing (lint L019: a bare np.asarray here would be an invisible
    # device->host sync); optimization (normalized) space
    W = telemetry.sync_fetch(res.w, label="bootstrap_coefficients")
    if normalization is not None:
        # models live in original space (createModel parity)
        W = telemetry.sync_fetch(
            jax.vmap(normalization.transform_model_coefficients)(res.w),
            label="bootstrap_coefficients",
        )

    coef_summaries = [CoefficientSummary.of(W[:, j]) for j in range(W.shape[1])]

    metric_samples: dict[str, list[float]] = {}
    models = []
    for b in range(num_samples):
        m = make_model(task, jnp.asarray(W[b]))
        models.append(m)
        hold_w = jnp.asarray(
            np.where(holdout_masks[b], base_w, 0.0), jnp.float32
        )
        hb = dataclasses.replace(batch, weights=hold_w)
        mm = metrics_fn(m, hb) if metrics_fn is not None else evaluate(m, hb)
        for k, v in mm.items():
            metric_samples.setdefault(k, []).append(v)

    return BootstrapReport(
        coefficient_summaries=coef_summaries,
        metric_summaries={
            k: CoefficientSummary.of(np.asarray(v))
            for k, v in metric_samples.items()
        },
        models=models if keep_models else None,
    )


# ---------------------------------------------------------------------------
# GLMix (random-effect) bootstrap: B resamples as vmapped lanes riding the
# sweep machinery (ISSUE 20 leg 1)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ReBootstrapReport:
    """Per-entity-coefficient bootstrap aggregates for one RE bucket:
    every array is [E, K] over the bucket's entity x coefficient grid.
    The CI bounds are the 2.5/97.5 bootstrap percentiles — the error
    bars the publish gate's quality block carries per version."""

    num_samples: int
    mean: np.ndarray
    std_dev: np.ndarray
    q1: np.ndarray
    median: np.ndarray
    q3: np.ndarray
    ci_low: np.ndarray
    ci_high: np.ndarray
    live_entities: np.ndarray  # bool [E]; False = padding / empty lane

    def contains_zero(self) -> np.ndarray:
        """bool [E, K]: CI straddles zero (NOT significant)."""
        return (self.ci_low <= 0.0) & (0.0 <= self.ci_high)

    def summary(self) -> dict:
        """JSON-safe rollup for version metadata: how wide the error
        bars are and how much of the grid is distinguishable from
        zero, restricted to live (non-padding) entity lanes."""
        live = np.asarray(self.live_entities, bool)
        width = (self.ci_high - self.ci_low)[live]
        cz = self.contains_zero()[live]
        if width.size == 0:
            return {"entities": 0, "num_samples": self.num_samples}
        return {
            "entities": int(live.sum()),
            "coefficients_per_entity": int(self.mean.shape[1]),
            "num_samples": self.num_samples,
            "mean_ci_width": round(float(width.mean()), 6),
            "max_ci_width": round(float(width.max()), 6),
            "contains_zero_fraction": round(float(cz.mean()), 6),
        }


def bootstrap_re_weights(
    num_samples: int, base_weights: np.ndarray, seed: int = 0
) -> np.ndarray:
    """[B, E, R] multinomial resample-count multipliers, drawn per
    entity over its live (weight > 0) rows; padding rows stay zero.

    Entity draws are independent and consumed in entity order from one
    seeded generator, so gathering entity lanes out of the full array
    (the masked-lane bootstrap) sees EXACTLY the draws the full-lane
    bootstrap used for those entities — which is what makes
    masked-vs-full CI agreement on touched rows exact."""
    bw = np.asarray(base_weights, np.float64)
    B, (E, R) = num_samples, bw.shape
    rng = np.random.default_rng(seed)
    out = np.zeros((B, E, R))
    for e in range(E):
        live = np.nonzero(bw[e] > 0)[0]
        n = live.size
        if n == 0:
            continue
        counts = rng.multinomial(n, np.full(n, 1.0 / n), size=B)
        out[:, e, live] = counts
    return out


def bootstrap_random_effect(
    ebatch,
    task: str,
    config: OptimizerConfig,
    w0,
    num_samples: int = 32,
    seed: int = 0,
    lane_weights: Optional[np.ndarray] = None,
    normalization=None,
) -> ReBootstrapReport:
    """Bootstrap one random-effect bucket: B weight-resample lanes
    composed with the per-entity vmap (sweep.runner.re_bootstrap_solver)
    solve B*E problems in ONE executable, every lane warm-started from
    the point estimate ``w0`` [E, K]. The bucket design broadcasts
    across the B axis, so wall time stays well under 2x a single fit
    even at B=64 (bench_diagnostics gates the ratio).

    ``lane_weights`` [B, E, R] overrides the drawn multipliers — the
    masked-lane path passes a gathered slice of the full-bucket draw.
    """
    from photon_ml_tpu.sweep.runner import re_bootstrap_solver

    if num_samples < 2:
        raise ValueError("num_samples must be at least 2")
    config.validate(task)

    if lane_weights is None:
        base_w = np.asarray(
            telemetry.sync_fetch(
                ebatch.weights, label="bootstrap_re_base_weights"
            )
        )
        lane_weights = bootstrap_re_weights(num_samples, base_w, seed)
    else:
        lane_weights = np.asarray(lane_weights)
        num_samples = int(lane_weights.shape[0])
    live_entities = lane_weights.sum(axis=(0, 2)) > 0

    factors = shifts = None
    if normalization is not None:
        factors, shifts = normalization.factors, normalization.shifts
    obj = make_objective(
        task,
        l2_weight=config.regularization.l2_weight(config.regularization_weight),
        factors=factors,
        shifts=shifts,
    )
    l1 = jnp.float32(
        config.regularization.l1_weight(config.regularization_weight)
    )
    key_cfg = dataclasses.replace(config, regularization_weight=0.0)
    solver = re_bootstrap_solver(key_cfg)
    res = solver(
        obj,
        ebatch,
        jnp.asarray(lane_weights, jnp.float32),
        jnp.asarray(w0, jnp.float32),
        l1,
    )
    # [B, E, K], fetched once through the accounted crossing
    W = telemetry.sync_fetch(res.w, label="bootstrap_re_coefficients")
    W = np.asarray(W, np.float64)

    q1, med, q3 = np.percentile(W, [25, 50, 75], axis=0)
    lo, hi = np.percentile(W, [2.5, 97.5], axis=0)
    return ReBootstrapReport(
        num_samples=int(W.shape[0]),
        mean=W.mean(axis=0),
        std_dev=(
            W.std(axis=0, ddof=1)
            if W.shape[0] > 1
            else np.zeros(W.shape[1:], np.float64)
        ),
        q1=q1,
        median=med,
        q3=q3,
        ci_low=lo,
        ci_high=hi,
        live_entities=live_entities,
    )
