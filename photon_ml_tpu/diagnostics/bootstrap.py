"""Bootstrap training: per-coefficient confidence intervals and metric
distributions from resampled refits.

Reference analog: photon-diagnostics BootstrapTraining.scala:30-181 and
supervised/model/CoefficientSummary.scala. The reference tags rows into
1000 splits and filters RDDs per bootstrap sample; TPU-first, each sample
is a WEIGHT VECTOR (multinomial resample counts over the training portion,
0 on the holdout) and all B refits run as ONE vmapped jit-compiled solve —
same shapes, no data movement, B-way parallel on the MXU.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.diagnostics.evaluation import evaluate
from photon_ml_tpu.models.glm import make_model
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.factory import OptimizerConfig, dispatch_solve

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class CoefficientSummary:
    """Per-scalar accumulation summary (CoefficientSummary.scala analog:
    count/mean/stddev/min/max + quartile estimates)."""

    count: int
    mean: float
    std_dev: float
    min: float
    max: float
    q1: float
    median: float
    q3: float

    @staticmethod
    def of(samples: np.ndarray) -> "CoefficientSummary":
        s = np.asarray(samples, np.float64)
        q1, med, q3 = np.percentile(s, [25, 50, 75])
        return CoefficientSummary(
            count=int(s.size),
            mean=float(s.mean()),
            std_dev=float(s.std(ddof=1)) if s.size > 1 else 0.0,
            min=float(s.min()),
            max=float(s.max()),
            q1=float(q1),
            median=float(med),
            q3=float(q3),
        )

    def contains_zero(self) -> bool:
        return self.min <= 0.0 <= self.max

    def to_summary_string(self) -> str:
        return (
            f"Range: [Min: {self.min:.3f}, Q1: {self.q1:.3f}, "
            f"Med: {self.median:.3f}, Q3: {self.q3:.3f}, Max: {self.max:.3f}) "
            f"Mean: [{self.mean:.3f}], Std. Dev.[{self.std_dev:.3f}], "
            f"# samples = [{self.count}]"
        )


@dataclasses.dataclass
class BootstrapReport:
    """Aggregates over bootstrap refits (BootstrapReport analog)."""

    coefficient_summaries: list[CoefficientSummary]  # 1:1 with coefficients
    metric_summaries: dict[str, CoefficientSummary]
    models: Optional[list] = None  # per-sample GLMs when keep_models

    def significant_coefficients(self) -> np.ndarray:
        """Indices whose bootstrap CI (min..max) excludes zero — the
        'very unlikely to be zero' set the reference doc describes."""
        return np.asarray(
            [i for i, s in enumerate(self.coefficient_summaries)
             if not s.contains_zero()],
            np.int64,
        )


@lru_cache(maxsize=32)
def _bootstrap_solver(config: OptimizerConfig, loss_name: str):
    def solve_one(obj, batch, weights, w0, l1, constraints):
        b = dataclasses.replace(batch, weights=weights)
        return dispatch_solve(
            glm_adapter(obj, b), w0, config, l1, constraints=constraints
        )

    # weights vmap over the sample axis; batch/obj/w0/l1/constraints broadcast
    return jax.jit(
        jax.vmap(solve_one, in_axes=(None, None, 0, None, None, None))
    )


def bootstrap_train(
    batch,
    task: str,
    config: OptimizerConfig,
    num_samples: int = 16,
    train_portion: float = 0.8,
    seed: int = 0,
    keep_models: bool = False,
    metrics_fn: Optional[Callable] = None,
    normalization=None,
) -> BootstrapReport:
    """Train ``num_samples`` bootstrap refits and aggregate.

    Each sample: rows are split train/holdout at ``train_portion`` (capped
    at 0.9 like the reference's 900/1000 splits), the training rows receive
    multinomial resample counts as weight multipliers (sampling with
    replacement), and the model refits from zero. Holdout metrics feed the
    metric distributions (Evaluation.evaluate per model in the reference).
    """
    if num_samples < 2:
        raise ValueError("num_samples must be at least 2")
    if not 0.0 < train_portion <= 1.0:
        raise ValueError(f"train_portion must be in (0, 1], got {train_portion}")
    train_portion = min(train_portion, 0.9)
    config.validate(task)

    rng = np.random.default_rng(seed)
    base_w = np.asarray(batch.weights)
    n_pad = len(base_w)
    live = base_w > 0
    n_live = int(live.sum())

    sample_weights = np.zeros((num_samples, n_pad))
    holdout_masks = np.zeros((num_samples, n_pad), bool)
    live_idx = np.nonzero(live)[0]
    n_train = max(int(round(train_portion * n_live)), 1)
    for b in range(num_samples):
        perm = rng.permutation(n_live)
        train_rows = live_idx[perm[:n_train]]
        holdout_rows = live_idx[perm[n_train:]]
        counts = rng.multinomial(n_train, np.full(n_train, 1.0 / n_train))
        sample_weights[b, train_rows] = base_w[train_rows] * counts
        holdout_masks[b, holdout_rows] = True

    factors = shifts = None
    if normalization is not None:
        factors, shifts = normalization.factors, normalization.shifts
    obj = make_objective(
        task,
        l2_weight=config.regularization.l2_weight(config.regularization_weight),
        factors=factors,
        shifts=shifts,
    )
    l1 = jnp.float32(config.regularization.l1_weight(config.regularization_weight))
    key_cfg = dataclasses.replace(config, regularization_weight=0.0)
    solver = _bootstrap_solver(key_cfg, task)
    w0 = jnp.zeros((batch.num_features,), jnp.float32)
    constraints = config.build_box_constraints(int(batch.num_features))
    res = solver(
        obj, batch, jnp.asarray(sample_weights, jnp.float32), w0, l1, constraints
    )
    # [B, d] coefficient matrix, fetched ONCE through the accounted
    # crossing (lint L019: a bare np.asarray here would be an invisible
    # device->host sync); optimization (normalized) space
    W = telemetry.sync_fetch(res.w, label="bootstrap_coefficients")
    if normalization is not None:
        # models live in original space (createModel parity)
        W = telemetry.sync_fetch(
            jax.vmap(normalization.transform_model_coefficients)(res.w),
            label="bootstrap_coefficients",
        )

    coef_summaries = [CoefficientSummary.of(W[:, j]) for j in range(W.shape[1])]

    metric_samples: dict[str, list[float]] = {}
    models = []
    for b in range(num_samples):
        m = make_model(task, jnp.asarray(W[b]))
        models.append(m)
        hold_w = jnp.asarray(
            np.where(holdout_masks[b], base_w, 0.0), jnp.float32
        )
        hb = dataclasses.replace(batch, weights=hold_w)
        mm = metrics_fn(m, hb) if metrics_fn is not None else evaluate(m, hb)
        for k, v in mm.items():
            metric_samples.setdefault(k, []).append(v)

    return BootstrapReport(
        coefficient_summaries=coef_summaries,
        metric_summaries={
            k: CoefficientSummary.of(np.asarray(v))
            for k, v in metric_samples.items()
        },
        models=models if keep_models else None,
    )
