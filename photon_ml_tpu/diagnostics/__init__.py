"""Model diagnostics (photon-diagnostics analog): bootstrap CIs, batch
evaluation metrics + AIC, fitting curves, Hosmer-Lemeshow, feature
importance, Kendall-tau independence, and the report rendering pipeline."""

from photon_ml_tpu.diagnostics.bootstrap import (  # noqa: F401
    BootstrapReport,
    CoefficientSummary,
    bootstrap_train,
)
from photon_ml_tpu.diagnostics.evaluation import (  # noqa: F401
    AKAIKE_INFORMATION_CRITERION,
    AREA_UNDER_PRECISION_RECALL,
    AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
    DATA_LOG_LIKELIHOOD,
    MEAN_ABSOLUTE_ERROR,
    MEAN_SQUARE_ERROR,
    PEAK_F1_SCORE,
    ROOT_MEAN_SQUARE_ERROR,
    area_under_pr,
    evaluate,
    peak_f1,
)
from photon_ml_tpu.diagnostics.feature_importance import (  # noqa: F401
    FeatureImportanceReport,
    expected_magnitude_importance,
    variance_importance,
)
from photon_ml_tpu.diagnostics.fitting import (  # noqa: F401
    FittingReport,
    fitting_diagnostic,
)
from photon_ml_tpu.diagnostics.hl import (  # noqa: F401
    HistogramBin,
    HosmerLemeshowReport,
    hosmer_lemeshow,
)
from photon_ml_tpu.diagnostics.independence import (  # noqa: F401
    KendallTauReport,
    kendall_tau_analysis,
    prediction_error_independence,
)
from photon_ml_tpu.diagnostics.model_diagnostic import (  # noqa: F401
    ModelDiagnostic,
    TrainingDiagnostic,
    diagnose_model,
)
from photon_ml_tpu.diagnostics.reporting import (  # noqa: F401
    BulletedList,
    Chapter,
    Document,
    LinePlot,
    NumberedList,
    Section,
    Table,
    Text,
    render_html,
    render_text,
)
