"""Report rendering: a logical report tree rendered to HTML or text.

Reference analog: photon-diagnostics reporting/ (~35 files: LogicalReport ->
LogicalToPhysicalReportTransformer -> HTML (xml literals) and text
renderers, with chapters/sections/simple text/bulleted+numbered lists and
a NumberingContext). Collapsed here to one module: the report IS the
logical tree (Document > Chapter > Section > items), and render_html /
render_text walk it with hierarchical numbering. Plots are rendered as
inline SVG line charts (the "light-plot" PlotUtils analog) — no image
dependencies.
"""

from __future__ import annotations

import dataclasses
import html as _html
from typing import Sequence, Union

Item = Union["Section", "Text", "BulletedList", "NumberedList", "Table", "LinePlot"]


@dataclasses.dataclass
class Text:
    text: str


@dataclasses.dataclass
class BulletedList:
    items: Sequence[str]


@dataclasses.dataclass
class NumberedList:
    items: Sequence[str]


@dataclasses.dataclass
class Table:
    header: Sequence[str]
    rows: Sequence[Sequence[object]]
    caption: str = ""


@dataclasses.dataclass
class LinePlot:
    """Simple multi-series line plot (PlotUtils/PlotPhysicalReport analog)."""

    x: Sequence[float]
    series: dict[str, Sequence[float]]  # name -> y values
    title: str = ""
    x_label: str = ""
    y_label: str = ""


@dataclasses.dataclass
class Section:
    title: str
    items: Sequence[Item] = ()


@dataclasses.dataclass
class Chapter:
    title: str
    sections: Sequence[Section] = ()


@dataclasses.dataclass
class Document:
    title: str
    chapters: Sequence[Chapter] = ()


# ---------------------------------------------------------------------------
# text renderer (reporting/text analog)
# ---------------------------------------------------------------------------


def render_text(doc: Document) -> str:
    out: list[str] = [doc.title, "=" * len(doc.title), ""]
    for ci, ch in enumerate(doc.chapters, 1):
        out.append(f"{ci}. {ch.title}")
        out.append("-" * (len(ch.title) + 4))
        for si, sec in enumerate(ch.sections, 1):
            out.append(f"{ci}.{si} {sec.title}")
            for item in sec.items:
                out.extend(_text_item(item))
            out.append("")
    return "\n".join(out)


def _text_item(item: Item) -> list[str]:
    if isinstance(item, Text):
        return [item.text]
    if isinstance(item, BulletedList):
        return [f"  * {x}" for x in item.items]
    if isinstance(item, NumberedList):
        return [f"  {i}. {x}" for i, x in enumerate(item.items, 1)]
    if isinstance(item, Table):
        widths = [
            max(len(str(h)), *(len(str(r[j])) for r in item.rows)) if item.rows
            else len(str(h))
            for j, h in enumerate(item.header)
        ]
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        lines = []
        if item.caption:
            lines.append(item.caption)
        lines.append(fmt.format(*[str(h) for h in item.header]))
        lines.extend(fmt.format(*[str(c) for c in r]) for r in item.rows)
        return lines
    if isinstance(item, LinePlot):
        lines = [f"[plot] {item.title} ({item.x_label} vs {item.y_label})"]
        for name, ys in item.series.items():
            pts = ", ".join(f"({x:.3g}, {y:.4g})" for x, y in zip(item.x, ys))
            lines.append(f"  {name}: {pts}")
        return lines
    if isinstance(item, Section):
        return [item.title] + [l for it in item.items for l in _text_item(it)]
    raise TypeError(f"unknown report item {type(item).__name__}")


# ---------------------------------------------------------------------------
# HTML renderer (reporting/html analog)
# ---------------------------------------------------------------------------


def render_html(doc: Document) -> str:
    body: list[str] = [f"<h1>{_html.escape(doc.title)}</h1>"]
    for ci, ch in enumerate(doc.chapters, 1):
        body.append(f"<h2>{ci}. {_html.escape(ch.title)}</h2>")
        for si, sec in enumerate(ch.sections, 1):
            body.append(f"<h3>{ci}.{si} {_html.escape(sec.title)}</h3>")
            for item in sec.items:
                body.append(_html_item(item))
    return (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>{_html.escape(doc.title)}</title>"
        "<style>body{font-family:sans-serif;margin:2em}"
        "table{border-collapse:collapse}td,th{border:1px solid #999;"
        "padding:4px 8px}</style></head><body>"
        + "".join(body)
        + "</body></html>"
    )


def _html_item(item: Item) -> str:
    if isinstance(item, Text):
        return f"<p>{_html.escape(item.text)}</p>"
    if isinstance(item, BulletedList):
        lis = "".join(f"<li>{_html.escape(str(x))}</li>" for x in item.items)
        return f"<ul>{lis}</ul>"
    if isinstance(item, NumberedList):
        lis = "".join(f"<li>{_html.escape(str(x))}</li>" for x in item.items)
        return f"<ol>{lis}</ol>"
    if isinstance(item, Table):
        head = "".join(f"<th>{_html.escape(str(h))}</th>" for h in item.header)
        rows = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in r) + "</tr>"
            for r in item.rows
        )
        cap = f"<caption>{_html.escape(item.caption)}</caption>" if item.caption else ""
        return f"<table>{cap}<tr>{head}</tr>{rows}</table>"
    if isinstance(item, LinePlot):
        return _svg_line_plot(item)
    if isinstance(item, Section):
        inner = "".join(_html_item(it) for it in item.items)
        return f"<h4>{_html.escape(item.title)}</h4>{inner}"
    raise TypeError(f"unknown report item {type(item).__name__}")


_PALETTE = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e", "#8c564b"]


def _svg_line_plot(p: LinePlot, width: int = 480, height: int = 300) -> str:
    xs = list(map(float, p.x))
    all_y = [float(y) for ys in p.series.values() for y in ys]
    if not xs or not all_y:
        return f"<p>[empty plot {_html.escape(p.title)}]</p>"
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(all_y), max(all_y)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    m = 40  # margin

    def sx(x):
        return m + (x - x0) / xr * (width - 2 * m)

    def sy(y):
        return height - m - (y - y0) / yr * (height - 2 * m)

    parts = [
        f"<svg width='{width}' height='{height}' "
        "xmlns='http://www.w3.org/2000/svg'>",
        f"<text x='{width // 2}' y='16' text-anchor='middle' "
        f"font-size='13'>{_html.escape(p.title)}</text>",
        f"<line x1='{m}' y1='{height - m}' x2='{width - m}' "
        f"y2='{height - m}' stroke='#333'/>",
        f"<line x1='{m}' y1='{m}' x2='{m}' y2='{height - m}' stroke='#333'/>",
        f"<text x='{width // 2}' y='{height - 8}' text-anchor='middle' "
        f"font-size='11'>{_html.escape(p.x_label)}</text>",
        f"<text x='12' y='{height // 2}' font-size='11' "
        f"transform='rotate(-90 12 {height // 2})' "
        f"text-anchor='middle'>{_html.escape(p.y_label)}</text>",
        f"<text x='{m}' y='{height - m + 14}' font-size='10'>{x0:.3g}</text>",
        f"<text x='{width - m}' y='{height - m + 14}' font-size='10' "
        f"text-anchor='end'>{x1:.3g}</text>",
        f"<text x='{m - 4}' y='{height - m}' font-size='10' "
        f"text-anchor='end'>{y0:.3g}</text>",
        f"<text x='{m - 4}' y='{m + 4}' font-size='10' text-anchor='end'>"
        f"{y1:.3g}</text>",
    ]
    for i, (name, ys) in enumerate(p.series.items()):
        color = _PALETTE[i % len(_PALETTE)]
        pts = " ".join(f"{sx(x):.1f},{sy(float(y)):.1f}" for x, y in zip(xs, ys))
        parts.append(
            f"<polyline points='{pts}' fill='none' stroke='{color}' "
            "stroke-width='1.5'/>"
        )
        parts.append(
            f"<text x='{width - m + 4}' y='{m + 14 * i}' font-size='10' "
            f"fill='{color}'>{_html.escape(name)}</text>"
        )
    parts.append("</svg>")
    return "".join(parts)
