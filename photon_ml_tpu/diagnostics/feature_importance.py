"""Per-feature importance diagnostics.

Reference analog: photon-diagnostics featureimportance/ — expected-magnitude
importance |coef_j| * meanAbs(x_j) (ExpectedMagnitudeFeatureImportance
Diagnostic.scala) and variance importance |coef_j * Var(x_j)|
(VarianceFeatureImportanceDiagnostic.scala); both fall back to |coef_j|
without a feature summary, and report rank-ordered (name, importance).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.data.stats import FeatureSummary
from photon_ml_tpu.models.glm import GeneralizedLinearModel


@dataclasses.dataclass
class FeatureImportanceReport:
    """Rank-ordered importances (FeatureImportanceReport analog)."""

    importance_type: str
    importance_description: str
    ranked: list[tuple[str, int, float]]  # (feature key, index, importance)

    def top(self, k: int) -> list[tuple[str, int, float]]:
        return self.ranked[:k]

    def to_summary_string(self, k: int = 20) -> str:
        lines = [f"{self.importance_type} ({self.importance_description}):"]
        for name, idx, imp in self.top(k):
            lines.append(f"  {name} [{idx}]: {imp:.6g}")
        return "\n".join(lines)


def _rank(
    importances: np.ndarray, feature_names: Optional[Sequence[str]]
) -> list[tuple[str, int, float]]:
    order = np.argsort(-importances)
    return [
        (
            feature_names[int(i)] if feature_names is not None else str(int(i)),
            int(i),
            float(importances[i]),
        )
        for i in order
    ]


def expected_magnitude_importance(
    model: GeneralizedLinearModel,
    summary: Optional[FeatureSummary] = None,
    feature_names: Optional[Sequence[str]] = None,
) -> FeatureImportanceReport:
    """|coef_j| * E|x_j| (falls back to |coef_j| without a summary)."""
    coefs = np.asarray(model.coefficients.means)
    exp_abs = (
        np.asarray(summary.mean_abs) if summary is not None else np.ones_like(coefs)
    )
    return FeatureImportanceReport(
        importance_type="Inner product expectation",
        importance_description=(
            "Expected magnitude of inner product contribution"
            if summary is not None
            else "Magnitude of feature coefficient"
        ),
        ranked=_rank(np.abs(coefs * exp_abs), feature_names),
    )


def variance_importance(
    model: GeneralizedLinearModel,
    summary: Optional[FeatureSummary] = None,
    feature_names: Optional[Sequence[str]] = None,
) -> FeatureImportanceReport:
    """|coef_j * Var(x_j)| (falls back to |coef_j| without a summary)."""
    coefs = np.asarray(model.coefficients.means)
    var = (
        np.asarray(summary.variance) if summary is not None else np.ones_like(coefs)
    )
    return FeatureImportanceReport(
        importance_type="Inner product variance",
        importance_description=(
            "Expected inner product variance contribution"
            if summary is not None
            else "Magnitude of feature coefficient"
        ),
        ranked=_rank(np.abs(coefs * var), feature_names),
    )
