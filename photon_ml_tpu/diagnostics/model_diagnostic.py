"""Diagnostic interfaces + the full-model diagnostic report composer.

Reference analog: photon-diagnostics ModelDiagnostic.scala /
TrainingDiagnostic.scala (the trait pair each diagnostic implements) and the
legacy Driver's diagnose stage (Driver.scala:600-627), which runs fitting /
bootstrap / H-L / feature importances / independence analysis and renders
one HTML report per model.
"""

from __future__ import annotations

from typing import Optional, Protocol, Sequence

import numpy as np

from photon_ml_tpu.data.stats import FeatureSummary
from photon_ml_tpu.diagnostics.evaluation import evaluate
from photon_ml_tpu.diagnostics.feature_importance import (
    expected_magnitude_importance,
    variance_importance,
)
from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow
from photon_ml_tpu.diagnostics.independence import prediction_error_independence
from photon_ml_tpu.diagnostics.reporting import (
    BulletedList,
    Chapter,
    Document,
    Section,
    Table,
    Text,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import get_loss


class ModelDiagnostic(Protocol):
    """Computes a per-model report from a trained model + data
    (ModelDiagnostic.scala analog)."""

    def diagnose(self, model: GeneralizedLinearModel, data) -> object: ...


class TrainingDiagnostic(Protocol):
    """Computes a report from a model FACTORY + data (learning curves,
    bootstrap; TrainingDiagnostic.scala analog)."""

    def diagnose(self, model_factory, data) -> object: ...


def diagnose_model(
    model: GeneralizedLinearModel,
    batch,
    summary: Optional[FeatureSummary] = None,
    feature_names: Optional[Sequence[str]] = None,
    top_k_features: int = 20,
) -> Document:
    """Compose the standard per-model diagnostic document: metrics, feature
    importances, H-L calibration (logistic only), error independence."""
    task = get_loss(model.task).name
    metrics = evaluate(model, batch)
    sections = [
        Section(
            "Validation metrics",
            [Table(header=["metric", "value"],
                   rows=[(k, f"{v:.6g}") for k, v in sorted(metrics.items())])],
        )
    ]

    imp_rows = []
    for rep in (
        expected_magnitude_importance(model, summary, feature_names),
        variance_importance(model, summary, feature_names),
    ):
        imp_rows.append(
            Section(
                rep.importance_type,
                [
                    Text(rep.importance_description),
                    Table(
                        header=["feature", "index", "importance"],
                        rows=[
                            (n, i, f"{v:.6g}")
                            for n, i, v in rep.top(top_k_features)
                        ],
                    ),
                ],
            )
        )

    chapters = [
        Chapter("Model evaluation", sections),
        Chapter("Feature importance", imp_rows),
    ]

    # compute_score already includes batch.offsets (margins semantics)
    scores = np.asarray(model.compute_score(batch))
    labels = np.asarray(batch.labels)
    weights = np.asarray(batch.weights)

    if task == "logistic":
        probs = 1.0 / (1.0 + np.exp(-scores))
        hl = hosmer_lemeshow(probs, labels, weights)
        chapters.append(
            Chapter(
                "Calibration (Hosmer-Lemeshow)",
                [
                    Section(
                        "Chi-square test",
                        [
                            Text(
                                f"chi^2 = {hl.chi_square:.4f}, "
                                f"dof = {hl.degrees_of_freedom}, "
                                f"p = {hl.p_value:.4g}"
                            ),
                            Table(
                                header=[
                                    "bin", "observed +", "observed -",
                                    "expected +", "expected -",
                                ],
                                rows=[
                                    (
                                        f"[{b.lower_bound:.3f}, {b.upper_bound:.3f})",
                                        f"{b.observed_pos_count:.0f}",
                                        f"{b.observed_neg_count:.0f}",
                                        f"{b.expected_pos_count:.1f}",
                                        f"{b.expected_neg_count:.1f}",
                                    )
                                    for b in hl.bins
                                ],
                            ),
                        ]
                        + ([BulletedList(hl.warnings)] if hl.warnings else []),
                    )
                ],
            )
        )

    live = weights > 0
    kt = prediction_error_independence(scores[live], labels[live])
    chapters.append(
        Chapter(
            "Prediction-error independence",
            [Section("Kendall tau", [Text(kt.to_summary_string())])],
        )
    )
    return Document(title=f"Model diagnostics ({model.task})", chapters=chapters)
