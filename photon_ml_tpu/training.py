"""Top-level GLM training API: warm-started regularization-weight sweeps and
best-model selection.

Reference analog: photon-api ModelTraining.trainGeneralizedLinearModel
(ModelTraining.scala:101-198) — sort lambdas descending, train each model
warm-started from the previous lambda's optimum — plus photon-client
ModelSelection (ModelSelection.scala: AUC for classifiers, RMSE for linear
regression, data log-likelihood for Poisson) and the coefficient-variance
computation of DistributedOptimizationProblem.computeVariances
(DistributedOptimizationProblem.scala:80-94: 1 / (hessian_diagonal + 1e-12)).

TPU-first design: the regularization weight is a TRACED leaf of the
objective (GLMObjective.l2_weight) and a traced l1 scalar, so the whole
sweep runs through ONE compiled program — the on-device analog of the
reference's mutable ``updateRegularizationWeight``
(DistributedOptimizationProblem.scala:60-71). Warm starts chain on device;
only convergence scalars return to host between lambdas.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.evaluation.evaluators import EVALUATORS, better_than
from photon_ml_tpu.models.glm import GeneralizedLinearModel, make_model
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.common import BoxConstraints, SolveResult
from photon_ml_tpu.optim.factory import OptimizerConfig, dispatch_solve
from photon_ml_tpu.parallel.distributed import distributed_solve
from photon_ml_tpu.parallel.mesh import DATA_AXIS

Array = jax.Array

# DistributedOptimizationProblem.computeVariances adds this to the Hessian
# diagonal before inverting (MathConst.HIGH_PRECISION_TOLERANCE_THRESHOLD)
_VARIANCE_EPS = 1e-12


@lru_cache(maxsize=64)
def _sweep_solver(config: OptimizerConfig):
    """Compile-once sweep solver: objective leaves (incl. the l2 weight),
    batch, w0, l1 and constraints are traced; only the config is static."""

    def _sweep_solve(obj, batch, w0, l1, constraints):
        return dispatch_solve(glm_adapter(obj, batch), w0, config, l1, constraints)

    return telemetry.instrumented_jit(_sweep_solve, name="glm_sweep_solve")


@dataclasses.dataclass
class SweepEntry:
    """One trained model of a regularization sweep."""

    reg_weight: float
    model: GeneralizedLinearModel
    result: SolveResult


def _variances(obj, w_opt: Array, batch, mesh, axis) -> Array:
    """1 / (diag H(w*) + eps), in optimization (normalized) space
    (DistributedOptimizationProblem.scala:80-94)."""
    if mesh is not None:
        from photon_ml_tpu.parallel.distributed import distributed_hessian_diagonal

        hdiag = distributed_hessian_diagonal(obj, w_opt, batch, mesh, axis)
    else:
        hdiag = obj.hessian_diagonal(w_opt, batch)
    return 1.0 / (hdiag + _VARIANCE_EPS)


def train_glm(
    batch,
    task: str,
    lambdas: Sequence[float],
    config: OptimizerConfig,
    normalization: Optional[NormalizationContext] = None,
    constraints: Optional[BoxConstraints] = None,
    initial_model: Optional[GeneralizedLinearModel] = None,
    compute_variances: bool = False,
    mesh: Optional[Mesh] = None,
    axis: str = DATA_AXIS,
) -> list[SweepEntry]:
    """Train one GLM per regularization weight, descending, warm-started.

    ``config.regularization_weight`` is ignored; each value of ``lambdas``
    is swept through the traced-weight solve. Returned entries are in the
    caller's original ``lambdas`` order (the reference returns the sorted
    list; we preserve input order for ergonomic zip()s — the TRAINING order
    is still sorted descending for warm-start quality).

    With ``mesh``, ``batch`` must be a stacked per-shard batch (see
    parallel.mesh.shard_rows) and each solve data-parallels over ``axis``.

    Variances (``compute_variances=True``) are computed at each optimum in
    optimization space and mapped back to original space with the same
    coefficient transform the reference applies
    (GeneralizedLinearOptimizationProblem.scala:80-96).
    """
    if not lambdas:
        raise ValueError("lambdas must be non-empty")
    config.validate(task)
    if constraints is None:
        constraints = config.build_box_constraints(int(batch.num_features))
    task = get_loss(task).name

    factors = shifts = None
    if normalization is not None:
        factors, shifts = normalization.factors, normalization.shifts

    n_feat = int(batch.num_features)

    # w0: zero model, or the initial model's coefficients mapped INTO
    # optimization space (models live in original space)
    if initial_model is not None:
        w_start = initial_model.coefficients.means
        if normalization is not None:
            w_start = normalization.inverse_transform_model_coefficients(w_start)
    else:
        w_start = jnp.zeros((n_feat,), dtype=jnp.float32)

    # descending sweep order (ModelTraining.scala:166: sortWith(_ >= _))
    order = sorted(range(len(lambdas)), key=lambda i: -lambdas[i])

    base_obj = make_objective(task, factors=factors, shifts=shifts)

    if mesh is None:
        # one jit program for the whole sweep (reg weights traced), cached
        # across train_glm calls keyed on the static config
        _solve = _sweep_solver(
            dataclasses.replace(config, regularization_weight=0.0)
        )

    results: dict[int, SweepEntry] = {}
    w_prev = w_start
    with telemetry.span("train_glm", task=task, num_lambdas=len(lambdas)):
        for i in order:
            lam = float(lambdas[i])
            with telemetry.span("lambda_solve", reg_weight=lam):
                l2 = config.regularization.l2_weight(lam)
                l1 = config.regularization.l1_weight(lam)
                if mesh is not None:
                    res = distributed_solve(
                        task,
                        batch,
                        dataclasses.replace(
                            config, regularization_weight=lam
                        ),
                        w_prev,
                        mesh,
                        axis=axis,
                        constraints=constraints,
                        factors=factors,
                        shifts=shifts,
                    )
                else:
                    res = _solve(
                        base_obj.with_l2(l2), batch, w_prev, jnp.float32(l1),
                        constraints,
                    )
                w_opt = res.w
                w_prev = w_opt  # warm start the next (smaller) lambda
                telemetry.counter("glm_sweep_solves").inc()

                variances = None
                if compute_variances:
                    if not get_loss(task).has_hessian:
                        raise ValueError(
                            "variances need a twice-differentiable loss; "
                            f"'{task}' is not"
                        )
                    obj_l = base_obj.with_l2(l2)
                    variances = _variances(obj_l, w_opt, batch, mesh, axis)

                means = w_opt
                if normalization is not None:
                    means = normalization.transform_model_coefficients(w_opt)
                    if variances is not None:
                        # DELIBERATE deviation from the reference, which
                        # applies the MEANS transform to variances too
                        # (GeneralizedLinearOptimizationProblem.scala:90-96)
                        # — that scales by factor instead of factor^2 and
                        # the intercept shift cross-term can drive variances
                        # negative. Var(c*X) = c^2 Var(X): scale by
                        # factor^2, no shift term.
                        if normalization.factors is not None:
                            variances = variances * normalization.factors**2
                results[i] = SweepEntry(
                    reg_weight=lam,
                    model=make_model(task, means, variances=variances),
                    result=res,
                )

    return [results[i] for i in range(len(lambdas))]


def _default_selection_metric(task: str) -> str:
    """ModelSelection.scala: AUC for binary classifiers, RMSE for linear
    regression, data log-likelihood (poisson loss) for Poisson."""
    task = get_loss(task).name
    if task in ("logistic", "smoothed_hinge"):
        return "auc"
    if task == "squared":
        return "rmse"
    return "poisson_loss"


def select_best_model(
    entries: Sequence[SweepEntry],
    validation_batch,
    metric: Optional[str] = None,
    scorer: Optional[Callable] = None,
) -> tuple[SweepEntry, float]:
    """Pick the sweep entry whose validation metric is best
    (ModelSelection.selectModelByKey analog). Returns (entry, metric value)."""
    if not entries:
        raise ValueError("no models to select from")
    metric = metric or _default_selection_metric(entries[0].model.task)
    fn = EVALUATORS.get(metric)
    if fn is None:
        raise ValueError(f"unknown metric '{metric}'. Known: {sorted(EVALUATORS)}")

    best: Optional[tuple[SweepEntry, float]] = None
    for e in entries:
        scores = (
            scorer(e.model) if scorer is not None
            else e.model.compute_score(validation_batch)
        )
        val = float(
            fn(scores, validation_batch.labels, validation_batch.weights)
        )
        if best is None or better_than(metric, val, best[1]):
            best = (e, val)
    return best
