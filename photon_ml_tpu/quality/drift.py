"""Online quality drift telemetry: per-version score-distribution and
calibration-bin sketches.

The serving engine scores millions of rows between retrains; nothing so
far watched whether the score DISTRIBUTION drifts between the version
that passed the publish gate and the traffic it now sees. This module is
the streaming side of the quality layer (ISSUE 20 leg 3):

- :func:`observe_scores` — every ``ScoringEngine.score_rows`` chunk
  feeds its (already host-fetched) mean predictions into a bounded
  per-version :class:`ScoreSketch` (count/sum/sumsq/min/max + a fixed
  10-bin histogram over [0, 1]);
- :func:`observe_labeled` — the nearline updater feeds (predicted,
  label) pairs from feedback events into per-version calibration bins
  (predicted-mean vs observed-rate per bin — the online Hosmer–Lemeshow
  view);
- the ``"quality"`` snapshot section — registered once at import via
  ``telemetry.register_snapshot_provider`` — publishes one drift row per
  retained version into every ``telemetry.snapshot()``, which is exactly
  the surface ``/metricsz``, the ``--telemetry-out`` JSONL flush,
  ``cli report`` (single and ``--fleet``), and the RunReport "Quality"
  section already read. Rows carry a PSI (population stability index)
  against the oldest retained version with enough samples, so "did the
  hot swap shift the score distribution" is one number per version.

Bounded like PR 18's request traces: at most :data:`MAX_VERSIONS`
versions are retained, ring-evicted oldest-first on overflow
(``quality.versions_evicted``), and each sketch is a fixed-size array —
a long-lived serving fleet cannot grow this without bound.

Fault seam: ``quality.drift_flush`` fires inside the snapshot provider.
Drift telemetry is observability, never control — an injected raise here
is absorbed by the metrics registry's provider-skip contract (the
section vanishes from ONE snapshot; scoring and publishing are
untouched), which ``tests/test_quality.py`` asserts.

Hot-path discipline: :func:`observe_scores` is reachable from
``ScoringEngine.score_rows`` (an L013 sync seed), so nothing in this
module performs a device->host crossing — callers hand in arrays that
already crossed through ``telemetry.device.sync_fetch``.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from photon_ml_tpu import faults
from photon_ml_tpu.telemetry import metrics as _metrics

__all__ = [
    "MAX_VERSIONS",
    "NUM_BINS",
    "FP_DRIFT_FLUSH",
    "ScoreSketch",
    "CalibrationSketch",
    "DriftMonitor",
    "MONITOR",
    "observe_scores",
    "observe_labeled",
    "population_stability_index",
    "reset",
]

#: Ring capacity: drift rows for at most this many versions are retained;
#: publishing version N+9 evicts the oldest — same boundedness contract
#: as the request tracer's flight ring.
MAX_VERSIONS = 8

#: Fixed histogram bins over [0, 1] (mean predictions post-link; values
#: outside clamp into the edge bins so linear-task margins still sketch).
NUM_BINS = 10

#: A version needs this many observed scores before it can anchor a PSI
#: baseline — PSI against a near-empty histogram is noise, not drift.
MIN_BASELINE_SAMPLES = 50

FP_DRIFT_FLUSH = faults.register_point(
    "quality.drift_flush",
    description="quality drift snapshot assembly (the /metricsz and "
    "telemetry-flush provider) — observability, never control: a raise "
    "here drops the section from one snapshot and nothing else",
)


def population_stability_index(
    expected: np.ndarray, actual: np.ndarray
) -> float:
    """PSI between two histograms (counts). The standard drift score:
    < 0.1 stable, 0.1-0.25 moderate shift, > 0.25 investigate. Zero
    bins are floored so an empty bin contributes a finite term."""
    e = np.asarray(expected, np.float64)
    a = np.asarray(actual, np.float64)
    if e.sum() <= 0 or a.sum() <= 0:
        return 0.0
    ep = np.maximum(e / e.sum(), 1e-6)
    ap = np.maximum(a / a.sum(), 1e-6)
    return ((ap - ep) * np.log(ap / ep)).sum().item()


class ScoreSketch:
    """Streaming moments + fixed histogram of one version's scores."""

    __slots__ = ("count", "total", "total_sq", "min", "max", "bins")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.total_sq = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.bins = np.zeros((NUM_BINS,), np.int64)

    def update(self, scores: np.ndarray) -> None:
        if scores.size == 0:
            return
        s = scores.ravel()
        self.count += int(s.size)
        self.total += s.sum().item()
        self.total_sq += (s * s).sum().item()
        mn, mx = s.min().item(), s.max().item()
        self.min = mn if self.min is None else min(self.min, mn)
        self.max = mx if self.max is None else max(self.max, mx)
        idx = np.clip((s * NUM_BINS).astype(np.int64), 0, NUM_BINS - 1)
        self.bins += np.bincount(idx, minlength=NUM_BINS)

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        mean = self.total / self.count
        var = max(self.total_sq / self.count - mean * mean, 0.0)
        return {
            "count": self.count,
            "mean": round(mean, 6),
            "std": round(var ** 0.5, 6),
            "min": round(self.min, 6),
            "max": round(self.max, 6),
            "histogram": self.bins.tolist(),
        }


class CalibrationSketch:
    """Per-bin (predicted sum, label sum, count) from labeled feedback:
    the online calibration view — observed rate vs mean prediction per
    score bin, and the worst per-bin gap as one scalar."""

    __slots__ = ("count", "bin_count", "bin_pred", "bin_label")

    def __init__(self):
        self.count = 0
        self.bin_count = np.zeros((NUM_BINS,), np.int64)
        self.bin_pred = np.zeros((NUM_BINS,), np.float64)
        self.bin_label = np.zeros((NUM_BINS,), np.float64)

    def update(self, predicted: np.ndarray, labels: np.ndarray) -> None:
        p = predicted.ravel()
        y = labels.ravel()
        if p.size == 0 or p.size != y.size:
            return
        self.count += int(p.size)
        idx = np.clip((p * NUM_BINS).astype(np.int64), 0, NUM_BINS - 1)
        self.bin_count += np.bincount(idx, minlength=NUM_BINS)
        self.bin_pred += np.bincount(idx, weights=p, minlength=NUM_BINS)
        self.bin_label += np.bincount(idx, weights=y, minlength=NUM_BINS)

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        live = self.bin_count > 0
        n = np.maximum(self.bin_count, 1)
        pred_mean = self.bin_pred / n
        obs_rate = self.bin_label / n
        gaps = np.where(live, np.abs(pred_mean - obs_rate), 0.0)
        return {
            "count": self.count,
            "bin_count": self.bin_count.tolist(),
            "predicted_mean": np.round(pred_mean, 6).tolist(),
            "observed_rate": np.round(obs_rate, 6).tolist(),
            "max_gap": round(gaps.max().item(), 6),
        }


class DriftMonitor:
    """Bounded per-version drift state behind one lock; the module-level
    :data:`MONITOR` instance is what the serving engine and nearline
    updater feed and what the ``"quality"`` snapshot section reads."""

    def __init__(self, max_versions: int = MAX_VERSIONS):
        self.max_versions = max_versions
        self._lock = threading.Lock()
        # insertion-ordered: eviction pops the oldest-inserted version
        self._scores: dict[str, ScoreSketch] = {}
        self._calibration: dict[str, CalibrationSketch] = {}

    def _sketch_locked(self, table: dict, version: str, factory):
        got = table.get(version)
        if got is None:
            got = table[version] = factory()
            self._evict_locked()
        return got

    def _evict_locked(self) -> None:
        versions = list(
            dict.fromkeys(list(self._scores) + list(self._calibration))
        )
        while len(versions) > self.max_versions:
            oldest = versions.pop(0)
            self._scores.pop(oldest, None)
            self._calibration.pop(oldest, None)
            _metrics.counter("quality.versions_evicted").inc()

    def observe_scores(self, version: str, scores: np.ndarray) -> None:
        with self._lock:
            sketch = self._sketch_locked(
                self._scores, version, ScoreSketch
            )
            sketch.update(scores)
        _metrics.counter("quality.scores_observed").inc(int(scores.size))

    def observe_labeled(
        self, version: str, predicted: np.ndarray, labels: np.ndarray
    ) -> None:
        with self._lock:
            sketch = self._sketch_locked(
                self._calibration, version, CalibrationSketch
            )
            sketch.update(predicted, labels)
        _metrics.counter("quality.labeled_observed").inc(
            int(np.size(labels))
        )

    def snapshot_rows(self) -> dict:
        """The ``"quality"`` snapshot section: one row per retained
        version (insertion order = publish order), PSI against the
        oldest version with enough samples."""
        faults.fault_point(FP_DRIFT_FLUSH)
        with self._lock:
            versions = list(
                dict.fromkeys(list(self._scores) + list(self._calibration))
            )
            score_summaries = {
                v: s.summary() for v, s in self._scores.items()
            }
            cal_summaries = {
                v: c.summary() for v, c in self._calibration.items()
            }
        baseline = None
        for v in versions:
            s = score_summaries.get(v)
            if s and s.get("count", 0) >= MIN_BASELINE_SAMPLES:
                baseline = v
                break
        rows = {}
        for v in versions:
            row: dict = {}
            s = score_summaries.get(v)
            if s is not None:
                row["scores"] = s
                if (
                    baseline is not None
                    and v != baseline
                    and s.get("count", 0) > 0
                ):
                    row["psi_vs_baseline"] = round(
                        population_stability_index(
                            np.array(
                                score_summaries[baseline]["histogram"]
                            ),
                            np.array(s["histogram"]),
                        ),
                        6,
                    )
            c = cal_summaries.get(v)
            if c is not None:
                row["calibration"] = c
            rows[v] = row
        return {"versions": rows, "baseline_version": baseline}


#: Process-global monitor; module-level helpers delegate to it.
MONITOR = DriftMonitor()


def observe_scores(version: Optional[str], scores: np.ndarray) -> None:
    """Feed one chunk of HOST-side mean predictions (post
    ``telemetry.sync_fetch``) into ``version``'s drift sketch. A None
    version (an engine constructed without one) sketches under
    ``"unversioned"`` so ad-hoc engines still drift-track."""
    MONITOR.observe_scores(version or "unversioned", scores)


def observe_labeled(
    version: Optional[str], predicted: np.ndarray, labels: np.ndarray
) -> None:
    """Feed labeled feedback (host arrays) into ``version``'s
    calibration bins — the nearline updater's flush-path hook."""
    MONITOR.observe_labeled(version or "unversioned", predicted, labels)


def reset() -> None:
    """Drop all drift state (test isolation). The snapshot provider
    registration survives — it is wiring, not run state."""
    global MONITOR
    MONITOR = DriftMonitor()


def _provider() -> dict:
    return MONITOR.snapshot_rows()


_metrics.register_snapshot_provider("quality", _provider)
