"""Champion/challenger publish gate: candidate quality stats with
bootstrap error bars, and the no-regression decision.

The freshness conductor publishes versions continuously; until this
layer nothing asked whether a candidate is actually BETTER than — or at
least not worse than — the champion it replaces. The gate closes that
loop (ISSUE 20 leg 2):

- :func:`game_quality_stats` scores a model on an evaluation set and
  returns :class:`QualityStats` — validation AUC with a bootstrap
  confidence interval (B host-side multinomial weight resamples of the
  one fetched margin vector; no extra device solves) plus Hosmer–
  Lemeshow calibration for logistic tasks. The JSON form is what
  ``publish_version`` stamps into version metadata and lineage.
- :func:`decide_gate` compares a candidate against the lineage-linked
  champion's recorded stats: a candidate whose AUC falls BELOW the
  champion's bootstrap CI lower bound (i.e. a regression the error bars
  cannot explain), or whose H-L calibration collapses while the
  champion's held, is refused. ``serving/registry.py`` turns a refusal
  into a quarantined version directory and raises
  :class:`QualityGateRefused`; callers (``cli refresh``, the pipeline
  conductor) record the decision instead of swapping the model in.

Gate policy in one line: *publish unless the champion's own error bars
say the candidate regressed.* The CI — not a fixed epsilon — sets the
tolerance, so noisy small-data refreshes gate loosely and well-measured
champions gate tightly. ``override=True`` (``--no-quality-gate``)
records a ``bypassed`` decision and publishes anyway.

Fault seam: ``quality.publish_gate`` fires at the top of the gated
publish path, BEFORE any registry write — a hard kill mid-evaluation
must leave the registry without a partial or wrongly-quarantined
version (``tools/chaos.py --quality``).

AUC is computed on margins (scores + offsets): every supported link is
monotone, so ranking — hence AUC — is link-invariant, and the single
``telemetry.device.sync_fetch`` of the margin vector is the only
device->host crossing in this module.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from photon_ml_tpu import faults
from photon_ml_tpu import telemetry

__all__ = [
    "FP_PUBLISH_GATE",
    "HL_P_FLOOR",
    "QualityStats",
    "GateDecision",
    "QualityGateRefused",
    "weighted_auc",
    "game_quality_stats",
    "decide_gate",
]

FP_PUBLISH_GATE = faults.register_point(
    "quality.publish_gate",
    description="gated publish_version, after candidate stats are in "
    "hand but before ANY registry write — a kill here must leave the "
    "registry exactly as it was (no partial, no wrong quarantine)",
)

#: A candidate whose Hosmer-Lemeshow p-value drops below this while the
#: champion's held above it is mis-calibrated beyond noise: quarantine.
HL_P_FLOOR = 1e-4


def weighted_auc(
    scores: np.ndarray, labels: np.ndarray, weights: np.ndarray
) -> float:
    """Exact weighted ROC AUC on host arrays (ties count half), the
    probability a random positive outranks a random negative. NaN when
    either class has no weight — degenerate sets cannot gate."""
    s = np.asarray(scores, np.float64).ravel()
    y = np.asarray(labels, np.float64).ravel()
    w = np.asarray(weights, np.float64).ravel()
    pos = y > 0.5
    wpos = np.where(pos, w, 0.0)
    wneg = np.where(pos, 0.0, w)
    tot_pos, tot_neg = wpos.sum(), wneg.sum()
    if tot_pos <= 0 or tot_neg <= 0:
        return float("nan")
    _, inv = np.unique(s, return_inverse=True)
    pos_per = np.bincount(inv, weights=wpos)
    neg_per = np.bincount(inv, weights=wneg)
    neg_below = np.cumsum(neg_per) - neg_per
    num = (pos_per * (neg_below + 0.5 * neg_per)).sum()
    return (num / (tot_pos * tot_neg)).item()


@dataclasses.dataclass
class QualityStats:
    """One model's gate-relevant quality on one evaluation set; the
    JSON form rides version metadata (``extra.quality``) and lineage."""

    auc: float
    auc_ci_low: float
    auc_ci_high: float
    rows: int
    bootstrap_samples: int
    hl_chi_square: Optional[float] = None
    hl_p_value: Optional[float] = None

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        return {k: v for k, v in out.items() if v is not None}

    @classmethod
    def from_json(cls, payload: dict) -> "QualityStats":
        """Tolerant load from a metadata quality block (extra keys —
        the recorded gate decision, bootstrap summaries — ignored)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        kept = {k: v for k, v in payload.items() if k in fields}
        kept.setdefault("auc", float("nan"))
        kept.setdefault("auc_ci_low", float("nan"))
        kept.setdefault("auc_ci_high", float("nan"))
        kept.setdefault("rows", 0)
        kept.setdefault("bootstrap_samples", 0)
        return cls(**kept)


def game_quality_stats(
    model,
    data,
    num_samples: int = 32,
    seed: int = 0,
) -> QualityStats:
    """Candidate quality on ``data``: AUC with a ``num_samples``-way
    bootstrap CI, plus H-L calibration for logistic tasks. One device
    fetch (the margin vector); resampling is host-side reweighting, so
    B=32 costs milliseconds on top of the score pass."""
    from photon_ml_tpu.ops.losses import get_loss

    scores = model.score(data)
    fetched = telemetry.sync_fetch(scores, label="quality.gate_scores")
    n = int(data.num_rows)
    margins = np.asarray(fetched, np.float64)[:n] + np.asarray(
        data.offset, np.float64
    )[:n]
    labels = np.asarray(data.response, np.float64)[:n]
    weights = np.asarray(data.weight, np.float64)[:n]

    auc = weighted_auc(margins, labels, weights)
    lo = hi = auc
    if num_samples > 0 and n > 1 and not math.isnan(auc):
        rng = np.random.default_rng(seed)
        counts = rng.multinomial(n, np.full(n, 1.0 / n), size=num_samples)
        resampled = [
            weighted_auc(margins, labels, weights * counts[b])
            for b in range(num_samples)
        ]
        resampled = [a for a in resampled if not math.isnan(a)]
        if resampled:
            lo, hi = np.percentile(resampled, [2.5, 97.5]).tolist()

    hl_chi = hl_p = None
    if get_loss(model.task).name == "logistic":
        from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow

        probs = 1.0 / (1.0 + np.exp(-margins))
        try:
            report = hosmer_lemeshow(probs, labels, weights)
            hl_chi = round(float(report.chi_square), 6)
            hl_p = float(report.p_value)
        except Exception:  # noqa: BLE001 — calibration is advisory
            pass

    telemetry.counter("quality.stats_computed").inc()
    return QualityStats(
        auc=auc,
        auc_ci_low=lo,
        auc_ci_high=hi,
        rows=n,
        bootstrap_samples=num_samples,
        hl_chi_square=hl_chi,
        hl_p_value=hl_p,
    )


@dataclasses.dataclass
class GateDecision:
    """The recorded outcome of one gated publish attempt."""

    decision: str  # published | quarantined | bypassed | no_champion
    reason: str
    champion_version: Optional[str] = None
    candidate: Optional[dict] = None
    champion: Optional[dict] = None
    metric: str = "auc"

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        return {k: v for k, v in out.items() if v is not None}


class QualityGateRefused(RuntimeError):
    """A gated publish refused the candidate. ``decision`` carries the
    full :class:`GateDecision`; ``quarantine_path`` the directory the
    registry parked the refused version under (invisible to version
    scans), so the evidence survives for offline diagnosis."""

    def __init__(self, decision: GateDecision, quarantine_path=None):
        super().__init__(
            f"quality gate refused candidate vs champion "
            f"{decision.champion_version}: {decision.reason}"
        )
        self.decision = decision
        self.quarantine_path = quarantine_path


def decide_gate(
    candidate: QualityStats,
    champion_quality: Optional[dict],
    champion_version: Optional[str] = None,
    override: bool = False,
    hl_p_floor: float = HL_P_FLOOR,
) -> GateDecision:
    """Champion/challenger comparison. Quarantine iff a champion with
    recorded stats exists AND (the candidate's AUC falls below the
    champion's bootstrap CI lower bound, or the candidate's H-L
    calibration collapsed below ``hl_p_floor`` while the champion's
    held). Everything else publishes, with the reason recorded."""
    cand_json = candidate.to_json()
    if override:
        return GateDecision(
            decision="bypassed",
            reason="gate override requested (--no-quality-gate)",
            champion_version=champion_version,
            candidate=cand_json,
            champion=champion_quality,
        )
    if champion_quality is None:
        return GateDecision(
            decision="no_champion",
            reason="no champion with recorded quality stats in lineage",
            candidate=cand_json,
        )
    champ = QualityStats.from_json(champion_quality)
    if math.isnan(candidate.auc) or math.isnan(champ.auc_ci_low):
        return GateDecision(
            decision="published",
            reason="AUC undefined on one side (degenerate eval set); "
            "gate cannot compare — publishing",
            champion_version=champion_version,
            candidate=cand_json,
            champion=champion_quality,
        )
    if candidate.auc < champ.auc_ci_low:
        return GateDecision(
            decision="quarantined",
            reason=(
                f"candidate auc {candidate.auc:.6f} below champion "
                f"bootstrap CI lower bound {champ.auc_ci_low:.6f} "
                f"(champion auc {champ.auc:.6f})"
            ),
            champion_version=champion_version,
            candidate=cand_json,
            champion=champion_quality,
        )
    if (
        candidate.hl_p_value is not None
        and candidate.hl_p_value < hl_p_floor
        and (champ.hl_p_value is None or champ.hl_p_value >= hl_p_floor)
    ):
        return GateDecision(
            decision="quarantined",
            reason=(
                f"candidate Hosmer-Lemeshow p {candidate.hl_p_value:.2e} "
                f"below floor {hl_p_floor:.0e} while champion held "
                f"(champion p "
                f"{'n/a' if champ.hl_p_value is None else format(champ.hl_p_value, '.2e')})"
            ),
            champion_version=champion_version,
            candidate=cand_json,
            champion=champion_quality,
        )
    return GateDecision(
        decision="published",
        reason=(
            f"candidate auc {candidate.auc:.6f} within champion CI "
            f"[{champ.auc_ci_low:.6f}, {champ.auc_ci_high:.6f}]"
        ),
        champion_version=champion_version,
        candidate=cand_json,
        champion=champion_quality,
    )
