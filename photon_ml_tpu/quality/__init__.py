"""Quality observability (ISSUE 20): the fourth observability layer —
model quality over time.

Three legs, one package:

- :mod:`photon_ml_tpu.quality.gate` — champion/challenger publish gate:
  candidate AUC/H-L stats with bootstrap error bars
  (:func:`game_quality_stats`) and the no-regression decision
  (:func:`decide_gate`), enforced inside ``serving.registry
  .publish_version`` and recorded in version metadata + lineage.
- :mod:`photon_ml_tpu.quality.drift` — online score-distribution and
  calibration-bin sketches fed by ``ScoringEngine.score_rows`` and the
  nearline updater, published as the ``"quality"`` section of every
  ``telemetry.snapshot()`` (``/metricsz``, JSONL flush, RunReport).
- The GLMix bootstrap itself (B resamples as vmapped lanes riding the
  sweep machinery) lives in :mod:`photon_ml_tpu.diagnostics.bootstrap`
  with the solver factory in :mod:`photon_ml_tpu.sweep.runner` — this
  package consumes its summaries in the published quality block.

Importing the package registers both fault seams (``quality
.publish_gate``, ``quality.drift_flush``) and the drift snapshot
provider.
"""

from __future__ import annotations

from photon_ml_tpu.quality import drift  # noqa: F401
from photon_ml_tpu.quality.drift import (  # noqa: F401
    FP_DRIFT_FLUSH,
    DriftMonitor,
    observe_labeled,
    observe_scores,
    population_stability_index,
)
from photon_ml_tpu.quality.gate import (  # noqa: F401
    FP_PUBLISH_GATE,
    GateDecision,
    QualityGateRefused,
    QualityStats,
    decide_gate,
    game_quality_stats,
    weighted_auc,
)

__all__ = [
    "drift",
    "FP_DRIFT_FLUSH",
    "DriftMonitor",
    "observe_labeled",
    "observe_scores",
    "population_stability_index",
    "FP_PUBLISH_GATE",
    "GateDecision",
    "QualityGateRefused",
    "QualityStats",
    "decide_gate",
    "game_quality_stats",
    "weighted_auc",
]
