"""Native-accelerated Avro ingestion: schema -> program compiler + driver.

``read_game_arrays_native`` is the fast path behind
:func:`photon_ml_tpu.data.avro.read_game_dataset_from_avro`: it compiles
the record schema into a compact i32 program (opcodes mirrored in
native/avro_decode.cpp), hands the container blocks to the C++
interpreter, and gets back columnar numpy arrays — labels/offsets/weights,
per-shard COO triples, and interned id columns. ~60x the pure-Python
schema-walking decoder (PERF_NOTES.md).

Returns None whenever anything is unsupported (exotic schema shapes,
missing native toolchain, non-deflate codec) — callers always keep the
pure-Python path, so this is a transparent accelerator, never a
requirement (same contract as parse_libsvm_native).
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.data.native import load_native

# opcodes — keep in sync with native/avro_decode.cpp
OP_SKIP_LONG = 1
OP_SKIP_FLOAT = 2
OP_SKIP_DOUBLE = 3
OP_SKIP_BYTES = 4
OP_SKIP_BOOL = 5
OP_SKIP_FIXED = 6
OP_SCALAR_D = 7
OP_SCALAR_F = 8
OP_SCALAR_L = 9
OP_SCALAR_B = 10
OP_UNION = 11
OP_FEATURE_BAG = 12
OP_FNAME = 13
OP_FTERM = 14
OP_FVALUE_D = 15
OP_FVALUE_F = 16
OP_ID_FIELD = 17
OP_ID_MAP = 18
OP_ARRAY_SKIP = 19
OP_MAP_SKIP = 20

_DEST = {"label": 0, "offset": 1, "weight": 2}


class _Unsupported(Exception):
    pass


def _resolve(schema, named):
    if isinstance(schema, str) and schema in named:
        return named[schema]
    return schema


def _skip_ops(schema, named) -> list[int]:
    """Program that SKIPS one value of ``schema``."""
    schema = _resolve(schema, named)
    if isinstance(schema, str):
        return {
            "null": [],
            "boolean": [OP_SKIP_BOOL],
            "int": [OP_SKIP_LONG],
            "long": [OP_SKIP_LONG],
            "float": [OP_SKIP_FLOAT],
            "double": [OP_SKIP_DOUBLE],
            "string": [OP_SKIP_BYTES],
            "bytes": [OP_SKIP_BYTES],
        }[schema]
    if isinstance(schema, list):
        branches = [_skip_ops(s, named) for s in schema]
        out = [OP_UNION, len(branches)] + [len(b) for b in branches]
        for b in branches:
            out.extend(b)
        return out
    t = schema["type"]
    if t == "record":
        out = []
        for f in schema["fields"]:
            out.extend(_skip_ops(f["type"], named))
        return out
    if t == "array":
        item = _skip_ops(schema["items"], named)
        return [OP_ARRAY_SKIP, len(item)] + item
    if t == "map":
        val = _skip_ops(schema["values"], named)
        return [OP_MAP_SKIP, len(val)] + val
    if t == "enum":
        return [OP_SKIP_LONG]
    if t == "fixed":
        return [OP_SKIP_FIXED, int(schema["size"])]
    if isinstance(t, (str, dict, list)):
        return _skip_ops(t, named)
    raise _Unsupported(f"skip {schema}")


def _scalar_ops(schema, named, op_by_type: dict) -> list[int]:
    """Program reading one numeric/union-null scalar into a channel."""
    schema = _resolve(schema, named)
    if isinstance(schema, str):
        if schema not in op_by_type:
            raise _Unsupported(f"scalar type {schema}")
        return list(op_by_type[schema])
    if isinstance(schema, list):
        branches = [_scalar_ops(s, named, op_by_type) for s in schema]
        out = [OP_UNION, len(branches)] + [len(b) for b in branches]
        for b in branches:
            out.extend(b)
        return out
    raise _Unsupported(f"scalar {schema}")


def _feature_item_ops(schema, named) -> list[int]:
    """Program for one feature-bag item (name/term/value record)."""
    schema = _resolve(schema, named)
    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        raise _Unsupported("feature item is not a record")
    out = []
    seen_name = seen_value = False
    for f in schema["fields"]:
        ft = _resolve(f["type"], named)
        if f["name"] == "name" and ft == "string":
            out.append(OP_FNAME)
            seen_name = True
        elif f["name"] == "term":
            if ft != "string":
                # skipping a mistyped term would silently collapse distinct
                # name+term keys into one feature — refuse, fall back
                raise _Unsupported("feature term is not a plain string")
            out.append(OP_FTERM)
        elif f["name"] == "value" and ft in ("double", "float"):
            out.append(OP_FVALUE_D if ft == "double" else OP_FVALUE_F)
            seen_value = True
        else:
            out.extend(_skip_ops(f["type"], named))
    if not (seen_name and seen_value):
        raise _Unsupported("feature item lacks name/value")
    return out


def compile_program(
    schema: dict,
    feature_shards: Mapping[str, Sequence[str]],
    id_columns: Sequence[str],
) -> Optional[np.ndarray]:
    """Schema -> i32 program, or None if the shape is unsupported."""
    named: dict = {}

    def collect(s):
        if isinstance(s, dict):
            t = s.get("type")
            if t in ("record", "enum", "fixed") and "name" in s:
                named[s["name"]] = s
            if t == "record":
                for f in s["fields"]:
                    collect(f["type"])
            elif t == "array":
                collect(s["items"])
            elif t == "map":
                collect(s["values"])
        elif isinstance(s, list):
            for x in s:
                collect(x)

    collect(schema)
    bag_to_shard = {}
    for si, (_, bags) in enumerate(feature_shards.items()):
        for b in bags:
            if b in bag_to_shard:
                # one bag feeding MULTIPLE shards is legal (shard merging);
                # the program format emits a bag into one shard only, so
                # fall back to the pure-Python reader
                return None
            bag_to_shard[b] = si
    id_pos = {c: i for i, c in enumerate(id_columns)}

    scal = {
        "double": [OP_SCALAR_D],
        "float": [OP_SCALAR_F],
        "int": [OP_SCALAR_L],
        "long": [OP_SCALAR_L],
        "boolean": [OP_SCALAR_B],
        "null": [],
    }
    try:
        if not (isinstance(schema, dict) and schema.get("type") == "record"):
            raise _Unsupported("top level is not a record")
        out: list[int] = []
        for f in schema["fields"]:
            name = f["name"]
            ft = _resolve(f["type"], named)
            if name in _DEST:
                dest = _DEST[name]
                ops = _scalar_ops(
                    f["type"], named,
                    {k: (v + [dest] if v else v) for k, v in scal.items()},
                )
                out.extend(ops)
            elif name in bag_to_shard:
                if not (isinstance(ft, dict) and ft.get("type") == "array"):
                    raise _Unsupported(f"feature bag '{name}' is not an array")
                item = _feature_item_ops(ft["items"], named)
                out.extend(
                    [OP_FEATURE_BAG, bag_to_shard[name], len(item)] + item
                )
            elif name in id_pos:
                ops = None
                if ft == "string":
                    ops = [OP_ID_FIELD, id_pos[name]]
                elif isinstance(ft, list):
                    branches = []
                    for s in ft:
                        s_r = _resolve(s, named)
                        if s_r == "string":
                            branches.append([OP_ID_FIELD, id_pos[name]])
                        elif s_r == "null":
                            branches.append([])
                        else:
                            raise _Unsupported("id field union branch")
                    ops = [OP_UNION, len(branches)] + [
                        len(b) for b in branches
                    ]
                    for b in branches:
                        ops.extend(b)
                else:
                    raise _Unsupported("id field is not a string")
                out.extend(ops)
            elif name == "metadataMap":
                mt = ft
                if isinstance(mt, list):  # union-null metadataMap
                    branches = []
                    for s in mt:
                        s_r = _resolve(s, named)
                        if s_r == "null":
                            branches.append([])
                        elif (
                            isinstance(s_r, dict)
                            and s_r.get("type") == "map"
                            and _resolve(s_r["values"], named) == "string"
                        ):
                            branches.append([OP_ID_MAP])
                        else:
                            raise _Unsupported("metadataMap union branch")
                    out.extend(
                        [OP_UNION, len(branches)]
                        + [len(b) for b in branches]
                    )
                    for b in branches:
                        out.extend(b)
                elif (
                    isinstance(mt, dict)
                    and mt.get("type") == "map"
                    and _resolve(mt["values"], named) == "string"
                ):
                    out.append(OP_ID_MAP)
                else:
                    raise _Unsupported("metadataMap shape")
            else:
                out.extend(_skip_ops(f["type"], named))
        return np.asarray(out, np.int32)
    except (_Unsupported, KeyError):
        return None


def _concat_strs(strs: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
    enc = [s.encode("utf-8") for s in strs]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    blob = np.frombuffer(b"".join(enc), np.uint8).copy() if enc else np.zeros(
        0, np.uint8
    )
    return blob, offs


def index_map_blobs(
    shard_names: Sequence[str],
    index_maps: Optional[Mapping[str, Mapping[str, int]]],
):
    """Index maps -> the flat (feat_bytes, feat_offs, feat_ids,
    shard_key_counts) arrays ``avro_parse`` consumes, or None when a map
    is duck-typed (no ``.keys()``; the pure-Python reader handles those).
    Shared by the one-shot reader below and the ingest pipeline's decode
    workers (photon_ml_tpu.ingest.decode), which build the blobs ONCE and
    reuse them across every chunk."""
    if index_maps is None:
        return (
            np.zeros(0, np.uint8),
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            np.full(len(shard_names), -1, np.int64),
        )
    key_blobs, key_offs, key_ids, key_counts = [], [], [], []
    byte_base = 0
    for s in shard_names:
        imap = index_maps[s]
        try:
            keys = list(imap.keys())
        except (AttributeError, TypeError):
            # duck-typed maps (e.g. MmapIndexMap) expose only get/len
            return None
        blob, offs = _concat_strs(keys)
        key_blobs.append(blob)
        # offsets address the CONCATENATED byte blob across shards
        key_offs.append(offs + byte_base)
        byte_base += len(blob)
        key_ids.append(np.asarray([imap[k] for k in keys], np.int64))
        key_counts.append(len(keys))
    feat_bytes = np.concatenate(key_blobs) if key_blobs else np.zeros(
        0, np.uint8
    )
    # per-shard offset runs are stored contiguously incl. +1 slots
    feat_offs = np.concatenate(key_offs)
    feat_ids = np.concatenate(key_ids) if key_ids else np.zeros(0, np.int64)
    return feat_bytes, feat_offs, feat_ids, np.asarray(key_counts, np.int64)


_proto_ready = False


def _lib():
    global _proto_ready
    lib = load_native()
    if lib is None or not hasattr(lib, "avro_parse"):
        return None
    if not _proto_ready:
        u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        f64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        lib.avro_parse.restype = ctypes.c_void_p
        lib.avro_parse.argtypes = [
            u8, ctypes.c_int64, ctypes.c_int64, u8, ctypes.c_int32,
            i32, ctypes.c_int64, ctypes.c_int32,
            u8, i64, i64, i64,
            ctypes.c_int32, u8, i64, ctypes.c_int32,
        ]
        lib.avro_last_error.restype = ctypes.c_char_p
        lib.avro_rows.restype = ctypes.c_int64
        lib.avro_rows.argtypes = [ctypes.c_void_p]
        lib.avro_fill_scalars.argtypes = [ctypes.c_void_p, f64, f64, f64, u8]
        lib.avro_shard_nnz.restype = ctypes.c_int64
        lib.avro_shard_nnz.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.avro_fill_coo.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, f64, i64, i64,
        ]
        for fn in ("avro_shard_vocab_size", "avro_shard_vocab_bytes"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.avro_fill_shard_vocab.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, u8, i64,
        ]
        for fn in ("avro_id_vocab_size", "avro_id_vocab_bytes"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.avro_fill_ids.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, i64, u8, i64,
        ]
        lib.avro_free.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "avro_write_training_blocks"):
            lib.avro_write_training_blocks.restype = ctypes.c_int64
            lib.avro_write_training_blocks.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, f64,
                ctypes.c_int32, i64, i32, f64, u8, i64,
                ctypes.c_int32, u8, i64, i64, u8, i64, i64,
                ctypes.c_int64, u8,
            ]
            lib.avro_encode_last_error.restype = ctypes.c_char_p
        _proto_ready = True
    return lib


def write_training_blocks_native(
    path: str,
    labels: np.ndarray,
    bags: Sequence[tuple[np.ndarray, np.ndarray, np.ndarray]],
    feature_names: Sequence[str],
    id_columns: Mapping[str, tuple[np.ndarray, Sequence[str]]],
    block_records: int,
    sync: bytes,
) -> Optional[int]:
    """Append TrainingExampleAvro-shaped record blocks via
    native/avro_encode.cpp; None when the native library is unavailable
    (caller falls back to the pure-Python writer). ``bags`` is the ordered
    feature arrays, each (starts[n+1], name_id, vals); ``id_columns`` maps
    metadataMap key -> (codes[n], vocab strings)."""
    lib = _lib()
    if lib is None or not hasattr(lib, "avro_write_training_blocks"):
        return None
    name_blob, name_offs = _concat_strs(list(feature_names))
    keys = list(id_columns)
    key_blob, key_offs = _concat_strs(keys)
    n = len(labels)
    # flatten bags: starts become absolute into the concatenated arrays
    starts_flat = np.empty(len(bags) * (n + 1), np.int64)
    nid_parts, val_parts = [], []
    base = 0
    for b, (starts, nid, vals) in enumerate(bags):
        starts_flat[b * (n + 1):(b + 1) * (n + 1)] = (
            np.asarray(starts, np.int64) + base
        )
        nid_parts.append(np.asarray(nid, np.int32))
        val_parts.append(np.asarray(vals, np.float64))
        base += len(nid_parts[-1])
    codes_flat = np.empty(len(keys) * n, np.int64)
    vocab_blobs, vocab_offs, vocab_counts = [], [], []
    byte_base = 0
    for ci, k in enumerate(keys):
        codes, vocab = id_columns[k]
        codes_flat[ci * n:(ci + 1) * n] = np.asarray(codes, np.int64)
        blob, offs = _concat_strs([str(v) for v in vocab])
        vocab_blobs.append(blob)
        vocab_offs.append(offs + byte_base)
        byte_base += len(blob)
        vocab_counts.append(len(vocab))
    rc = lib.avro_write_training_blocks(
        path.encode(), n,
        np.ascontiguousarray(labels, np.float64),
        len(bags), starts_flat,
        np.concatenate(nid_parts) if nid_parts else np.zeros(0, np.int32),
        np.concatenate(val_parts) if val_parts else np.zeros(0, np.float64),
        name_blob, name_offs,
        len(keys), key_blob, key_offs, codes_flat,
        np.concatenate(vocab_blobs) if vocab_blobs else np.zeros(0, np.uint8),
        np.concatenate(vocab_offs) if vocab_offs else np.zeros(0, np.int64),
        np.asarray(vocab_counts, np.int64),
        block_records, np.frombuffer(sync, np.uint8),
    )
    if rc < 0:
        raise ValueError(
            "native avro write failed: "
            + lib.avro_encode_last_error().decode()
        )
    return int(rc)


def _decode_vocab(blob: np.ndarray, offs: np.ndarray) -> np.ndarray:
    raw = blob.tobytes()
    # native '<U' dtype (NOT object): downstream np.savez of id vocabularies
    # must stay pickle-free
    return np.asarray(
        [raw[offs[i]:offs[i + 1]].decode("utf-8")
         for i in range(len(offs) - 1)]
    )


def read_game_arrays_native(
    paths: Sequence[str],
    feature_shards: Mapping[str, Sequence[str]],
    index_maps: Optional[Mapping[str, Mapping[str, int]]],
    id_columns: Sequence[str],
    threads: int = 0,
    vocab_only: bool = False,
):
    """Parse files into columnar arrays, or None if unsupported.

    Returns ``(labels, offsets, weights, coo_per_shard, id_cols,
    shard_vocabs, label_seen, file_rows)`` where ``coo_per_shard[shard] =
    (vals, rows, cols)``, ``id_cols[ci] = (codes, vocab)`` (dense interned
    codes + first-seen vocabulary — never materialized per-row strings),
    ``label_seen`` marks rows whose label field was PRESENT (a genuine
    NaN label stays distinguishable from absent), and ``file_rows[i]`` is
    the row count contributed by ``paths[i]`` (diagnostics map merged row
    indices back to a path + local record); with ``index_maps`` given,
    cols are final dense ids and unknown features are dropped; without,
    cols index ``shard_vocabs[shard]`` (first-seen interning order) for
    the caller to remap.

    ``threads``: parallel block-decode workers (0 = one per host core;
    env ``PHOTON_AVRO_THREADS`` overrides) — Avro blocks are
    sync-delimited and independent, so the file decodes block-parallel
    the way the reference decodes per-partition on executors
    (AvroDataReader.scala:87-237).
    """
    lib = _lib()
    if lib is None:
        return None
    if threads <= 0:
        threads = int(os.environ.get("PHOTON_AVRO_THREADS", "0") or 0)

    shard_names = list(feature_shards)
    blobs = index_map_blobs(shard_names, index_maps)
    if blobs is None:
        return None  # duck-typed maps: fall back to the Python reader
    feat_bytes, feat_offs, feat_ids, shard_key_counts = blobs

    id_blob, id_offs = _concat_strs(list(id_columns))

    all_parts = []
    from photon_ml_tpu.data.avro import _MAGIC, _Reader, _decode

    prog_cache: dict[str, np.ndarray] = {}
    for path in paths:
        with open(path, "rb") as f:
            raw = f.read()
        if raw[:4] != _MAGIC:
            return None
        data = np.frombuffer(raw, np.uint8)
        r = _Reader(raw)
        r.pos = 4
        meta = _decode(r, {"type": "map", "values": "bytes"}, {})
        schema_json = meta["avro.schema"].decode()
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            return None
        prog_f = prog_cache.get(schema_json)
        if prog_f is None:  # schemas may differ across daily files
            prog_f = compile_program(
                json.loads(schema_json), feature_shards, id_columns
            )
            if prog_f is None:
                return None
            prog_cache[schema_json] = prog_f
        sync = np.frombuffer(r.buf[r.pos:r.pos + 16], np.uint8).copy()
        block_start = r.pos + 16

        handle = lib.avro_parse(
            data, len(data), block_start, sync,
            1 if codec == "deflate" else 0,
            prog_f, len(prog_f), len(shard_names),
            feat_bytes, feat_offs, feat_ids, shard_key_counts,
            len(id_columns), id_blob, id_offs, threads,
        )
        if not handle:
            err = lib.avro_last_error().decode()
            raise ValueError(f"{path}: {err}")
        try:
            n = lib.avro_rows(handle)
            if vocab_only:
                # index-building wants only the interned key vocabularies:
                # skip the COO/scalar numpy materialization (the C-side
                # buffers are freed with the handle)
                labels = np.zeros(0, np.float64)
                offsets = weights = labels
                label_seen = np.zeros(0, np.uint8)
            else:
                labels = np.empty(n, np.float64)
                offsets = np.empty(n, np.float64)
                weights = np.empty(n, np.float64)
                label_seen = np.empty(n, np.uint8)
                lib.avro_fill_scalars(handle, labels, offsets, weights,
                                      label_seen)
            coo = []
            vocabs = []
            for si in range(len(shard_names)):
                if vocab_only:
                    coo.append((np.zeros(0), np.zeros(0, np.int64),
                                np.zeros(0, np.int64)))
                else:
                    nnz = lib.avro_shard_nnz(handle, si)
                    v = np.empty(nnz, np.float64)
                    rw = np.empty(nnz, np.int64)
                    cl = np.empty(nnz, np.int64)
                    lib.avro_fill_coo(handle, si, v, rw, cl)
                    coo.append((v, rw, cl))
                if index_maps is None:
                    nv = lib.avro_shard_vocab_size(handle, si)
                    nb = lib.avro_shard_vocab_bytes(handle, si)
                    blob = np.empty(nb, np.uint8)
                    offs = np.empty(nv + 1, np.int64)
                    lib.avro_fill_shard_vocab(handle, si, blob, offs)
                    vocabs.append(_decode_vocab(blob, offs))
                else:
                    vocabs.append(None)
            idvals = []
            for ci in range(len(id_columns)):
                codes = np.empty(n, np.int64)
                nb = lib.avro_id_vocab_bytes(handle, ci)
                nv = lib.avro_id_vocab_size(handle, ci)
                blob = np.empty(nb, np.uint8)
                offs = np.empty(nv + 1, np.int64)
                lib.avro_fill_ids(handle, ci, codes, blob, offs)
                if np.any(codes < 0):
                    bad = int(np.argmax(codes < 0))
                    raise KeyError(
                        f"{path}: record {bad} lacks id column "
                        f"'{id_columns[ci]}' (top-level field or "
                        "metadataMap entry)"
                    )
                idvals.append((codes, _decode_vocab(blob, offs)))
        finally:
            lib.avro_free(handle)
        all_parts.append(
            (labels, offsets, weights, coo, idvals, vocabs, label_seen)
        )

    return _merge_parts(all_parts, len(shard_names), len(id_columns))


def _merge_parts(parts, n_shards: int, n_ids: int):
    """Concatenate per-file results, re-basing row indices and re-mapping
    per-file intern vocabularies onto a merged first-seen vocabulary.
    Appends per-file row counts so callers can name the source file of a
    merged row in diagnostics."""
    file_rows = [len(p[0]) for p in parts]
    if len(parts) == 1:
        return (*parts[0], file_rows)
    labels = np.concatenate([p[0] for p in parts])
    label_seen = np.concatenate([p[6] for p in parts])
    offsets = np.concatenate([p[1] for p in parts])
    weights = np.concatenate([p[2] for p in parts])
    row_bases = np.cumsum([0] + [len(p[0]) for p in parts[:-1]])
    coo = []
    vocabs = []
    for si in range(n_shards):
        vals = np.concatenate([p[3][si][0] for p in parts])
        rows = np.concatenate(
            [p[3][si][1] + base for p, base in zip(parts, row_bases)]
        )
        if parts[0][5][si] is None:
            cols = np.concatenate([p[3][si][2] for p in parts])
            vocabs.append(None)
        else:
            merged: dict[str, int] = {}
            col_parts = []
            for p in parts:
                vocab = p[5][si]
                remap = np.empty(len(vocab), np.int64)
                for i, k in enumerate(vocab):
                    if k not in merged:
                        merged[k] = len(merged)
                    remap[i] = merged[k]
                col_parts.append(remap[p[3][si][2]])
            cols = np.concatenate(col_parts)
            vocabs.append(np.asarray(list(merged)))
        coo.append((vals, rows, cols))
    idvals = []
    for ci in range(n_ids):
        merged_ids: dict[str, int] = {}
        code_parts = []
        for p in parts:
            codes, vocab = p[4][ci]
            remap = np.empty(len(vocab), np.int64)
            for i, k in enumerate(vocab):
                if k not in merged_ids:
                    merged_ids[k] = len(merged_ids)
                remap[i] = merged_ids[k]
            code_parts.append(remap[codes] if len(codes) else codes)
        idvals.append(
            (np.concatenate(code_parts), np.asarray(list(merged_ids)))
        )
    return (
        labels, offsets, weights, coo, idvals, vocabs, label_seen, file_rows
    )
