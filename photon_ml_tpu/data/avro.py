"""Avro ingestion/egress: a dependency-free Avro binary codec plus the
Photon wire formats (TrainingExampleAvro, BayesianLinearModelAvro,
ScoringResultAvro).

Reference analog: photon-client data/avro/ (AvroDataReader.scala:87-237,
AvroUtils.scala, ModelProcessingUtils.scala, ScoreProcessingUtils.scala) and
the photon-avro-schemas module's .avsc files. The environment has no avro
library, so this module implements the Avro 1.x object-container format
directly (spec: binary encoding with zigzag varints; container = magic
'Obj\\x01' + metadata map + 16-byte sync marker + blocks, each
[count, byte-size, payload, sync], codec null or deflate). The schemas below
are re-authored from the reference's .avsc definitions.

Reader semantics match AvroDataReader: features are (name, term, value)
records keyed name + '\\x01' + term (util/Utils.getFeatureKey), feature
shards merge one or more feature-bag columns (featureColumnMap), an
intercept column is appended per shard, and response/offset/weight plus id
columns come from top-level fields or the metadataMap
(GameConverters.scala:38-110).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Iterable, Iterator, Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.game.dataset import GameDataset, build_game_dataset
from photon_ml_tpu.ops.sparse import SparseBatch

_MAGIC = b"Obj\x01"

# ---------------------------------------------------------------------------
# binary encoding primitives (Avro spec section "Binary Encoding")
# ---------------------------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_long(out: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                return _zigzag_decode(acc)
            shift += 7

    def read_bytes(self) -> bytes:
        n = self.read_long()
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_fixed(self, n: int) -> bytes:
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out


# ---------------------------------------------------------------------------
# schema-driven encode/decode (generic records as Python dicts)
# ---------------------------------------------------------------------------


def _encode(out: io.BytesIO, schema, value, named: dict) -> None:
    if isinstance(schema, str):
        t = schema
        if t in named:
            _encode(out, named[t], value, named)
        elif t == "null":
            pass
        elif t == "boolean":
            out.write(b"\x01" if value else b"\x00")
        elif t in ("int", "long"):
            _write_long(out, int(value))
        elif t == "float":
            out.write(struct.pack("<f", float(value)))
        elif t == "double":
            out.write(struct.pack("<d", float(value)))
        elif t == "string":
            raw = str(value).encode("utf-8")
            _write_long(out, len(raw))
            out.write(raw)
        elif t == "bytes":
            _write_long(out, len(value))
            out.write(value)
        else:
            raise ValueError(f"unknown schema type '{t}'")
    elif isinstance(schema, list):  # union: index + value
        idx = _union_branch(schema, value)
        _write_long(out, idx)
        _encode(out, schema[idx], value, named)
    else:
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _encode(out, f["type"], value[f["name"]], named)
        elif t == "array":
            items = list(value)
            if items:
                _write_long(out, len(items))
                for it in items:
                    _encode(out, schema["items"], it, named)
            _write_long(out, 0)
        elif t == "map":
            entries = dict(value)
            if entries:
                _write_long(out, len(entries))
                for k, v in entries.items():
                    _encode(out, "string", k, named)
                    _encode(out, schema["values"], v, named)
            _write_long(out, 0)
        elif t == "enum":
            _write_long(out, schema["symbols"].index(value))
        elif t == "fixed":
            out.write(value)
        else:
            _encode(out, t, value, named)  # e.g. {"type": "string"}


def _union_branch(union: list, value) -> int:
    def kind(s):
        return s if isinstance(s, str) else s.get("type")

    if value is None:
        for i, s in enumerate(union):
            if kind(s) == "null":
                return i
        raise ValueError("union has no null branch for None value")
    for i, s in enumerate(union):
        if kind(s) != "null":
            return i
    raise ValueError("union has only null branches")


def _decode(r: _Reader, schema, named: dict):
    if isinstance(schema, str):
        t = schema
        if t in named:
            return _decode(r, named[t], named)
        if t == "null":
            return None
        if t == "boolean":
            return r.read_fixed(1) == b"\x01"
        if t in ("int", "long"):
            return r.read_long()
        if t == "float":
            return struct.unpack("<f", r.read_fixed(4))[0]
        if t == "double":
            return struct.unpack("<d", r.read_fixed(8))[0]
        if t == "string":
            return r.read_bytes().decode("utf-8")
        if t == "bytes":
            return r.read_bytes()
        raise ValueError(f"unknown schema type '{t}'")
    if isinstance(schema, list):
        return _decode(r, schema[r.read_long()], named)
    t = schema["type"]
    if t == "record":
        return {f["name"]: _decode(r, f["type"], named) for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            n = r.read_long()
            if n == 0:
                return out
            if n < 0:  # block with byte size prefix
                n = -n
                r.read_long()
            for _ in range(n):
                out.append(_decode(r, schema["items"], named))
    if t == "map":
        out = {}
        while True:
            n = r.read_long()
            if n == 0:
                return out
            if n < 0:
                n = -n
                r.read_long()
            for _ in range(n):
                k = r.read_bytes().decode("utf-8")
                out[k] = _decode(r, schema["values"], named)
    if t == "enum":
        return schema["symbols"][r.read_long()]
    if t == "fixed":
        return r.read_fixed(schema["size"])
    return _decode(r, t, named)


def _collect_named(schema, named: dict) -> None:
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            named[schema["name"]] = schema
        if t == "record":
            for f in schema["fields"]:
                _collect_named(f["type"], named)
        elif t == "array":
            _collect_named(schema["items"], named)
        elif t == "map":
            _collect_named(schema["values"], named)
    elif isinstance(schema, list):
        for s in schema:
            _collect_named(s, named)


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------


def write_avro(
    path: str,
    schema: dict,
    records: Iterable[Mapping],
    codec: str = "deflate",
    block_records: int = 4096,
    sync: bytes = b"photon-ml-tpu-s!",
) -> int:
    """Write an Avro object-container file; returns the record count."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec '{codec}'")
    named: dict = {}
    _collect_named(schema, named)
    count_total = 0
    with open(path + ".tmp", "wb") as f:
        f.write(_MAGIC)
        meta = io.BytesIO()
        _encode(
            meta,
            {"type": "map", "values": "bytes"},
            {
                "avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode(),
            },
            {},
        )
        f.write(meta.getvalue())
        f.write(sync)

        block = io.BytesIO()
        n_in_block = 0

        def flush():
            nonlocal n_in_block
            if n_in_block == 0:
                return
            payload = block.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]  # raw deflate
            head = io.BytesIO()
            _write_long(head, n_in_block)
            _write_long(head, len(payload))
            f.write(head.getvalue())
            f.write(payload)
            f.write(sync)
            block.seek(0)
            block.truncate()
            n_in_block = 0

        for rec in records:
            _encode(block, schema, rec, named)
            n_in_block += 1
            count_total += 1
            if n_in_block >= block_records:
                flush()
        flush()
    os.replace(path + ".tmp", path)
    return count_total


def training_example_schema(bag_names: "Sequence[str]" = ("features",)) -> dict:
    """TrainingExampleAvro generalized to several feature bags (the
    multi-shard featureShardContainer analog): one array<FeatureAvro>
    field per bag, in order, between label and metadataMap."""
    if tuple(bag_names) == ("features",):
        return TRAINING_EXAMPLE_AVRO
    fields = [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
    ]
    for i, b in enumerate(bag_names):
        item = FEATURE_AVRO if i == 0 else "FeatureAvro"
        fields.append({"name": b, "type": {"type": "array", "items": item}})
    fields += [
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ]
    return {
        "name": "TrainingExampleAvro", "type": "record", "fields": fields
    }


def write_training_examples_fast(
    path: str,
    labels: np.ndarray,
    bags: "Mapping[str, tuple[np.ndarray, np.ndarray, np.ndarray]]",
    feature_names: "Sequence[str]",
    id_columns: "Mapping[str, tuple[np.ndarray, Sequence[str]]]",
    block_records: int = 65536,
    sync: bytes = b"photon-ml-tpu-s!",
) -> int:
    """Columnar TrainingExampleAvro writer (~100x the per-record python
    path). ``bags`` maps feature-bag field name -> (starts[n+1], name_id,
    vals): row r of bag carries features name_id/vals[starts[r]:
    starts[r+1]] (term always ""); ``id_columns`` maps metadataMap key ->
    (codes, vocab). Python writes the container header (schema from
    :func:`training_example_schema`); native/avro_encode.cpp appends the
    record blocks (codec null). Falls back to the per-record python
    writer when the native toolchain is unavailable."""
    from photon_ml_tpu.data.avro_native import write_training_blocks_native

    schema = training_example_schema(list(bags))
    with open(path + ".tmp", "wb") as f:
        f.write(_MAGIC)
        meta = io.BytesIO()
        _encode(
            meta,
            {"type": "map", "values": "bytes"},
            {
                "avro.schema": json.dumps(schema).encode(),
                "avro.codec": b"null",
            },
            {},
        )
        f.write(meta.getvalue())
        f.write(sync)
    try:
        rc = write_training_blocks_native(
            path + ".tmp", labels, list(bags.values()), feature_names,
            id_columns, block_records, sync,
        )
    except Exception:
        os.remove(path + ".tmp")
        raise
    if rc is None:
        os.remove(path + ".tmp")  # header-only stub; fallback rewrites
        names = list(feature_names)
        id_items = [
            (k, np.asarray(codes), [str(v) for v in vocab])
            for k, (codes, vocab) in id_columns.items()
        ]

        def recs():
            for r in range(len(labels)):
                rec = {
                    "uid": None,
                    "label": float(labels[r]),
                    "metadataMap": {
                        k: vocab[int(codes[r])]
                        for k, codes, vocab in id_items
                    },
                    "weight": None,
                    "offset": None,
                }
                for bname, (starts, nid, vals) in bags.items():
                    lo, hi = int(starts[r]), int(starts[r + 1])
                    rec[bname] = [
                        {
                            "name": names[int(nid[k])],
                            "term": "",
                            "value": float(vals[k]),
                        }
                        for k in range(lo, hi)
                    ]
                yield rec

        return write_avro(
            path, schema, recs(), codec="null",
            block_records=block_records, sync=sync,
        )
    os.replace(path + ".tmp", path)
    return rc


def read_avro(path: str) -> Iterator[dict]:
    """Stream records from an Avro object-container file."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != _MAGIC:
        raise ValueError(f"{path} is not an Avro container file")
    r = _Reader(data)
    r.pos = 4
    meta = _decode(r, {"type": "map", "values": "bytes"}, {})
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec '{codec}'")
    named: dict = {}
    _collect_named(schema, named)
    sync = r.read_fixed(16)
    while r.pos < len(data):
        n = r.read_long()
        size = r.read_long()
        payload = r.read_fixed(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        br = _Reader(payload)
        for _ in range(n):
            yield _decode(br, schema, named)
        if r.read_fixed(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt block)")


# ---------------------------------------------------------------------------
# photon schemas (re-authored from photon-avro-schemas/src/main/avro/*.avsc)
# ---------------------------------------------------------------------------

FEATURE_AVRO = {
    "name": "FeatureAvro",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "name": "TrainingExampleAvro",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

NAME_TERM_VALUE_AVRO = {
    "name": "NameTermValueAvro",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "name": "BayesianLinearModelAvro",
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {
            "name": "means",
            "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO},
        },
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

SCORING_RESULT_AVRO = {
    "name": "ScoringResultAvro",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}


# ---------------------------------------------------------------------------
# training-data reader (AvroDataReader analog)
# ---------------------------------------------------------------------------


def _as_paths(paths: str | Sequence[str]) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(".avro")
            )
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no .avro files under {paths}")
    return out


def build_index_maps_from_avro(
    paths: str | Sequence[str],
    feature_shards: Mapping[str, Sequence[str]],
    add_intercept: bool = True,
) -> dict[str, IndexMap]:
    """ONE scan builds the index maps for EVERY shard (the generate-by-scan
    path of AvroDataReader.scala:208-237 / FeatureIndexingJob). Uses the
    native decoder's interning pass when available (the vocab keys ARE the
    composed feature keys); pure-Python record walk otherwise."""
    from photon_ml_tpu.data.avro_native import read_game_arrays_native

    names = list(feature_shards)
    try:
        fast = read_game_arrays_native(
            _as_paths(paths),
            {s: tuple(feature_shards[s]) for s in names},
            None,
            (),
            vocab_only=True,  # skip the COO/scalar materialization
        )
    except ValueError:
        fast = None  # corrupt-for-native input: let the python walk report
    if fast is not None:
        return {
            s: IndexMap.build(iter(fast[5][si]),
                              add_intercept=add_intercept)
            for si, s in enumerate(names)
        }

    keysets: dict[str, dict] = {s: {} for s in names}
    for path in _as_paths(paths):
        for rec in read_avro(path):
            for s in names:
                ks = keysets[s]
                for bag in feature_shards[s]:
                    for f in rec.get(bag) or ():
                        ks.setdefault(feature_key(f["name"], f["term"]))
    return {
        s: IndexMap.build(iter(keysets[s]), add_intercept=add_intercept)
        for s in names
    }


def build_index_map_from_avro(
    paths: str | Sequence[str],
    feature_bags: Sequence[str] = ("features",),
    add_intercept: bool = True,
) -> IndexMap:
    """Single-shard convenience wrapper over build_index_maps_from_avro."""
    return build_index_maps_from_avro(
        paths, {"shard": tuple(feature_bags)}, add_intercept=add_intercept
    )["shard"]


def _read_game_dataset_native(
    file_list: list[str],
    feature_shards: Mapping[str, Sequence[str]],
    index_maps: Optional[Mapping[str, IndexMap]],
    id_columns: Sequence[str],
    add_intercept: bool,
    is_response_required: bool,
):
    """Native-decoder fast path (photon_ml_tpu.data.avro_native); returns
    ``(GameDataset, index_maps)`` or None when the native path is
    unavailable/unsupported (the pure-Python decoder below then runs —
    identical semantics). One scan builds BOTH the dataset and, when
    ``index_maps`` is None, the feature index maps."""
    from photon_ml_tpu.data.avro_native import read_game_arrays_native

    fast = read_game_arrays_native(
        file_list, feature_shards, index_maps, id_columns
    )
    if fast is None:
        return None
    labels, offsets, weights, coo, idvals, vocabs, label_seen, file_rows = fast
    n = len(labels)
    if n == 0:
        raise ValueError(f"no records in {file_list}")
    missing = label_seen == 0
    if np.any(missing) and is_response_required:
        # report the specific file + per-file record index, matching the
        # pure-Python fallback's diagnostics
        merged_idx = int(np.argmax(missing))
        bases = np.concatenate([[0], np.cumsum(file_rows)])
        fi = int(np.searchsorted(bases, merged_idx, side="right")) - 1
        raise ValueError(
            f"record {merged_idx - int(bases[fi])} of {file_list[fi]} "
            "has no label"
        )

    if index_maps is None:
        # ONE pass built both the COO (interned ids) and the vocabularies;
        # materialize the IndexMaps and remap interned -> final dense ids
        built = {}
        remapped = []
        for si, (shard, _) in enumerate(feature_shards.items()):
            imap = IndexMap.build(
                iter(vocabs[si]), add_intercept=add_intercept
            )
            built[shard] = imap
            vals, rws, cls = coo[si]
            remap = np.asarray(
                [imap.get(k) for k in vocabs[si]], np.int64
            )
            remapped.append(
                (vals, rws, remap[cls] if len(cls) else cls)
            )
        index_maps = built
        coo = remapped

    shards = {}
    for si, shard in enumerate(feature_shards):
        vals, rws, cls = coo[si]
        imap = index_maps[shard]
        if add_intercept:
            icept = imap.get(INTERCEPT_KEY)
            if icept >= 0:
                # decode emits rows in order; interleave the per-row
                # intercept arithmetically so the result STAYS row-sorted
                # (from_coo then skips its argsort over the nnz)
                vals, rws, cls = _interleave_intercept_sorted(
                    vals, rws, cls, n, icept
                )
        shards[shard] = SparseBatch.from_coo(
            values=vals,
            rows=rws,
            cols=cls,
            labels=labels,
            num_features=len(imap),
        )
    # native id columns arrive as (interned codes, first-seen vocab):
    # sort the vocab and remap codes (models score via searchsorted over a
    # SORTED vocab) — no per-row strings are ever materialized
    from photon_ml_tpu.game.dataset import IdColumn

    id_cols = {}
    for ci, c in enumerate(id_columns):
        codes, vocab = idvals[ci]
        order = np.argsort(vocab)
        rank = np.empty(len(order), np.int64)
        rank[order] = np.arange(len(order))
        id_cols[c] = IdColumn(
            codes=rank[codes] if len(codes) else codes, vocab=vocab[order]
        )
    return (
        build_game_dataset(
            response=labels,
            feature_shards=shards,
            id_columns=id_cols,
            offset=offsets,
            weight=weights,
        ),
        index_maps,
    )


def _interleave_intercept_sorted(
    vals: np.ndarray, rws: np.ndarray, cls: np.ndarray, n: int, icept: int
):
    """Insert one intercept nnz after each row's features, preserving row
    order, in O(nnz) — the sorted-merge of a row-sorted COO with the
    per-row intercept diagonal."""
    nnz = len(vals)
    out_v = np.empty(nnz + n)
    out_r = np.empty(nnz + n, np.int64)
    out_c = np.empty(nnz + n, np.int64)
    # each decode nnz shifts right by the number of intercepts already
    # placed (= its row index); the intercept of row r lands right after
    # row r's features
    dest = np.arange(nnz) + rws
    out_v[dest] = vals
    out_r[dest] = rws
    out_c[dest] = cls
    idest = np.searchsorted(rws, np.arange(n), side="right") + np.arange(n)
    out_v[idest] = 1.0
    out_r[idest] = np.arange(n)
    out_c[idest] = icept
    return out_v, out_r, out_c


def read_game_dataset_from_avro(
    paths: str | Sequence[str],
    feature_shards: Optional[Mapping[str, Sequence[str]]] = None,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
    id_columns: Sequence[str] = (),
    add_intercept: bool = True,
    is_response_required: bool = True,
    return_index_maps: bool = False,
) -> GameDataset:
    """Read TrainingExampleAvro-shaped records into a GameDataset.

    ``feature_shards`` maps shard name -> record feature-bag field names to
    MERGE into that shard's column (featureColumnMap semantics,
    AvroDataReader.readMerged); default one shard "features" from the
    ``features`` bag. ``index_maps`` (per shard) translate name+term keys to
    dense ids — built IN THE SAME SCAN when absent (one pass interns keys
    and emits the COO; a separate index-build pass would double-decode the
    input). Unknown features are DROPPED (reference: index-map misses are
    skipped). ``id_columns`` are taken from top-level record fields or the
    metadataMap (GameConverters:38-110). ``return_index_maps``: return
    ``(dataset, index_maps)`` so training drivers can persist the scanned
    feature space without re-scanning.
    """
    feature_shards = dict(feature_shards or {"features": ("features",)})
    file_list = _as_paths(paths)

    fast = _read_game_dataset_native(
        file_list, feature_shards, index_maps, id_columns,
        add_intercept, is_response_required,
    )
    if fast is not None:
        ds, maps = fast
        return (ds, maps) if return_index_maps else ds

    if index_maps is None:
        index_maps = {
            shard: build_index_map_from_avro(
                file_list, bags, add_intercept=add_intercept
            )
            for shard, bags in feature_shards.items()
        }

    labels: list[float] = []
    offsets: list[float] = []
    weights: list[float] = []
    ids: dict[str, list] = {c: [] for c in id_columns}
    coo: dict[str, tuple[list, list, list]] = {
        s: ([], [], []) for s in feature_shards
    }

    row = 0
    for path in file_list:
        for rec in read_avro(path):
            label = rec.get("label")
            if label is None:
                if is_response_required:
                    raise ValueError(f"{path}: record {row} has no label")
                label = 0.0
            labels.append(float(label))
            off = rec.get("offset")
            offsets.append(0.0 if off is None else float(off))
            wgt = rec.get("weight")  # explicit 0.0 weights must survive
            weights.append(1.0 if wgt is None else float(wgt))
            meta = rec.get("metadataMap") or {}
            for c in id_columns:
                v = rec.get(c)
                if v is None:  # absent OR null top-level field -> metadataMap
                    v = meta.get(c)
                if v is None:
                    raise KeyError(
                        f"{path}: record {row} lacks id column '{c}' "
                        "(top-level field or metadataMap entry)"
                    )
                ids[c].append(v)
            for shard, bags in feature_shards.items():
                imap = index_maps[shard]
                vals, rws, cls = coo[shard]
                for bag in bags:
                    for f in rec.get(bag) or ():
                        idx = imap.get(feature_key(f["name"], f["term"]))
                        if idx >= 0:
                            vals.append(float(f["value"]))
                            rws.append(row)
                            cls.append(idx)
                if add_intercept:
                    icept = imap.get(INTERCEPT_KEY)
                    if icept >= 0:
                        vals.append(1.0)
                        rws.append(row)
                        cls.append(icept)
            row += 1

    if row == 0:
        raise ValueError(f"no records in {file_list}")

    shards = {}
    for shard in feature_shards:
        vals, rws, cls = coo[shard]
        shards[shard] = SparseBatch.from_coo(
            values=np.asarray(vals),
            rows=np.asarray(rws, np.int64),
            cols=np.asarray(cls, np.int64),
            labels=np.asarray(labels),
            num_features=len(index_maps[shard]),
        )
    ds = build_game_dataset(
        response=np.asarray(labels),
        feature_shards=shards,
        id_columns={c: np.asarray(v) for c, v in ids.items()},
        offset=np.asarray(offsets),
        weight=np.asarray(weights),
    )
    return (ds, index_maps) if return_index_maps else ds


def write_training_examples(
    path: str,
    data: GameDataset,
    shard_name: str,
    index_map: IndexMap,
    id_columns: Sequence[str] = (),
    codec: str = "deflate",
) -> int:
    """Export a GameDataset shard as TrainingExampleAvro records (the
    inverse of the reader; used for fixtures and interop)."""
    batch = data.shard(shard_name)
    n = data.num_rows
    vals = np.asarray(batch.values)
    rows = np.asarray(batch.rows)
    cols = np.asarray(batch.cols)
    live = (vals != 0) & (rows < n)
    order = np.argsort(rows[live], kind="stable")
    v, rw, cl = vals[live][order], rows[live][order], cols[live][order]
    starts = np.searchsorted(rw, np.arange(n))
    ends = np.searchsorted(rw, np.arange(n), side="right")

    def records():
        for i in range(n):
            feats = []
            for j in range(int(starts[i]), int(ends[i])):
                key = index_map.name_of(int(cl[j]))
                if key == INTERCEPT_KEY:
                    continue  # intercept is re-injected at read time
                name, _, term = key.partition("\x01")
                feats.append({"name": name, "term": term, "value": float(v[j])})
            meta = {
                c: str(data.id_columns[c].vocab[data.id_columns[c].codes[i]])
                for c in id_columns
            }
            yield {
                "uid": str(i),
                "label": float(data.response[i]),
                "features": feats,
                "metadataMap": meta or None,
                "weight": float(data.weight[i]),
                "offset": float(data.offset[i]),
            }

    return write_avro(path, TRAINING_EXAMPLE_AVRO, records(), codec=codec)


# ---------------------------------------------------------------------------
# model + score egress (ModelProcessingUtils / ScoreProcessingUtils analogs)
# ---------------------------------------------------------------------------


def write_bayesian_linear_model(
    path: str,
    coefficients: np.ndarray,
    index_map: IndexMap,
    model_id: str = "",
    variances: Optional[np.ndarray] = None,
    model_class: Optional[str] = None,
    loss_function: Optional[str] = None,
) -> None:
    """Export dense coefficients as one BayesianLinearModelAvro record
    (ModelProcessingUtils.saveGameModelsToHDFS coefficient layout). Zero
    coefficients are skipped, matching the sparse Avro representation."""
    means = np.asarray(coefficients)

    def ntv(arr):
        out = []
        for i in np.nonzero(arr)[0]:
            key = index_map.name_of(int(i))
            name, _, term = key.partition("\x01")
            out.append({"name": name, "term": term, "value": float(arr[i])})
        return out

    rec = {
        "modelId": model_id,
        "modelClass": model_class,
        "means": ntv(means),
        "variances": ntv(np.asarray(variances)) if variances is not None else None,
        "lossFunction": loss_function,
    }
    write_avro(path, BAYESIAN_LINEAR_MODEL_AVRO, [rec])


def read_bayesian_linear_model(
    path: str, index_map: IndexMap
) -> tuple[np.ndarray, Optional[np.ndarray], dict]:
    """Load (means, variances, metadata) from a BayesianLinearModelAvro file;
    features missing from the index map are dropped."""
    recs = list(read_avro(path))
    if len(recs) != 1:
        raise ValueError(f"{path}: expected 1 model record, got {len(recs)}")
    rec = recs[0]

    def dense(items):
        out = np.zeros(len(index_map))
        for f in items:
            idx = index_map.get(feature_key(f["name"], f["term"]))
            if idx >= 0:
                out[idx] = f["value"]
        return out

    means = dense(rec["means"])
    variances = dense(rec["variances"]) if rec.get("variances") else None
    meta = {
        "modelId": rec["modelId"],
        "modelClass": rec.get("modelClass"),
        "lossFunction": rec.get("lossFunction"),
    }
    return means, variances, meta


FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "name": "FeatureSummarizationResultAvro",
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}


def write_feature_summary(
    path: str,
    summary,
    index_map: IndexMap,
    codec: str = "deflate",
) -> int:
    """Persist per-feature statistics as FeatureSummarizationResultAvro
    records (ModelProcessingUtils.writeBasicStatistics:559-608 analog:
    max/min/mean/normL1/normL2/numNonzeros/variance per name+term)."""
    metrics_arrays = {
        "max": np.asarray(summary.max),
        "min": np.asarray(summary.min),
        "mean": np.asarray(summary.mean),
        "normL1": np.asarray(summary.norm_l1),
        "normL2": np.asarray(summary.norm_l2),
        "numNonzeros": np.asarray(summary.num_nonzeros),
        "variance": np.asarray(summary.variance),
    }

    def records():
        for i in range(len(index_map)):
            key = index_map.name_of(i)
            name, _, term = key.partition("\x01")
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {k: float(v[i]) for k, v in metrics_arrays.items()},
            }

    return write_avro(path, FEATURE_SUMMARIZATION_RESULT_AVRO, records(), codec=codec)


def read_feature_summary(path: str) -> dict[str, dict[str, float]]:
    """Load a feature-summary file as {feature key: {metric: value}}."""
    out = {}
    for rec in read_avro(path):
        out[feature_key(rec["featureName"], rec["featureTerm"])] = rec["metrics"]
    return out


def write_scoring_results(
    path: str,
    scores: np.ndarray,
    model_id: str = "",
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    uids: Optional[Sequence[str]] = None,
    codec: str = "deflate",
) -> int:
    """Persist scores as ScoringResultAvro (ScoreProcessingUtils analog)."""
    scores = np.asarray(scores)

    def records():
        for i in range(len(scores)):
            yield {
                "uid": str(uids[i]) if uids is not None else str(i),
                "label": float(labels[i]) if labels is not None else None,
                "modelId": model_id,
                "predictionScore": float(scores[i]),
                "weight": float(weights[i]) if weights is not None else None,
                "metadataMap": None,
            }

    return write_avro(path, SCORING_RESULT_AVRO, records(), codec=codec)


def read_scoring_results(path: str) -> list[dict]:
    return list(read_avro(path))
