"""Date-range input path expansion.

Reference analog: photon-client util/{DateRange,DateRangeDaysAgo}.scala and
IOUtils.getInputPathsWithinDateRange — training inputs organized as daily
directories ``root/yyyy/MM/dd`` selected by a "yyyymmdd-yyyymmdd" range or
a "start-end" days-ago pair. Missing days are skipped unless
``error_on_missing``.
"""

from __future__ import annotations

import datetime
import os
from typing import Optional, Sequence


def parse_date_range(spec: str) -> tuple[datetime.date, datetime.date]:
    """Parse "yyyymmdd-yyyymmdd" (DateRange.fromDateString analog)."""
    try:
        start_s, end_s = spec.split("-")
        start = datetime.datetime.strptime(start_s, "%Y%m%d").date()
        end = datetime.datetime.strptime(end_s, "%Y%m%d").date()
    except ValueError as e:
        raise ValueError(f"bad date range '{spec}' (want yyyymmdd-yyyymmdd)") from e
    if start > end:
        raise ValueError(f"invalid range: start {start} after end {end}")
    return start, end


def parse_days_ago(
    spec: str, today: Optional[datetime.date] = None
) -> tuple[datetime.date, datetime.date]:
    """Parse "start-end" days-ago (DateRangeDaysAgo analog): "90-1" =
    from 90 days ago through yesterday."""
    today = today or datetime.date.today()
    try:
        start_ago_s, end_ago_s = spec.split("-")
        start_ago, end_ago = int(start_ago_s), int(end_ago_s)
    except ValueError as e:
        raise ValueError(f"bad days-ago range '{spec}' (want e.g. 90-1)") from e
    start = today - datetime.timedelta(days=start_ago)
    end = today - datetime.timedelta(days=end_ago)
    if start > end:
        raise ValueError(f"invalid range: {spec} starts after it ends")
    return start, end


def daily_paths(
    root: str,
    start: datetime.date,
    end: datetime.date,
    error_on_missing: bool = False,
) -> list[str]:
    """``root/yyyy/MM/dd`` directories within [start, end], existing only
    (IOUtils.getInputPathsWithinDateRange analog)."""
    out = []
    day = start
    while day <= end:
        p = os.path.join(root, f"{day.year:04d}", f"{day.month:02d}",
                         f"{day.day:02d}")
        if os.path.isdir(p):
            out.append(p)
        elif error_on_missing:
            raise FileNotFoundError(f"missing daily input dir {p}")
        day += datetime.timedelta(days=1)
    return out


def expand_input_paths(
    paths: Sequence[str],
    date_range: Optional[str] = None,
    date_range_days_ago: Optional[str] = None,
    error_on_missing: bool = False,
    today: Optional[datetime.date] = None,
) -> list[str]:
    """Expand input roots by an optional date range; without one, paths
    pass through unchanged."""
    if date_range and date_range_days_ago:
        raise ValueError("give date_range OR date_range_days_ago, not both")
    if not date_range and not date_range_days_ago:
        return list(paths)
    if date_range:
        start, end = parse_date_range(date_range)
    else:
        start, end = parse_days_ago(date_range_days_ago, today=today)
    out: list[str] = []
    for root in paths:
        out.extend(daily_paths(root, start, end, error_on_missing))
    if not out:
        raise FileNotFoundError(
            f"no daily input dirs under {list(paths)} in [{start}, {end}]"
        )
    return out
