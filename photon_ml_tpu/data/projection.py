"""Gaussian random projection matrices for random-effect feature-space
reduction and factored-random-effect latent spaces.

Reference analog: photon-api projector/ProjectionMatrix.scala:95-124 —
entries drawn N(0, 1) scaled by 1/projected_dim (the reference deliberately
uses std = k rather than sqrt(k) to keep entries small), clipped to
[-1, 1], with an optional intercept passthrough row (all zeros except a 1
in the intercept column). On TPU the projection is just a dense [k, d]
matmul / per-nnz column gather — no broadcast object needed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ProjectionMatrix:
    """A dense projection x -> A @ x  (A: [projected_dim, original_dim]).

    ``project_coefficients`` maps a model trained in projected space back
    to original space (ProjectionMatrix.scala projectCoefficients:
    w_original = A^T w_projected).
    """

    matrix: Array  # f[k, d]

    @property
    def projected_dim(self) -> int:
        return self.matrix.shape[0]

    @property
    def original_dim(self) -> int:
        return self.matrix.shape[1]

    def project_features(self, x: Array) -> Array:
        return self.matrix @ x

    def project_coefficients(self, w_projected: Array) -> Array:
        return self.matrix.T @ w_projected

    def extended(self) -> Array:
        """Matrix with one extra all-zero column at index ``original_dim``
        so sentinel feature ids (= d, the padding convention of
        EntityBucket.projection) gather zeros."""
        return jnp.pad(self.matrix, ((0, 0), (0, 1)))


def build_gaussian_projection_matrix(
    projected_dim: int,
    original_dim: int,
    intercept_index: Optional[int] = None,
    seed: int = 0,
    dtype=jnp.float32,
) -> ProjectionMatrix:
    """Random Gaussian projection (ProjectionMatrix.scala:95-124): entries
    N(0, 1)/projected_dim clipped to [-1, 1]. With ``intercept_index``, an
    extra passthrough row keeps the intercept feature intact (the
    reference's isKeepingInterceptTerm dummy row)."""
    if projected_dim < 1 or original_dim < 1:
        raise ValueError("projection dims must be positive")
    rng = np.random.default_rng(seed)
    m = rng.standard_normal((projected_dim, original_dim)) / projected_dim
    m = np.clip(m, -1.0, 1.0)
    if intercept_index is not None:
        passthrough = np.zeros((1, original_dim))
        passthrough[0, intercept_index] = 1.0
        m = np.concatenate([m, passthrough], axis=0)
    return ProjectionMatrix(matrix=jnp.asarray(m, dtype))
