"""Feature index maps: feature name+term <-> dense integer id.

Reference analog: photon-api util/{IndexMap,DefaultIndexMap,PalDBIndexMap}
(SURVEY.md §2.c "Index maps"). The PalDB off-heap store is replaced by a
host-side persisted format designed for zero-parse mmap loading: a sorted
uint64-hash table (binary-searchable via numpy memmap) plus a names blob for
reverse lookup. Index maps live only on the host — devices see dense int32
feature ids, never strings.

Feature keys follow the reference convention name + '\\x01' + term
(photon-client util/Utils.getFeatureKey).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Iterable, Iterator, Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.utils.atomic import atomic_write_json, atomic_write_npy

DELIMITER = "\x01"
INTERCEPT_KEY = "(INTERCEPT)"  # reference: GLMSuite/Constants INTERCEPT_NAME_TERM


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{DELIMITER}{term}" if term else name


def _hash64(key: str) -> int:
    # stable across processes (unlike Python's salted hash)
    return int.from_bytes(hashlib.blake2b(key.encode(), digest_size=8).digest(), "little")


class IndexMap(Mapping[str, int]):
    """In-memory feature index map (DefaultIndexMap analog) with optional
    binary persistence for fast reload (PalDBIndexMap analog)."""

    def __init__(self, names: Sequence[str]):
        self._names = list(names)
        self._index = {n: i for i, n in enumerate(self._names)}
        if len(self._index) != len(self._names):
            raise ValueError("duplicate feature keys in index map")

    # Mapping interface -----------------------------------------------------
    def __getitem__(self, key: str) -> int:
        return self._index[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def get(self, key: str, default: int = -1) -> int:  # type: ignore[override]
        return self._index.get(key, default)

    def name_of(self, idx: int) -> str:
        return self._names[idx]

    @property
    def names(self) -> list[str]:
        return self._names

    # construction ----------------------------------------------------------
    @staticmethod
    def build(
        keys: Iterable[str],
        add_intercept: bool = False,
        sort: bool = True,
    ) -> "IndexMap":
        """Build from an iterable of (possibly repeated) feature keys.

        Sorting gives a deterministic id assignment independent of input
        order (the reference's FeatureIndexingJob achieves determinism by
        hash-partitioned offsets; sorted order is the simpler equivalent).
        """
        uniq = set(keys)
        if add_intercept:
            uniq.add(INTERCEPT_KEY)
        names = sorted(uniq) if sort else list(uniq)
        return IndexMap(names)

    # persistence -----------------------------------------------------------
    def save(self, directory: str) -> None:
        """Write the mmap-friendly layout: sorted (hash, id) arrays + names.

        Raises on a 64-bit hash collision between two distinct feature keys:
        MmapIndexMap resolves lookups by hash alone, so a collision in the
        persisted table would silently return the wrong feature id.
        """
        os.makedirs(directory, exist_ok=True)
        hashes = np.asarray([_hash64(n) for n in self._names], dtype=np.uint64)
        if len(hashes) != len(np.unique(hashes)):
            sorted_h = np.sort(hashes)
            dup = sorted_h[:-1][sorted_h[:-1] == sorted_h[1:]][0]
            clashing = [n for n in self._names if np.uint64(_hash64(n)) == dup]
            raise ValueError(
                f"64-bit hash collision between feature keys {clashing!r}; "
                "the mmap store cannot represent this vocabulary"
            )
        order = np.argsort(hashes)
        # atomic + fsynced writes (utils.atomic): the index map is shipped
        # next to the model; a crash mid-save must not leave a truncated
        # table that scoring would silently mmap (tools/check.py L008)
        atomic_write_npy(
            os.path.join(directory, "hashes.npy"), hashes[order]
        )
        atomic_write_npy(
            os.path.join(directory, "ids.npy"),
            np.asarray(order, dtype=np.int64),
        )
        atomic_write_json(os.path.join(directory, "names.json"), self._names)
        atomic_write_json(
            os.path.join(directory, "meta.json"),
            {"num_features": len(self._names), "format": 1},
        )

    @staticmethod
    def load(directory: str) -> "IndexMap":
        with open(os.path.join(directory, "names.json")) as f:
            return IndexMap(json.load(f))


class MmapIndexMap:
    """Read-only index map backed by memory-mapped arrays — loads in O(1)
    regardless of vocabulary size, lookups by binary search over the sorted
    hash table. The PalDBIndexMap replacement for huge vocabularies where
    materializing a Python dict is too slow/large."""

    def __init__(self, directory: str):
        self._hashes = np.load(os.path.join(directory, "hashes.npy"), mmap_mode="r")
        self._ids = np.load(os.path.join(directory, "ids.npy"), mmap_mode="r")
        with open(os.path.join(directory, "meta.json")) as f:
            self._size = json.load(f)["num_features"]
        self._dir = directory
        self._names: Optional[list[str]] = None  # lazy, reverse lookups only

    def __len__(self) -> int:
        return self._size

    def get(self, key: str, default: int = -1) -> int:
        h = np.uint64(_hash64(key))
        pos = int(np.searchsorted(self._hashes, h))
        if pos < len(self._hashes) and self._hashes[pos] == h:
            return int(self._ids[pos])
        return default

    def get_many(self, keys: Sequence[str]) -> np.ndarray:
        """Vectorized lookup; -1 for unknown keys."""
        hs = np.asarray([_hash64(k) for k in keys], dtype=np.uint64)
        pos = np.searchsorted(self._hashes, hs)
        pos_c = np.minimum(pos, len(self._hashes) - 1)
        hit = self._hashes[pos_c] == hs
        out = np.where(hit, self._ids[pos_c], -1)
        return out.astype(np.int64)

    def name_of(self, idx: int) -> str:
        if self._names is None:
            with open(os.path.join(self._dir, "names.json")) as f:
                self._names = json.load(f)
        return self._names[idx]
