"""Native (C++) host-ingestion bindings via ctypes.

The reference's ingestion hot loops run on Spark executors (JVM); here
they are host-side, so the text-parsing inner loop lives in
native/fast_parse.cpp behind a C ABI (the environment has no pybind11 —
ctypes is the binding layer). The library is compiled on demand with g++
and cached; every caller must keep a pure-Python fallback, so the native
path is a transparent accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

logger = logging.getLogger("photon_ml_tpu.native")

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libphoton_native.so")
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


_SOURCES = ("fast_parse.cpp", "avro_decode.cpp", "avro_encode.cpp")


def _source_paths() -> list[str]:
    return [
        p
        for p in (os.path.join(_NATIVE_DIR, s) for s in _SOURCES)
        if os.path.exists(p)
    ]


def _build() -> bool:
    srcs = _source_paths()
    if not srcs:
        return False
    attempts = [srcs]
    if len(srcs) > 1:
        # avro_decode.cpp needs zlib; if that link fails (no libz on the
        # host), still build fast_parse alone so the libsvm accelerator
        # survives
        attempts.append(srcs[:1])
    for attempt in attempts:
        cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
               "-o", _LIB_PATH, *attempt]
        if any("avro_decode" in s for s in attempt):
            cmd.append("-lz")
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=240)
            return True
        except (OSError, subprocess.SubprocessError) as e:
            logger.info("native build failed for %s (%s)", attempt, e)
    logger.info("native build unavailable; using pure python")
    return False


def load_native() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable.

    ``PHOTON_NO_NATIVE=1`` hides the library even when it exists — the
    supported way to force (and test) the pure-Python fallback paths;
    checked before the load cache so toggling the env var mid-process
    (e.g. a monkeypatch) takes effect immediately.
    """
    global _lib, _load_attempted
    if os.environ.get("PHOTON_NO_NATIVE"):
        return None
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    srcs = _source_paths()
    stale = os.path.exists(_LIB_PATH) and any(
        os.path.getmtime(s) > os.path.getmtime(_LIB_PATH) for s in srcs
    )
    if (not os.path.exists(_LIB_PATH) or stale) and not _build():
        if not os.path.exists(_LIB_PATH):
            return None  # nothing to load; stale-but-present still loads
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        logger.info("native library load failed (%s)", e)
        return None
    lib.libsvm_count.restype = ctypes.c_int
    lib.libsvm_count.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.libsvm_parse.restype = ctypes.c_int64
    lib.libsvm_parse.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _lib = lib
    return _lib


def parse_libsvm_native(
    data: bytes, zero_based: bool = False
) -> Optional[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]]:
    """(values, rows, cols, labels, num_features) or None if the native
    library is unavailable. Raises ValueError on malformed input, matching
    the python parser's errors."""
    lib = load_native()
    if lib is None:
        return None
    n_rows = ctypes.c_int64()
    n_nnz = ctypes.c_int64()
    lib.libsvm_count(data, len(data), ctypes.byref(n_rows), ctypes.byref(n_nnz))
    values = np.empty(n_nnz.value, np.float64)
    rows = np.empty(n_nnz.value, np.int64)
    cols = np.empty(n_nnz.value, np.int64)
    labels = np.empty(n_rows.value, np.float64)
    parsed_rows = ctypes.c_int64()
    parsed_slots = ctypes.c_int64()
    max_col = lib.libsvm_parse(
        data, len(data), 0 if zero_based else 1, values, rows, cols, labels,
        ctypes.byref(parsed_rows), ctypes.byref(parsed_slots),
    )
    if max_col == -3:
        raise ValueError(
            "negative feature index (wrong zero_based setting?)"
        )
    if max_col == -2:
        raise ValueError("malformed libsvm token")
    # max_col == -1 is a VALID labels-only file: num_features = 0
    # the two passes must tokenize identically, or the arrays contain
    # uninitialized tails — refuse rather than return garbage
    if parsed_rows.value != n_rows.value or parsed_slots.value != n_nnz.value:
        raise ValueError(
            "malformed libsvm input: count/parse passes disagree "
            f"(rows {parsed_rows.value} vs {n_rows.value}, "
            f"nnz {parsed_slots.value} vs {n_nnz.value})"
        )
    return values, rows, cols, labels, int(max_col) + 1
