"""Model persistence: save/load for GLM and GAME models + scoring entry.

The TPU-native answer to the reference's HDFS Avro model store
(photon-client data/avro/ModelProcessingUtils.scala: saveGameModelsToHDFS:72,
loadGameModelFromHDFS:137, saveGameModelMetadataToHDFS:516) and the GAME
scoring driver (cli/game/scoring/Driver.scala:51-201). Layout on disk:

    model_dir/
      model-metadata.json               task, coordinate specs, extras
      fixed-effect/<name>/coefficients.npz
      random-effect/<name>/model.npz    per-bucket coefficient tables,
                                        projections, entity vocab/placement

Coefficient tables are stored as float32 npz arrays (no Avro dependency;
the wire format is the npz container). ``load_game_model`` reconstructs
device arrays lazily via jnp.asarray; scoring data with entities unseen at
training time scores 0 for those entities (RandomEffectModel semantics).
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.projection import ProjectionMatrix
from photon_ml_tpu.utils.atomic import atomic_write_json, atomic_write_npz
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.factored import (
    FactoredRandomEffectModel,
    MatrixFactorizationModel,
)
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectBucketModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel

_METADATA_FILE = "model-metadata.json"
_FORMAT_VERSION = 1


class ModelLoadError(ValueError):
    """A model directory failed to load: the message names the offending
    path and what was wrong (missing file, truncated npz, missing array
    key, unsupported format_version). Subclasses ValueError so callers
    matching the old untyped errors keep working."""

    def __init__(self, path: str, message: str):
        super().__init__(f"{path}: {message}")
        self.path = path


def _write_json(path: str, obj) -> None:
    # fsync-before-rename (utils.atomic): a crash right after save_* returns
    # must never leave empty metadata next to a valid model
    atomic_write_json(path, obj, indent=2, sort_keys=True)


def _write_npz(path: str, **arrays) -> None:
    """Atomic npz write (tmp + fsync + rename) so a crash mid-save into an
    existing model directory can never leave a truncated array file next to
    valid metadata — every file in a model dir is replaced whole or not at
    all."""
    atomic_write_npz(path, **arrays)


def _read_metadata(model_dir: str, expected_type: str) -> dict:
    """Load + validate model-metadata.json with typed errors naming the
    offending path (a truncated save must not surface as a bare KeyError)."""
    path = os.path.join(model_dir, _METADATA_FILE)
    try:
        with open(path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise ModelLoadError(path, "missing metadata file") from None
    except json.JSONDecodeError as e:
        raise ModelLoadError(path, f"corrupt metadata JSON ({e})") from None
    version = meta.get("format_version")
    if version != _FORMAT_VERSION:
        raise ModelLoadError(
            path,
            f"unsupported format_version {version!r} "
            f"(this build reads version {_FORMAT_VERSION})",
        )
    if meta.get("model_type") != expected_type:
        raise ModelLoadError(
            path, f"does not contain a {expected_type.upper()} model"
        )
    return meta


class _NpzReader:
    """npz access where a missing key raises ModelLoadError with the path
    (np.load's bare KeyError names neither file nor context)."""

    def __init__(self, z, path: str):
        self._z = z
        self._path = path

    def __contains__(self, key: str) -> bool:
        return key in self._z

    def __getitem__(self, key: str):
        try:
            return self._z[key]
        except KeyError:
            raise ModelLoadError(
                self._path, f"missing array key '{key}'"
            ) from None
        except (zipfile.BadZipFile, OSError, ValueError) as e:
            raise ModelLoadError(
                self._path, f"corrupt array '{key}' ({e})"
            ) from None


class _open_npz:
    """Context manager: np.load with load failures mapped to ModelLoadError
    (FileNotFoundError / BadZipFile / truncated-container ValueError)."""

    def __init__(self, path: str):
        self._path = path

    def __enter__(self) -> _NpzReader:
        try:
            self._z = np.load(self._path, allow_pickle=False)
        except FileNotFoundError:
            raise ModelLoadError(self._path, "missing array file") from None
        except (zipfile.BadZipFile, OSError, ValueError) as e:
            raise ModelLoadError(self._path, f"corrupt npz ({e})") from None
        return _NpzReader(self._z, self._path)

    def __exit__(self, *exc):
        self._z.close()
        return False


# ---------------------------------------------------------------------------
# single GLM (legacy-driver model format)
# ---------------------------------------------------------------------------


def save_glm(model: GeneralizedLinearModel, path: str) -> None:
    """Save one GLM: coefficients (+variances) npz next to metadata JSON."""
    os.makedirs(path, exist_ok=True)
    arrays = {"means": np.asarray(model.coefficients.means, np.float32)}
    if model.coefficients.variances is not None:
        arrays["variances"] = np.asarray(model.coefficients.variances, np.float32)
    _write_npz(os.path.join(path, "coefficients.npz"), **arrays)
    _write_json(
        os.path.join(path, _METADATA_FILE),
        {"format_version": _FORMAT_VERSION, "model_type": "glm",
         "task": model.task},
    )


def load_glm(path: str) -> GeneralizedLinearModel:
    meta = _read_metadata(path, "glm")
    with _open_npz(os.path.join(path, "coefficients.npz")) as z:
        means = jnp.asarray(z["means"])
        variances = jnp.asarray(z["variances"]) if "variances" in z else None
    return GeneralizedLinearModel(
        coefficients=Coefficients(means=means, variances=variances),
        task=meta["task"],
    )


# ---------------------------------------------------------------------------
# GAME models
# ---------------------------------------------------------------------------


def _save_fixed_effect(model: FixedEffectModel, path: str) -> dict:
    os.makedirs(path, exist_ok=True)
    _write_npz(
        os.path.join(path, "coefficients.npz"),
        coefficients=np.asarray(model.coefficients, np.float32),
    )
    return {
        "type": "fixed_effect",
        "shard_name": model.shard_name,
        "num_features": int(np.asarray(model.coefficients).shape[0]),
    }


def _load_fixed_effect(path: str, spec: dict) -> FixedEffectModel:
    with _open_npz(os.path.join(path, "coefficients.npz")) as z:
        coefficients = jnp.asarray(z["coefficients"])
    return FixedEffectModel(
        coefficients=coefficients, shard_name=spec["shard_name"]
    )


def _save_random_effect(model: RandomEffectModel, path: str) -> dict:
    os.makedirs(path, exist_ok=True)
    arrays = {
        "entity_bucket": np.asarray(model.entity_bucket, np.int32),
        "entity_pos": np.asarray(model.entity_pos, np.int32),
        "vocab": np.asarray(model.vocab),
    }
    for i, bm in enumerate(model.buckets):
        arrays[f"coefficients_{i}"] = np.asarray(bm.coefficients, np.float32)
        arrays[f"projection_{i}"] = np.asarray(bm.projection, np.int32)
        arrays[f"entity_codes_{i}"] = np.asarray(bm.entity_codes, np.int32)
        if bm.variances is not None:
            arrays[f"variances_{i}"] = np.asarray(bm.variances, np.float32)
    _write_npz(os.path.join(path, "model.npz"), **arrays)
    return {
        "type": "random_effect",
        "shard_name": model.shard_name,
        "id_name": model.id_name,
        "num_buckets": len(model.buckets),
        "num_entities": int(len(model.vocab)),
    }


def _load_random_effect(path: str, spec: dict) -> RandomEffectModel:
    with _open_npz(os.path.join(path, "model.npz")) as z:
        buckets = tuple(
            RandomEffectBucketModel(
                coefficients=jnp.asarray(z[f"coefficients_{i}"]),
                projection=jnp.asarray(z[f"projection_{i}"]),
                entity_codes=jnp.asarray(z[f"entity_codes_{i}"]),
                variances=(
                    jnp.asarray(z[f"variances_{i}"])
                    if f"variances_{i}" in z
                    else None
                ),
            )
            for i in range(spec["num_buckets"])
        )
        return RandomEffectModel(
            id_name=spec["id_name"],
            shard_name=spec["shard_name"],
            buckets=buckets,
            entity_bucket=z["entity_bucket"],
            entity_pos=z["entity_pos"],
            vocab=z["vocab"],
        )


def _save_factored_random_effect(model: FactoredRandomEffectModel, path: str) -> dict:
    os.makedirs(path, exist_ok=True)
    _write_npz(
        os.path.join(path, "model.npz"),
        projection=np.asarray(model.projection.matrix, np.float32),
        latent=np.asarray(model.latent, np.float32),
        entity_flat=np.asarray(model.entity_flat, np.int64),
        vocab=np.asarray(model.vocab),
    )
    return {
        "type": "factored_random_effect",
        "shard_name": model.shard_name,
        "id_name": model.id_name,
        "latent_dim": int(model.latent_dim),
        "num_entities": int(len(model.vocab)),
    }


def _load_factored_random_effect(path: str, spec: dict) -> FactoredRandomEffectModel:
    with _open_npz(os.path.join(path, "model.npz")) as z:
        return FactoredRandomEffectModel(
            id_name=spec["id_name"],
            shard_name=spec["shard_name"],
            projection=ProjectionMatrix(matrix=jnp.asarray(z["projection"])),
            latent=jnp.asarray(z["latent"]),
            entity_flat=z["entity_flat"],
            vocab=z["vocab"],
        )


def _save_matrix_factorization(model: MatrixFactorizationModel, path: str) -> dict:
    """LatentFactorAvro analog (ModelProcessingUtils.scala:449-515)."""
    os.makedirs(path, exist_ok=True)
    _write_npz(
        os.path.join(path, "model.npz"),
        row_factors=np.asarray(model.row_factors, np.float32),
        col_factors=np.asarray(model.col_factors, np.float32),
        row_vocab=np.asarray(model.row_vocab),
        col_vocab=np.asarray(model.col_vocab),
    )
    return {
        "type": "matrix_factorization",
        "row_effect": model.row_effect,
        "col_effect": model.col_effect,
        "num_latent_factors": int(model.num_latent_factors),
    }


def _load_matrix_factorization(path: str, spec: dict) -> MatrixFactorizationModel:
    with _open_npz(os.path.join(path, "model.npz")) as z:
        return MatrixFactorizationModel(
            row_effect=spec["row_effect"],
            col_effect=spec["col_effect"],
            row_factors=jnp.asarray(z["row_factors"]),
            col_factors=jnp.asarray(z["col_factors"]),
            row_vocab=z["row_vocab"],
            col_vocab=z["col_vocab"],
        )


def save_game_model(
    model: GameModel, path: str, extra_metadata: Optional[dict] = None
) -> None:
    """Persist a GAME model: one subdirectory per coordinate + metadata.

    ``extra_metadata`` (e.g. the optimization configs that produced the
    model — the reference stores these in model-metadata.json:516) is
    round-tripped verbatim under the "extra" key.
    """
    os.makedirs(path, exist_ok=True)
    coords = {}
    for name, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            coords[name] = _save_fixed_effect(
                sub, os.path.join(path, "fixed-effect", name)
            )
        elif isinstance(sub, RandomEffectModel):
            coords[name] = _save_random_effect(
                sub, os.path.join(path, "random-effect", name)
            )
        elif isinstance(sub, FactoredRandomEffectModel):
            coords[name] = _save_factored_random_effect(
                sub, os.path.join(path, "factored-random-effect", name)
            )
        elif isinstance(sub, MatrixFactorizationModel):
            coords[name] = _save_matrix_factorization(
                sub, os.path.join(path, "matrix-factorization", name)
            )
        else:
            raise TypeError(
                f"coordinate '{name}': cannot persist {type(sub).__name__}"
            )
    _write_json(
        os.path.join(path, _METADATA_FILE),
        {
            "format_version": _FORMAT_VERSION,
            "model_type": "game",
            "task": model.task,
            "coordinates": coords,
            "coordinate_order": list(model.models.keys()),
            "extra": extra_metadata or {},
        },
    )


def load_game_model(path: str) -> GameModel:
    meta = _read_metadata(path, "game")
    models = {}
    meta_path = os.path.join(path, _METADATA_FILE)
    if "coordinate_order" not in meta:
        # a silently-empty model would score all-offsets; fail loudly
        raise ModelLoadError(meta_path, "missing coordinate_order")
    for name in meta["coordinate_order"]:
        spec = meta.get("coordinates", {}).get(name)
        if spec is None:
            raise ModelLoadError(
                meta_path, f"coordinate '{name}' listed but not described"
            )
        if spec["type"] == "fixed_effect":
            models[name] = _load_fixed_effect(
                os.path.join(path, "fixed-effect", name), spec
            )
        elif spec["type"] == "random_effect":
            models[name] = _load_random_effect(
                os.path.join(path, "random-effect", name), spec
            )
        elif spec["type"] == "factored_random_effect":
            models[name] = _load_factored_random_effect(
                os.path.join(path, "factored-random-effect", name), spec
            )
        elif spec["type"] == "matrix_factorization":
            models[name] = _load_matrix_factorization(
                os.path.join(path, "matrix-factorization", name), spec
            )
        else:
            raise ValueError(f"unknown coordinate type '{spec['type']}'")
    return GameModel(task=meta["task"], models=models)


def load_game_model_metadata(path: str) -> dict:
    with open(os.path.join(path, _METADATA_FILE)) as f:
        return json.load(f)


def load_feature_index_maps(model_dir: str) -> Optional[dict]:
    """Per-shard IndexMaps persisted under ``<model_dir>/feature-indexes/``
    (the training feature space pinned next to the coefficients), or None
    when the directory is absent. Shared by the batch scoring driver and
    the serving engine so both resolve names through the SAME maps the
    model was trained with."""
    from photon_ml_tpu.data.index_map import IndexMap

    idx_dir = os.path.join(model_dir, "feature-indexes")
    if not os.path.isdir(idx_dir):
        return None
    return {
        shard: IndexMap.load(os.path.join(idx_dir, shard))
        for shard in sorted(os.listdir(idx_dir))
    }


def score_game_dataset(model_dir: str, data: GameDataset) -> np.ndarray:
    """Load a saved GAME model and score a dataset (scoring driver analog).

    Returns raw scores (sum of sub-model margins) for the real rows of
    ``data``; entities unseen at training time contribute 0. The reference
    flow is cli/game/scoring/Driver.scala:109-132 (load -> GAMEModel.score).
    """
    model = load_game_model(model_dir)
    scores = np.asarray(model.score(data))
    return scores[: data.num_rows]
