"""Per-feature statistical summaries, computed on device from sparse batches.

Analog of the reference's BasicStatisticalSummary (photon-lib
stat/BasicStatisticalSummary.scala:25-55), which wraps Spark MLLIB colStats.
Here the moments come from two scatter-adds over the COO block — one fused
XLA program; under a mesh the partial sums psum over the data axis.

Sparse semantics match colStats: zeros count toward mean/variance (features
are dense-with-zeros conceptually), variance is the unbiased N-1 estimator.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.sparse import SparseBatch

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FeatureSummary:
    mean: Array
    variance: Array  # unbiased (N-1)
    count: Array  # scalar number of examples
    num_nonzeros: Array
    max: Array
    min: Array
    norm_l1: Array
    norm_l2: Array
    mean_abs: Array


def summarize(batch: SparseBatch) -> FeatureSummary:
    """Compute per-feature statistics over the valid (weight > 0) rows."""
    d = batch.num_features
    dtype = batch.dtype
    valid_row = (batch.weights > 0).astype(dtype)
    n = jnp.sum(valid_row)
    valid_nnz = jnp.take(valid_row, batch.rows, fill_value=0)
    v = batch.values * valid_nnz

    zeros = jnp.zeros((d,), dtype=dtype)
    s1 = zeros.at[batch.cols].add(v)
    s2 = zeros.at[batch.cols].add(v * v)
    sabs = zeros.at[batch.cols].add(jnp.abs(v))
    nnz = zeros.at[batch.cols].add((v != 0).astype(dtype))
    # max/min must account for implicit zeros when a feature has any zero entry.
    # Zero-valued entries (including nnz PADDING, whose value is 0 and whose
    # row may alias a real row when n == n_pad) are excluded from the scatter;
    # explicit zeros are indistinguishable from implicit ones and are folded
    # back in via the has_zero correction below (nnz counts v != 0 only).
    big = jnp.asarray(jnp.finfo(dtype).max, dtype)
    present = (valid_nnz > 0) & (batch.values != 0)
    maxv = jnp.full((d,), -big, dtype).at[batch.cols].max(
        jnp.where(present, batch.values, -big)
    )
    minv = jnp.full((d,), big, dtype).at[batch.cols].min(
        jnp.where(present, batch.values, big)
    )
    has_zero = nnz < n
    maxv = jnp.where(has_zero, jnp.maximum(maxv, 0.0), maxv)
    minv = jnp.where(has_zero, jnp.minimum(minv, 0.0), minv)
    # features with no observations at all
    maxv = jnp.where(nnz == 0, 0.0, maxv)
    minv = jnp.where(nnz == 0, 0.0, minv)

    mean = s1 / jnp.maximum(n, 1.0)
    # unbiased variance over all n samples (zeros included):
    # sum (x - mean)^2 = s2 - n*mean^2 ; divide by n-1
    var = (s2 - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
    var = jnp.maximum(var, 0.0)

    return FeatureSummary(
        mean=mean,
        variance=var,
        count=n,
        num_nonzeros=nnz,
        max=maxv,
        min=minv,
        norm_l1=sabs,
        norm_l2=jnp.sqrt(s2),
        mean_abs=sabs / jnp.maximum(n, 1.0),
    )
