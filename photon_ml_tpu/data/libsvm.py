"""LibSVM text reader -> SparseBatch.

Reference analog: photon-client io/deprecated LibSVMInputDataFormat
(SURVEY.md §2.d "Legacy input formats"); also the a1a demo workload path
(reference README.md:236-252). Parsing is host-side numpy; the result is a
device-ready :class:`SparseBatch` with an optional intercept column.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.ops.sparse import SparseBatch


@dataclasses.dataclass
class LibSVMData:
    """Host COO arrays parsed from LibSVM text (pre-device)."""

    values: np.ndarray
    rows: np.ndarray
    cols: np.ndarray
    labels: np.ndarray
    num_features: int

    def to_batch(
        self,
        num_features: Optional[int] = None,
        add_intercept: bool = True,
        dtype=None,
        row_pad_multiple: int = 8,
        nnz_pad_multiple: int = 128,
        offsets: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
    ) -> SparseBatch:
        """Materialize a SparseBatch; intercept becomes the LAST column."""
        import jax.numpy as jnp

        d = int(num_features if num_features is not None else self.num_features)
        values, rows, cols = self.values, self.rows, self.cols
        if add_intercept:
            n = len(self.labels)
            values = np.concatenate([values, np.ones(n)])
            rows = np.concatenate([rows, np.arange(n, dtype=rows.dtype)])
            cols = np.concatenate([cols, np.full(n, d, dtype=cols.dtype)])
            d += 1
        return SparseBatch.from_coo(
            values=values,
            rows=rows,
            cols=cols,
            labels=self.labels,
            num_features=d,
            offsets=offsets,
            weights=weights,
            dtype=dtype if dtype is not None else jnp.float32,
            row_pad_multiple=row_pad_multiple,
            nnz_pad_multiple=nnz_pad_multiple,
        )

    @property
    def intercept_index(self) -> int:
        """Index of the intercept column after to_batch(add_intercept=True)."""
        return self.num_features


def read_libsvm(
    path: str,
    zero_based: bool = False,
    binary_labels_to_01: bool = True,
    engine: str = "auto",
) -> LibSVMData:
    """Parse a LibSVM file. Labels {-1,+1} are mapped to {0,1} when
    ``binary_labels_to_01`` (the loss layer accepts both, but evaluators
    expect {0,1}).

    ``engine``: "auto" uses the native C++ parser (data/native.py, built on
    demand) and falls back to pure python; "python"/"native" force one.
    """
    if engine not in ("auto", "python", "native"):
        raise ValueError(f"unknown engine '{engine}'")
    parsed = None
    if engine in ("auto", "native"):
        from photon_ml_tpu.data.native import load_native, parse_libsvm_native

        # check availability (cheap, cached) BEFORE reading the whole file
        if load_native() is not None:
            with open(path, "rb") as f:
                raw = f.read()
            parsed = parse_libsvm_native(raw, zero_based=zero_based)
        elif engine == "native":
            raise RuntimeError("native parser unavailable (no g++ / build failed)")
    if parsed is not None:
        vals_arr, rows_arr, cols_arr, y_raw, num_features = parsed
        return _finish(
            vals_arr, rows_arr, cols_arr, y_raw, num_features,
            binary_labels_to_01,
        )
    labels: list[float] = []
    rows: list[int] = []
    cols: list[int] = []
    vals: list[float] = []
    max_col = -1
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break
                k, v = tok.split(":")
                c = int(k) - (0 if zero_based else 1)
                if c < 0:
                    raise ValueError(
                        f"negative feature index at line {i}: {tok} "
                        f"(wrong zero_based setting?)"
                    )
                rows.append(len(labels) - 1)
                cols.append(c)
                vals.append(float(v))
                max_col = max(max_col, c)

    return _finish(
        np.asarray(vals),
        np.asarray(rows, dtype=np.int64),
        np.asarray(cols, dtype=np.int64),
        np.asarray(labels),
        max_col + 1,
        binary_labels_to_01,
    )


def _finish(values, rows, cols, y, num_features, binary_labels_to_01):
    """Shared tail for both engines: label binarization + container."""
    if binary_labels_to_01 and set(np.unique(y)).issubset({-1.0, 1.0}):
        y = (y > 0).astype(np.float64)
    return LibSVMData(
        values=values, rows=rows, cols=cols, labels=y,
        num_features=num_features,
    )
