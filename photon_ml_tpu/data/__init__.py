from photon_ml_tpu.data.index_map import (  # noqa: F401
    INTERCEPT_KEY,
    IndexMap,
    MmapIndexMap,
    feature_key,
)
from photon_ml_tpu.data.libsvm import LibSVMData, read_libsvm  # noqa: F401
from photon_ml_tpu.data.normalization import (  # noqa: F401
    NormalizationContext,
    NormalizationType,
    build_normalization_context,
)
from photon_ml_tpu.data.stats import FeatureSummary, summarize  # noqa: F401
from photon_ml_tpu.data.validators import (  # noqa: F401
    DataValidationError,
    ValidationMode,
    validate,
)
