"""Feature normalization contexts.

Parity with photon-lib normalization/NormalizationContext.scala:70-131:
x' = (x - shift) .* factor applied algebraically inside the objective
(never materialized), and trained coefficients mapped back to the original
space by w = w' .* factor ; intercept -= w_out . shift.
"""

from __future__ import annotations

import dataclasses
from enum import Enum
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.stats import FeatureSummary

Array = jax.Array


class NormalizationType(str, Enum):
    NONE = "none"
    SCALE_WITH_MAX_MAGNITUDE = "scale_with_max_magnitude"
    SCALE_WITH_STANDARD_DEVIATION = "scale_with_standard_deviation"
    STANDARDIZATION = "standardization"


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """factors/shifts may be None (identity). ``intercept_index`` is the
    feature column holding the explicit intercept.

    INVARIANT (required, guaranteed by build_normalization_context): the
    intercept column is never normalized — ``factors[intercept_index] == 1``
    and ``shifts[intercept_index] == 0``. ``inverse_transform_model_
    coefficients`` is an exact inverse of ``transform_model_coefficients``
    only under this invariant; a hand-built context violating it silently
    produces wrong warm starts.

    Regularization semantics (reference parity, L2Regularization.scala):
    penalties apply to the coefficients the OPTIMIZER sees, i.e. in
    NORMALIZED space. The original-space optimum is therefore invariant to
    the normalization choice only when the regularization weight is zero
    (NormalizationTest.scala:33 tests exactly that); under L2 > 0 each
    normalization yields a (slightly) different original-space model.
    """

    factors: Optional[Array] = None
    shifts: Optional[Array] = None
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    def transform_model_coefficients(self, w: Array) -> Array:
        """Map coefficients trained in normalized space back to original space."""
        out = w if self.factors is None else w * self.factors
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts require an intercept column")
            out = out.at[self.intercept_index].add(-jnp.dot(out, self.shifts))
        return out

    def inverse_transform_model_coefficients(self, w: Array) -> Array:
        """Original space -> normalized space (exact inverse of the above).

        Used to warm-start a normalized solve from a model stored in
        original space (models always live in original space so scoring
        never needs the context)."""
        out = w
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts require an intercept column")
            out = out.at[self.intercept_index].add(jnp.dot(out, self.shifts))
        if self.factors is not None:
            out = out / self.factors
        return out


def build_normalization_context(
    normalization_type: NormalizationType | str,
    summary: Optional[FeatureSummary] = None,
    intercept_index: Optional[int] = None,
) -> NormalizationContext:
    """Factory matching NormalizationContext.apply (reference :96-131)."""
    ntype = NormalizationType(normalization_type)
    if ntype == NormalizationType.NONE:
        return NormalizationContext(intercept_index=intercept_index)
    if summary is None:
        raise ValueError(f"{ntype} requires a feature summary")

    def inv_or_one(x):
        return jnp.where(x > 0.0, 1.0 / jnp.where(x > 0.0, x, 1.0), 1.0)

    if ntype == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        magnitude = jnp.maximum(jnp.abs(summary.max), jnp.abs(summary.min))
        factors = inv_or_one(magnitude)
        if intercept_index is not None:
            factors = factors.at[intercept_index].set(1.0)
        return NormalizationContext(factors=factors, intercept_index=intercept_index)

    std = jnp.sqrt(summary.variance)
    factors = inv_or_one(std)

    if ntype == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        if intercept_index is not None:
            factors = factors.at[intercept_index].set(1.0)
        return NormalizationContext(factors=factors, intercept_index=intercept_index)

    # STANDARDIZATION: requires intercept so shifts are absorbable
    if intercept_index is None:
        raise ValueError("STANDARDIZATION requires an intercept column")
    shifts = summary.mean.at[intercept_index].set(0.0)
    factors = factors.at[intercept_index].set(1.0)
    return NormalizationContext(
        factors=factors, shifts=shifts, intercept_index=intercept_index
    )
