"""Row-level data sanity checks per task type.

Reference analog: photon-client data/DataValidators.scala (SURVEY.md §2.d):
finite features/labels/offsets/weights; binary labels for logistic;
non-negative labels for Poisson. Modes VALIDATE_FULL / VALIDATE_SAMPLE /
VALIDATE_DISABLED. Checks run host-side on the COO arrays before upload.
"""

from __future__ import annotations

from enum import Enum

import numpy as np

from photon_ml_tpu.ops.sparse import SparseBatch


class ValidationMode(str, Enum):
    FULL = "validate_full"
    SAMPLE = "validate_sample"
    DISABLED = "validate_disabled"


class DataValidationError(ValueError):
    pass


def _sample_mask(n: int, mode: ValidationMode, rng: np.random.Generator):
    if mode == ValidationMode.SAMPLE:
        return rng.random(n) < max(0.01, min(1.0, 1000.0 / max(n, 1)))
    return np.ones(n, dtype=bool)


def _sample_indices(n: int, rng: np.random.Generator) -> np.ndarray:
    """O(k)-memory subsample of [0, n) for SAMPLE-mode scans of the
    nnz-sized values array (a full random(n) temp would be 2x the array
    this mode exists to avoid copying)."""
    if n == 0:
        return np.zeros(0, np.int64)
    k = max(10, min(n, int(n * max(0.01, min(1.0, 1000.0 / n)))))
    return rng.integers(0, n, size=min(k, n))


def validate(
    batch: SparseBatch,
    task: str,
    mode: ValidationMode = ValidationMode.FULL,
    seed: int = 0,
    collect_all: bool = False,
) -> None:
    """Raise DataValidationError on the first failed check.

    ``collect_all=True`` runs EVERY check and aggregates the failures into
    one DataValidationError — the full damage report from one pass, so an
    operator fixing a bad ingest sees every problem at once instead of
    replaying the pipeline per failure."""
    if mode == ValidationMode.DISABLED:
        return
    rng = np.random.default_rng(seed)
    failures: list[str] = []

    def fail(message: str) -> None:
        if not collect_all:
            raise DataValidationError(message)
        failures.append(message)

    labels = np.asarray(batch.labels)
    offsets = np.asarray(batch.offsets)
    weights = np.asarray(batch.weights)
    values = np.asarray(batch.values)
    valid_rows = weights > 0  # padded rows excluded

    # uniform sampling contract: under SAMPLE every scan (rows AND nnz) is
    # subsampled; under FULL every scan is complete — and zero-copy (an
    # all-True fancy index would duplicate the nnz-sized values array, the
    # largest array in the batch). Weights are sampled by the row mask
    # alone — a NaN weight fails the >0 test, so filtering by valid_rows
    # would hide it from its own finiteness check.
    sampling = mode == ValidationMode.SAMPLE
    row_mask = _sample_mask(len(labels), mode, rng)
    mask = row_mask & valid_rows
    vals = values[_sample_indices(len(values), rng)] if sampling else values
    samp = lambda arr: arr[row_mask] if sampling else arr  # noqa: E731

    if not np.all(np.isfinite(vals)):
        fail("non-finite feature values")
    for name, arr in (("labels", labels), ("offsets", offsets)):
        if not np.all(np.isfinite(arr[mask] if sampling else arr[valid_rows])):
            fail(f"non-finite {name}")
    if not np.all(np.isfinite(samp(weights))):
        fail("non-finite weights")
    if np.any(samp(weights) < 0):
        fail("negative weights")

    task_l = task.lower()
    if "logistic" in task_l or "hinge" in task_l or "svm" in task_l:
        lab = labels[mask]
        lab = lab[np.isfinite(lab)]  # non-finite labels already reported
        ok = np.isin(lab, (0.0, 1.0)) | np.isin(lab, (-1.0, 1.0))
        if not np.all(ok):
            fail(
                f"binary task requires labels in {{0,1}} or {{-1,1}}; "
                f"found {np.unique(lab[~ok])[:5]}"
            )
    if "poisson" in task_l:
        if np.any(labels[mask] < 0):
            fail("poisson task requires non-negative labels")

    if failures:
        raise DataValidationError(
            f"{len(failures)} validation check(s) failed: "
            + "; ".join(failures)
        )
