"""Run-report driver: telemetry artifacts -> one readable answer.

    python -m photon_ml_tpu.cli report \
        --trace run.trace.jsonl --telemetry run.metrics.jsonl \
        --checkpoint-dir ckpt/ --out report.md [--json report.json] \
        [--compare baseline.report.json] [--fail-on-regress] \
        [--threshold 0.2] [--hot [N]] [--requests [N]]

Merges a span JSONL (``--trace-out``), a telemetry JSONL (metrics
snapshot + heartbeat lines), and a checkpoint directory's manifests into
one markdown report (stdout, or ``--out``): the phase-time tree, top-k
costs, fetch/recompile accounting, HBM peaks, per-coordinate convergence
and guard history, and heartbeat liveness.

``--fleet <dir>`` switches to the FLEET aggregation instead: the
directory's per-member artifact streams (``trace.proc-<i>.jsonl`` /
``telemetry.proc-<i>.jsonl`` — the identity suffixing contract) merge
into one report with per-member rows, collective-wait attribution, the
straggler callout, and lost-member degradation
(telemetry.fleet_report.FleetReport).

``--compare`` takes a baseline report JSON (written by ``--json`` on an
earlier run, or a bare ``{metric: value}`` dict) and appends a comparison
table; with ``--fail-on-regress`` the process exits ``3`` when any key
metric moved against its goodness direction by more than ``--threshold``
(default 20%) — the CI perf gate. With ``--fleet`` the comparison runs
over the AGGREGATED fleet key metrics (``fleet_rows_per_sec``,
``fleet_collective_wait_fraction``, ``fleet_mfu_spread``, ...).

Exit codes: 0 ok, 1 unreadable inputs, 2 usage, 3 regression detected.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

EXIT_OK = 0
EXIT_ERROR = 1
EXIT_REGRESSION = 3


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli report",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument(
        "--trace", help="span JSONL written by --trace-out / PHOTON_TRACE_OUT"
    )
    parser.add_argument(
        "--telemetry",
        help="metrics/heartbeat JSONL written by --telemetry-out",
    )
    parser.add_argument(
        "--checkpoint-dir",
        help="checkpoint directory whose step manifests carry convergence "
        "and guard history",
    )
    parser.add_argument(
        "--fleet",
        metavar="DIR",
        help="aggregate a FLEET directory of per-member artifact streams "
        "(*.proc-<i>.jsonl) into one merged report instead of reading "
        "single-run --trace/--telemetry artifacts",
    )
    parser.add_argument(
        "--out", help="write the markdown report here (default: stdout)"
    )
    parser.add_argument(
        "--json",
        dest="json_out",
        help="also write the full report as JSON (the compare-baseline "
        "format for future runs)",
    )
    parser.add_argument(
        "--hot",
        nargs="?",
        const=10,
        type=int,
        metavar="N",
        help="render ONLY the hot-executables table (top N by profiled "
        "exclusive device seconds, default 10) instead of the full "
        "report — the quick 'where did the time go' view",
    )
    parser.add_argument(
        "--requests",
        nargs="?",
        const=10,
        type=int,
        metavar="N",
        help="render ONLY the request-tracing section (the N slowest "
        "persisted request traces, default 10) — with --fleet the "
        "traces are joined across router and member streams by "
        "trace_id",
    )
    parser.add_argument(
        "--compare",
        help="baseline report JSON to diff key metrics against",
    )
    parser.add_argument(
        "--threshold",
        type=float,
        default=0.2,
        help="fractional regression threshold for --compare (default 0.2)",
    )
    parser.add_argument(
        "--fail-on-regress",
        action="store_true",
        help="exit 3 when --compare finds a key metric regressed beyond "
        "--threshold (CI perf gate)",
    )
    args = parser.parse_args(argv)
    if args.fleet and (args.trace or args.telemetry or args.checkpoint_dir):
        parser.error(
            "--fleet aggregates a member-artifact directory; it cannot "
            "be combined with --trace/--telemetry/--checkpoint-dir"
        )
    if not (
        args.fleet or args.trace or args.telemetry or args.checkpoint_dir
    ):
        parser.error(
            "nothing to report on: give --fleet, --trace, --telemetry, "
            "and/or --checkpoint-dir"
        )

    if args.fleet:
        from photon_ml_tpu.telemetry.fleet_report import FleetReport

        if not os.path.isdir(args.fleet):
            print(
                f"--fleet {args.fleet} is not a directory", file=sys.stderr
            )
            return EXIT_ERROR
        report = FleetReport.load(args.fleet)
        if not report.members:
            print(
                f"no member artifact streams (*.proc-<i>.jsonl) found "
                f"under {args.fleet}",
                file=sys.stderr,
            )
            return EXIT_ERROR
    else:
        from photon_ml_tpu.telemetry.report import RunReport

        try:
            report = RunReport.load(
                trace=args.trace,
                telemetry=args.telemetry,
                checkpoint_dir=args.checkpoint_dir,
            )
        except OSError as e:
            print(f"cannot read telemetry artifacts: {e}", file=sys.stderr)
            return EXIT_ERROR

    deltas = None
    if args.compare:
        try:
            with open(args.compare, encoding="utf-8") as fh:
                baseline = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"cannot read baseline {args.compare}: {e}", file=sys.stderr)
            return EXIT_ERROR
        if not isinstance(baseline, dict):
            print(
                f"baseline {args.compare} is not a report JSON object",
                file=sys.stderr,
            )
            return EXIT_ERROR
        deltas = report.compare(baseline, threshold=args.threshold)
        # per-executable rows are compared only when BOTH sides carry
        # them: a renamed or newly-appearing executable has no meaningful
        # delta, so it is noted and skipped rather than treated as a
        # regression (the shared-keys rule of compare_metrics)
        current_km = report.key_metrics()
        base_km = baseline.get("key_metrics", baseline)
        if isinstance(base_km, dict):
            cur_exec = {k for k in current_km if k.startswith("exec.")}
            base_exec = {k for k in base_km if k.startswith("exec.")}
            for name in sorted(cur_exec - base_exec):
                print(
                    f"note: `{name}` is new (absent from baseline — "
                    "renamed or newly-profiled executable); skipped in "
                    "the comparison",
                    file=sys.stderr,
                )
            for name in sorted(base_exec - cur_exec):
                print(
                    f"note: `{name}` exists only in the baseline "
                    "(renamed or no-longer-profiled executable); "
                    "skipped in the comparison",
                    file=sys.stderr,
                )

    if args.requests is not None:
        req_lines = report._requests_markdown(args.requests)
        md = (
            "\n".join(req_lines).rstrip() + "\n"
            if req_lines
            else "No request traces (run carried no request.* metrics "
            "or persisted request:* spans).\n"
        )
    elif args.hot is not None:
        hot_lines = report._hot_executables_markdown(args.hot)
        md = (
            "\n".join(hot_lines).rstrip() + "\n"
            if hot_lines
            else "No profiled executables (run carried no "
            "profile.exec.* gauges).\n"
        )
    else:
        md = report.to_markdown(deltas=deltas)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(md)
        print(f"report written to {args.out}")
    else:
        print(md)
    if args.json_out:
        report.save_json(args.json_out)
        if args.out:
            print(f"report JSON written to {args.json_out}")

    if deltas is not None:
        regressed = [d for d in deltas if d.regressed]
        if regressed:
            print(
                "regressions beyond threshold: "
                + ", ".join(
                    f"{d.metric} ({d.change:+.1%})" for d in regressed
                ),
                file=sys.stderr,
            )
            if args.fail_on_regress:
                return EXIT_REGRESSION
    return EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
