"""GAME scoring driver.

Reference analog: photon-client cli/game/scoring/Driver.scala:51-201 —
load model -> read data (response optional) -> score -> save
ScoringResultAvro -> optional evaluation:

    python -m photon_ml_tpu.cli score --model-dir out/model/best \\
        --config score.json [--output scores.avro] [--evaluators auc rmse]

The config's "input" block uses the same schema as the training driver.
"""

from __future__ import annotations

import argparse
import json
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.cli.train import read_input
from photon_ml_tpu.utils import logger, setup_logging, timed


def run(
    model_dir: str,
    input_spec: Mapping,
    output_path: Optional[str] = None,
    evaluators: Sequence[str] = (),
    model_id: str = "",
    allow_index_rebuild: bool = False,
) -> dict:
    import os

    from photon_ml_tpu.data.model_store import (
        ModelLoadError,
        load_feature_index_maps,
        load_game_model,
    )
    from photon_ml_tpu.evaluation import EVALUATORS

    # reuse the TRAINING feature space saved next to the model, so feature
    # ids line up with the stored coefficients (prepareFeatureMaps analog)
    index_maps = load_feature_index_maps(model_dir)
    idx_dir = os.path.join(model_dir, "feature-indexes")
    if index_maps is None and not allow_index_rebuild:
        # rebuilding the feature space from SCORING data silently misaligns
        # feature ids with the stored coefficients — hard error unless the
        # caller explicitly accepts the risk (the serving registry refuses
        # such model dirs outright, with no override)
        raise ModelLoadError(
            idx_dir,
            "missing feature-indexes/ — feature ids rebuilt from scoring "
            "data may not match the stored coefficients and scores would "
            "be silently wrong; pass --allow-index-rebuild to accept that "
            "risk",
        )
    elif index_maps is None:
        logger.warning(
            "%s has no feature-indexes/: index maps will be rebuilt by "
            "scanning the SCORING data — feature ids may not match the "
            "stored coefficients and scores may be silently wrong "
            "(--allow-index-rebuild)",
            model_dir,
        )

    with timed("read scoring data"):
        data, _ = read_input(
            input_spec, is_response_required=False, index_maps=index_maps
        )
    with timed("load model"):
        model = load_game_model(model_dir)
    with timed("score"):
        raw = np.asarray(model.score(data))[: data.num_rows]
    # saved scores include the offset (scoring Driver.scala:139-146)
    scores = raw + data.offset

    if output_path is not None:
        from photon_ml_tpu.data.avro import write_scoring_results

        with timed("save scores"):
            write_scoring_results(
                output_path,
                scores,
                model_id=model_id,
                labels=data.response,
                weights=data.weight,
            )

    metrics = {}
    if evaluators and len(np.unique(data.response)) < 2:
        logger.warning(
            "scoring data has a constant response column (%s) — requested "
            "evaluator metrics will be meaningless placeholders",
            data.response[0] if data.num_rows else "empty",
        )
    for name in evaluators:
        fn = EVALUATORS.get(name)
        if fn is None:
            raise ValueError(f"unknown evaluator '{name}'")
        metrics[name] = float(
            fn(
                np.asarray(scores, np.float32),
                np.asarray(data.response, np.float32),
                np.asarray(data.weight, np.float32),
            )
        )

    return {
        "num_rows": data.num_rows,
        "output": output_path,
        "metrics": metrics,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli score", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--model-dir", required=True, help="saved GAME model dir")
    parser.add_argument("--config", required=True, help="JSON config with input block")
    parser.add_argument("--output", help="ScoringResultAvro output path")
    parser.add_argument("--evaluators", nargs="*", default=[])
    parser.add_argument("--model-id", default="")
    parser.add_argument(
        "--allow-index-rebuild",
        action="store_true",
        help="score a model dir with no feature-indexes/ by rebuilding the "
        "feature space from the scoring data (scores may be silently wrong "
        "if the spaces differ)",
    )
    args = parser.parse_args(argv)

    setup_logging()
    with open(args.config) as f:
        config = json.load(f)
    input_spec = config["input"] if "input" in config else config
    summary = run(
        args.model_dir,
        input_spec,
        output_path=args.output,
        evaluators=args.evaluators,
        model_id=args.model_id,
        allow_index_rebuild=args.allow_index_rebuild,
    )
    print(json.dumps(summary, default=float))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
