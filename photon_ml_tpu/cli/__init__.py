"""CLI drivers (photon-client cli/ analog): train + score entry points."""
