"""Continuous-freshness driver: ``cli refresh`` — an incremental
warm-start retrain as a first-class subcommand.

A thin front over the train pipeline's warm-start branch: the SAME
training config (coordinates, evaluators, input spec) plus the base
artifact and today's delta::

    python -m photon_ml_tpu.cli refresh --config train.json \
        --warm-start ckpt/ --delta day2/part-0.avro \
        --registry-dir registry/

The combined stream is "yesterday's paths ∪ the delta" (deterministic
chunk ordering keeps yesterday's ids stable), only the touched
random-effect lanes re-solve, and the refreshed model publishes with its
lineage (base checkpoint digest + delta digest) in version metadata.
"""

from __future__ import annotations

import argparse
import json

from photon_ml_tpu.utils import setup_logging


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli refresh",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("--config", required=True,
                        help="training JSON config path")
    parser.add_argument(
        "--warm-start",
        metavar="DIR",
        help="base artifact (step/streamed checkpoint or saved model "
        "dir); defaults to config warm_start.dir",
    )
    parser.add_argument(
        "--delta",
        action="append",
        metavar="PATH",
        help="delta shard(s) appended to the input paths (repeatable)",
    )
    parser.add_argument(
        "--registry-dir",
        help="publish the refreshed model here with lineage metadata",
    )
    parser.add_argument("--output-dir", help="override config output_dir")
    parser.add_argument(
        "--lambda-points",
        type=int,
        help="local descending-λ sweep lanes around the incumbent "
        "regularization (needs a validation input)",
    )
    parser.add_argument(
        "--report-out",
        help="write the run report (with its Freshness section) here",
    )
    parser.add_argument(
        "--force",
        action="store_true",
        help="republish even when the delta digest matches what the "
        "newest registry version already trained on (without this flag "
        "an unchanged delta is a typed refusal — re-running a stuck "
        "cron must not publish no-op versions)",
    )
    parser.add_argument(
        "--no-quality-gate",
        action="store_true",
        help="bypass the champion/challenger publish gate: the "
        "candidate's quality stats are still computed and recorded "
        "(decision 'bypassed'), but a regression beyond the champion's "
        "bootstrap CI no longer quarantines the version",
    )
    parser.add_argument(
        "--bootstrap-samples",
        type=int,
        help="bootstrap resamples behind the published error bars "
        "(AUC CI + masked-lane coefficient CIs); default 32, 0 disables",
    )
    args = parser.parse_args(argv)

    setup_logging()
    with open(args.config) as f:
        config = json.load(f)
    ws = dict(config.get("warm_start") or {})
    if args.warm_start:
        ws["dir"] = args.warm_start
    if args.delta:
        ws["delta_paths"] = list(ws.get("delta_paths") or ()) + list(
            args.delta
        )
    if args.registry_dir:
        ws["registry_dir"] = args.registry_dir
    if args.lambda_points is not None:
        ws["lambda_points"] = args.lambda_points
    if args.force:
        ws["force"] = True
    if args.no_quality_gate:
        ws["quality_gate"] = False
    if args.bootstrap_samples is not None:
        ws["bootstrap_samples"] = args.bootstrap_samples
    if "dir" not in ws:
        parser.error("refresh needs --warm-start (or config warm_start.dir)")
    config["warm_start"] = ws
    # a reused TRAIN config usually points checkpoint.dir at the base
    # run's directory — exactly the dir the warm start reads. A refresh
    # must never write there (run_incremental_fit refuses), so the
    # inherited checkpoint config is dropped; incremental fits are
    # minutes-shaped and re-run from the base on failure.
    config.pop("checkpoint", None)
    if args.report_out:
        config["report_out"] = args.report_out

    from photon_ml_tpu.cli.train import run

    summary = run(config, output_dir=args.output_dir)
    print(json.dumps(summary, default=float))
    # no interrupted/75 path: refresh drops the checkpoint config (see
    # above), so the pipeline never installs the graceful-stop handshake
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
