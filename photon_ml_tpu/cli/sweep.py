"""Sweep driver: multi-λ training + best-model selection in one run.

Two entry points:

- ``cli train --sweep lambda=... --config train.json`` — the training
  driver runs the vmapped sweep INSTEAD of a single fit (train.py
  delegates to :func:`run_sweep_fit` here).
- ``cli sweep --config train.json [--sweep ...]`` — sweep-only reruns
  over the same config/dataset (e.g. re-selecting with a different grid
  or metric after the data is already materialized on disk), without the
  single-fit driver's final-model outputs.

Config object (the ``"sweep"`` key of a train config; every field has a
flag override)::

    "sweep": {
      "grid": "lambda=1e-4:1e2:log16 lambda.perUser=0.1,1",
      "metric": "auc",            # default: task's ModelSelection metric
      "policy": "best",           # or "parsimonious" (+ "rel_tol")
      "registry_dir": "registry/",  # publish the winner for live serving
      "warm_start": true,
      "num_iterations": 2          # CD sweeps; default config num_iterations
    }

The summary JSON carries a per-config table (λs, iterations, convergence
reason, validation metric) and the selection; malformed grids are typed
config errors naming the offending token (sweep.grid.SweepSpecError).
"""

from __future__ import annotations

import argparse
import json
from typing import Mapping, Optional

from photon_ml_tpu.sweep.grid import (
    SweepGrid,
    SweepSpecError,
    parse_range,
    parse_sweep_spec,
)

_SWEEP_KEYS = {
    "grid", "metric", "policy", "rel_tol", "registry_dir", "warm_start",
    "num_iterations",
}


def parse_sweep_config(spec) -> dict:
    """Normalize the config ``"sweep"`` value (string grid shorthand or
    object) into kwargs for :func:`run_sweep_fit`. Typed errors name the
    offending token/key."""
    if isinstance(spec, (str, list, tuple)):
        spec = {"grid": spec}
    spec = dict(spec)
    unknown = set(spec) - _SWEEP_KEYS
    if unknown:
        raise ValueError(f"unknown sweep config keys: {sorted(unknown)}")
    raw_grid = spec.get("grid")
    if not raw_grid:
        raise SweepSpecError("sweep.grid", "no lambda grid given")
    if isinstance(raw_grid, Mapping):
        # the SweepGrid.to_json round-trip form: {"lambda": [...], ...}.
        # Values go back through the SAME validator as the string grammar
        # (negative/NaN/empty lists must not sneak in via JSON).
        bad = set(raw_grid) - {"lambda"} - {
            k for k in raw_grid if k.startswith("lambda.")
        }
        if bad:
            raise SweepSpecError(
                str(sorted(bad)[0]), "unknown grid key (expected 'lambda' "
                "or 'lambda.<coordinate>')"
            )

        def points_of(key, value):
            if not isinstance(value, (list, tuple)) or not value:
                raise SweepSpecError(key, "empty grid (no points)")
            return parse_range(",".join(str(v) for v in value), context=key)

        default = raw_grid.get("lambda")
        grid = SweepGrid(
            default=None if default is None
            else points_of("lambda", default),
            per_coordinate={
                k[len("lambda."):]: points_of(k, v)
                for k, v in raw_grid.items()
                if k.startswith("lambda.")
            },
        )
    else:
        grid = parse_sweep_spec(raw_grid)
    return {
        "grid": grid,
        "metric": spec.get("metric"),
        "policy": spec.get("policy", "best"),
        "rel_tol": float(spec.get("rel_tol", 0.01)),
        "registry_dir": spec.get("registry_dir"),
        "warm_start": bool(spec.get("warm_start", True)),
        "num_iterations": spec.get("num_iterations"),
    }


def merge_sweep_flags(
    config: Mapping,
    grid=None,
    metric: Optional[str] = None,
    policy: Optional[str] = None,
    registry_dir: Optional[str] = None,
) -> Optional[dict]:
    """Overlay CLI sweep flags onto a config's ``"sweep"`` value (string
    shorthand normalized to an object). Returns the merged object, or
    None when neither config nor flags configure a sweep — ONE merge
    implementation shared by the train and sweep entry points."""
    sweep_cfg = config.get("sweep")
    sweep_cfg = (
        dict(sweep_cfg) if isinstance(sweep_cfg, Mapping)
        else ({"grid": sweep_cfg} if sweep_cfg else {})
    )
    if grid:
        sweep_cfg["grid"] = list(grid)
    if metric:
        sweep_cfg["metric"] = metric
    if policy:
        sweep_cfg["policy"] = policy
    if registry_dir:
        sweep_cfg["registry_dir"] = registry_dir
    return sweep_cfg or None


def run_sweep_fit(
    estimator,
    sweep_spec,
    train_data,
    validation_data,
    index_maps: Optional[Mapping],
    output_dir: Optional[str],
) -> dict:
    """Execute the sweep for the training driver; returns the summary's
    ``"sweep"`` section (per-config table + selection + export paths)."""
    parsed = parse_sweep_config(sweep_spec)
    if validation_data is None:
        raise ValueError(
            "a sweep needs a validation split to select on — add a "
            '"validation" input to the config'
        )
    result = estimator.fit_sweep(
        train_data,
        validation_data,
        parsed["grid"],
        metric=parsed["metric"],
        policy=parsed["policy"],
        rel_tol=parsed["rel_tol"],
        num_iterations=parsed["num_iterations"],
        warm_start=parsed["warm_start"],
        output_dir=output_dir,
        registry_dir=parsed["registry_dir"],
        index_maps=index_maps,
    )
    from photon_ml_tpu.optim.common import MAX_ITERATIONS, NOT_CONVERGED

    sweep = result.sweep
    selection = result.selection
    conv = sweep.convergence()
    lambdas = sweep.lambdas
    configs = []
    for g in range(sweep.size):
        entry = {
            "index": g,
            "lambdas": {name: lams[g] for name, lams in lambdas.items()},
            "iterations": int(
                max(c["iterations"][g] for c in conv.values())
            ),
            "converged": all(
                int(c["reasons"][g]) not in (NOT_CONVERGED, MAX_ITERATIONS)
                for c in conv.values()
            ),
            "metric": (
                None if selection.metrics[g] != selection.metrics[g]
                else float(selection.metrics[g])
            ),
        }
        configs.append(entry)
    out = {
        "configs": configs,
        "metric": selection.metric,
        "policy": selection.policy,
        "selected_index": selection.index,
        "selected_metric": selection.best_value,
        "selected_lambdas": configs[selection.index]["lambdas"],
        "history": sweep.history,
    }
    if result.published_version:
        out["published_version"] = result.published_version
    if output_dir:
        out["output_dir"] = output_dir
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli sweep", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--config", required=True, help="train JSON config")
    parser.add_argument(
        "--sweep",
        action="append",
        help="sweep grid token(s), e.g. 'lambda=1e-4:1e2:log16' or "
        "'lambda.perUser=0.1,1,10' (repeatable; overrides config sweep.grid)",
    )
    parser.add_argument(
        "--sweep-metric",
        help="validation metric to select on (default: the task's "
        "ModelSelection metric)",
    )
    parser.add_argument(
        "--sweep-policy",
        choices=("best", "parsimonious"),
        help="selection policy (parsimonious prefers the most regularized "
        "config within rel_tol of the best metric)",
    )
    parser.add_argument(
        "--registry-dir",
        help="publish the winning model here via publish_version (the "
        "serving ModelRegistry hot-swaps it live)",
    )
    parser.add_argument("--output-dir", help="save the winner under "
                        "<dir>/best (overrides config output_dir)")
    parser.add_argument("--trace-out", help="span JSONL (see cli train)")
    parser.add_argument("--telemetry-out", help="metrics JSONL")
    parser.add_argument("--report-out", help="run report markdown")
    args = parser.parse_args(argv)

    from photon_ml_tpu.cli.train import run
    from photon_ml_tpu.utils import setup_logging

    setup_logging()
    with open(args.config) as f:
        config = json.load(f)
    sweep_cfg = merge_sweep_flags(
        config,
        grid=args.sweep,
        metric=args.sweep_metric,
        policy=args.sweep_policy,
        registry_dir=args.registry_dir,
    )
    if not sweep_cfg or not sweep_cfg.get("grid"):
        parser.error("no sweep grid: pass --sweep lambda=... or set "
                     "config sweep.grid")
    config["sweep"] = sweep_cfg
    for key, value in (
        ("trace_out", args.trace_out),
        ("telemetry_out", args.telemetry_out),
        ("report_out", args.report_out),
    ):
        if value:
            config[key] = value
    summary = run(config, output_dir=args.output_dir)
    print(json.dumps(summary, default=float))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
