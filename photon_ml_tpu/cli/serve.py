"""GAME online scoring server driver.

Long-lived, low-latency counterpart of the batch ``cli score`` driver:

    python -m photon_ml_tpu.cli serve --registry-dir out/registry \\
        --port 8080 --max-batch 64 --max-delay-ms 5 --queue-depth 256

    python -m photon_ml_tpu.cli serve --model-dir out/model/best --stdio

``--registry-dir`` watches a versioned models directory and hot-swaps to
the newest valid version (see serving/registry.py for the layout);
``--model-dir`` pins one saved model (still requiring its
``feature-indexes/``). ``--stdio`` swaps the HTTP front end for a JSONL
stdin/stdout loop so pipelines and CI can drive the service without
sockets.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from photon_ml_tpu.utils import logger, setup_logging


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli serve", description=__doc__.splitlines()[0]
    )
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir", help="serve one saved GAME model dir")
    src.add_argument(
        "--registry-dir",
        help="watch a versioned models directory and hot-swap to the "
        "newest valid version",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--max-batch", type=int, default=64,
        help="largest padded device batch (compiled buckets are powers of "
        "two up to this)",
    )
    parser.add_argument(
        "--max-delay-ms", type=float, default=5.0,
        help="micro-batching deadline: how long a request may wait for "
        "co-riders",
    )
    parser.add_argument(
        "--queue-depth", type=int, default=256,
        help="admission control: pending-row cap before requests are shed "
        "with 503",
    )
    parser.add_argument(
        "--max-row-nnz", type=int, default=128,
        help="per-shard feature cap per request row",
    )
    parser.add_argument(
        "--poll-interval", type=float, default=2.0,
        help="registry watch interval in seconds",
    )
    parser.add_argument(
        "--stdio", action="store_true",
        help="serve a JSONL request/response loop on stdin/stdout instead "
        "of HTTP",
    )
    args = parser.parse_args(argv)

    setup_logging()
    from photon_ml_tpu import faults

    # a serving process with an armed fault plan WILL fail requests on
    # purpose — say so at startup, loudly
    faults.warn_if_armed()
    from photon_ml_tpu.serving import (
        ModelRegistry,
        ScoringEngine,
        ScoringServer,
        ScoringService,
        serve_stdio,
    )

    registry = None
    if args.model_dir:
        source = ScoringEngine.load(
            args.model_dir,
            max_batch=args.max_batch,
            max_row_nnz=args.max_row_nnz,
        ).warmup()
    else:
        registry = ModelRegistry(
            args.registry_dir,
            max_batch=args.max_batch,
            max_row_nnz=args.max_row_nnz,
            poll_interval=args.poll_interval,
        )
        registry.start()
        source = registry

    try:
        if args.stdio:
            return serve_stdio(source, sys.stdin, sys.stdout)
        service = ScoringService(
            source,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            queue_depth=args.queue_depth,
        )
        server = ScoringServer(service, host=args.host, port=args.port)
        server.start()
        stop = threading.Event()

        def _on_signal(signum, frame):
            logger.info("received signal %d: shutting down", signum)
            stop.set()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
        print(
            json.dumps(
                {
                    "serving": {
                        "host": args.host,
                        "port": server.port,
                        "model_version": service.health().get("model_version"),
                    }
                }
            ),
            flush=True,
        )
        stop.wait()
        server.stop()
        return 0
    finally:
        if registry is not None:
            registry.stop()


if __name__ == "__main__":
    raise SystemExit(main())
