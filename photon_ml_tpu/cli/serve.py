"""GAME online scoring server driver.

Long-lived, low-latency counterpart of the batch ``cli score`` driver:

    python -m photon_ml_tpu.cli serve --registry-dir out/registry \\
        --port 8080 --max-batch 64 --queue-depth 256

    python -m photon_ml_tpu.cli serve --model-dir out/model/best \\
        --mesh model=8 --frontend asyncio --batcher continuous \\
        --nearline memberId --nearline-publish-dir out/registry

    python -m photon_ml_tpu.cli serve --model-dir out/model/best --stdio

    python -m photon_ml_tpu.cli serve --registry-dir out/registry \\
        --member 1 --fleet-size 4 --announce-dir out/fleet \\
        --hbm-budget-mb 64 --port 0

    python -m photon_ml_tpu.cli serve --registry-dir out/registry \\
        --router --announce-dir out/fleet --port 8080

``--registry-dir`` watches a versioned models directory and hot-swaps to
the newest valid version (see serving/registry.py for the layout);
``--model-dir`` pins one saved model (still requiring its
``feature-indexes/``). ``--mesh model=N`` serves the random-effect
coefficient tables ENTITY-SHARDED over an N-device mesh axis instead of
replicated — the GLMix "tables too big for one chip" deployment;
``--re-checkpoint coord=dir`` restores that coordinate's table from a
sharded streamed-checkpoint manifest straight onto the serving mesh
(``restore_placed``, no host materialization). ``--frontend asyncio``
swaps the thread-per-connection stdlib server for the event-loop front
end; ``--batcher continuous`` swaps the fixed-deadline micro-batcher for
continuous batching (admit rows into the next in-flight bucket as device
capacity frees). ``--nearline <id_name>`` accepts ``POST /v1/update``
feedback events and re-solves just those entities' coefficient rows in
place. ``--stdio`` swaps the HTTP front end for a JSONL stdin/stdout
loop so pipelines and CI can drive the service without sockets.

``--member i --fleet-size N`` serves as ONE shard-owning fleet member:
the process loads only its deterministic entity slice of every
random-effect table (serving/shard.py), enforces ``--hbm-budget-mb``
against the SLICE, announces readiness into ``--announce-dir`` once
warm, and accepts ``/v1/admin/stage`` + ``/v1/admin/commit`` for live
resizes and hot swaps. ``--router`` serves the fleet's routing front
end instead: entity lookups fan out to owning members discovered from
the announce directory and partial margins fold exactly
(serving/router.py) — unreachable members degrade to fixed-effect-only
scores, never failures.

SIGTERM/SIGINT drains gracefully: admission closes (503 with
``Retry-After``), in-flight batches finish, and the process exits 75
("incomplete, restart me" — schedulers relaunch it). A second signal
hard-exits immediately.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from photon_ml_tpu.utils import logger, setup_logging


def _build_mesh(raw: str):
    """``--mesh`` flag -> a serving Mesh (or None for off)."""
    from photon_ml_tpu.cli.train import parse_mesh_flag
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.sharding import MODEL_AXIS

    spec = parse_mesh_flag(raw)
    if spec is False:
        return None
    if spec is True:
        import jax

        spec = {MODEL_AXIS: jax.device_count()}
    return make_mesh(spec)


def _parse_re_checkpoints(pairs):
    out = {}
    for pair in pairs or ():
        coord, eq, directory = pair.partition("=")
        if not eq or not coord or not directory:
            raise ValueError(
                f"--re-checkpoint expects 'coord=dir', got {pair!r}"
            )
        out[coord] = directory
    return out or None


class _ServingBeat:
    """Member-attributed serving heartbeat: append one JSONL line per
    interval carrying the cumulative request/row counters, so the fleet
    supervisor's ``tail_heartbeat_fields`` poll can difference
    successive beats into a live requests/s without any RPC into the
    member."""

    def __init__(self, path: str, member: int, interval_s: float = 1.0):
        self.path = path
        self.member = int(member)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()
        self._seq = 0
        self._t0 = time.monotonic()

    def beat(self) -> None:
        from photon_ml_tpu import telemetry

        with self._lock:
            self._seq += 1
            seq = self._seq
        line = {
            "type": "heartbeat",
            "seq": seq,
            "proc": self.member,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "serving_requests_total": int(
                telemetry.counter("serving.requests").value
            ),
            "serving_margin_rows_total": int(
                telemetry.counter("serving.margin_rows").value
            ),
        }
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(line) + "\n")

    def start(self) -> "_ServingBeat":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.beat()
        self._thread = threading.Thread(
            target=self._run, name="serving-beat", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError as e:  # a torn-down workdir must not kill serving
                logger.warning("serving heartbeat write failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 4)
            self._thread = None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli serve", description=__doc__.splitlines()[0]
    )
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir", help="serve one saved GAME model dir")
    src.add_argument(
        "--registry-dir",
        help="watch a versioned models directory and hot-swap to the "
        "newest valid version",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--mesh",
        help="serve entity-sharded over a named mesh: 'model=N' places "
        "random-effect coefficient tables across the N-device model axis "
        "('auto' uses all devices); registry hot swaps re-place every "
        "new version with the same sharding",
    )
    parser.add_argument(
        "--entity-axis",
        help="mesh axis to shard entity rows over (default: the mesh's "
        "model axis)",
    )
    parser.add_argument(
        "--re-checkpoint",
        action="append",
        metavar="COORD=DIR",
        help="restore this coordinate's coefficient table from a sharded "
        "streamed-checkpoint directory straight onto the serving mesh "
        "(repeatable)",
    )
    parser.add_argument(
        "--frontend",
        choices=("threading", "asyncio"),
        default="threading",
        help="HTTP front end: stdlib thread-per-connection or the "
        "single-event-loop server (asyncio defaults --batcher to "
        "continuous)",
    )
    parser.add_argument(
        "--batcher",
        choices=("deadline", "continuous"),
        help="request scheduler: fixed-deadline coalescing (MicroBatcher) "
        "or continuous batching (default: continuous under --frontend "
        "asyncio, deadline otherwise)",
    )
    parser.add_argument(
        "--max-batch", type=int, default=64,
        help="largest padded device batch (compiled buckets are powers of "
        "two up to this)",
    )
    parser.add_argument(
        "--max-delay-ms", type=float, default=5.0,
        help="micro-batching deadline: how long a request may wait for "
        "co-riders (deadline batcher only; continuous ignores it)",
    )
    parser.add_argument(
        "--queue-depth", type=int, default=256,
        help="admission control: pending-row cap before requests are shed "
        "with 503",
    )
    parser.add_argument(
        "--max-row-nnz", type=int, default=128,
        help="per-shard feature cap per request row",
    )
    parser.add_argument(
        "--poll-interval", type=float, default=2.0,
        help="registry watch interval in seconds",
    )
    parser.add_argument(
        "--nearline",
        metavar="ID_NAME",
        help="accept POST /v1/update feedback events and re-solve that "
        "random-effect coordinate's entity rows in place",
    )
    parser.add_argument(
        "--nearline-flush-s", type=float, default=1.0,
        help="nearline flush cadence: buffered events are re-solved and "
        "swapped into the live tables this often",
    )
    parser.add_argument(
        "--nearline-publish-dir",
        help="persist nearline-updated tables as new registry versions "
        "here (defaults to --registry-dir when watching one)",
    )
    parser.add_argument(
        "--nearline-publish-s", type=float, default=30.0,
        help="minimum seconds between nearline version publishes",
    )
    parser.add_argument(
        "--stdio", action="store_true",
        help="serve a JSONL request/response loop on stdin/stdout instead "
        "of HTTP",
    )
    fleet = parser.add_argument_group(
        "serving fleet (shard-owning members + routing front end)"
    )
    fleet.add_argument(
        "--member", type=int,
        help="serve as shard-owning fleet member i: load ONLY this "
        "member's contiguous entity slice of every random-effect table",
    )
    fleet.add_argument(
        "--fleet-size", type=int,
        help="fleet size N the ownership map is derived from (required "
        "with --member)",
    )
    fleet.add_argument(
        "--router", action="store_true",
        help="serve as the fleet routing front end: fan entity lookups "
        "out to owning members and fold partial margins exactly",
    )
    fleet.add_argument(
        "--announce-dir",
        help="fleet rendezvous directory: members atomically announce "
        "member-<i>.json once warm; the router adopts the newest "
        "complete epoch (required with --member / --router)",
    )
    fleet.add_argument(
        "--epoch", type=int, default=0,
        help="announce epoch this member starts in (a resize launches "
        "replacements at epoch+1)",
    )
    fleet.add_argument(
        "--hbm-budget-mb", type=float,
        help="fail startup (ShardBudgetError) if the member's SLICE "
        "exceeds this many MiB — the whole point of the fleet is that "
        "the slice fits where the full model cannot",
    )
    fleet.add_argument(
        "--heartbeat-dir",
        help="touch proc-<member>.alive here on a cadence so the fleet "
        "supervisor detects a dead member from file mtime alone",
    )
    fleet.add_argument(
        "--telemetry-out",
        help="append member-attributed serving heartbeat JSONL here "
        "(requests/s for the fleet status surface); the final metrics "
        "snapshot flushes to the same stream on graceful drain",
    )
    parser.add_argument(
        "--trace-out",
        help="span JSONL sink (member-suffixed in a fleet); request "
        "records tail-sample into it, and the drain path dumps the "
        "flight recorder (flight-proc-<i>.json) next to it",
    )
    fleet.add_argument(
        "--trace-sample-every", type=int, default=0,
        help="router: explicitly sample every Nth routed batch (full "
        "trace persisted on router AND members); 0 disables explicit "
        "sampling — slow/degraded/errored requests still persist",
    )
    fleet.add_argument(
        "--member-timeout-s", type=float, default=5.0,
        help="router: per-member fan-out timeout before bounded "
        "retry/backoff and degraded fallback",
    )
    fleet.add_argument(
        "--router-refresh-s", type=float, default=0.5,
        help="router: announce-directory rescan cadence",
    )
    args = parser.parse_args(argv)

    setup_logging()
    from photon_ml_tpu import faults, telemetry

    # a serving process with an armed fault plan WILL fail requests on
    # purpose — say so at startup, loudly
    faults.warn_if_armed()
    if args.trace_out:
        # member-suffixed (idempotent): N fleet processes pointed at one
        # --trace-out value write N streams, the --fleet report contract
        telemetry.configure(
            trace_out=telemetry.member_artifact_path(args.trace_out)
        )
    from photon_ml_tpu.serving import (
        AsyncScoringServer,
        FleetRouter,
        ModelRegistry,
        NearlineUpdater,
        ScoringEngine,
        ScoringServer,
        ScoringService,
        ShardMemberSource,
        fleet_lookups_from_version_dir,
        load_member_engine,
        scan_versions,
        serve_stdio,
        write_announce,
    )

    if args.member is not None and args.router:
        raise SystemExit(
            "--member and --router are different fleet processes; run one"
        )
    fleet_mode = args.member is not None or args.router
    if fleet_mode:
        if not args.announce_dir:
            raise SystemExit("--member/--router require --announce-dir")
        incompatible = [
            flag
            for flag, on in (
                ("--stdio", args.stdio),
                ("--nearline", args.nearline),
                ("--mesh", args.mesh),
            )
            if on
        ]
        if incompatible:
            raise SystemExit(
                "fleet processes replicate fixed effects and slice "
                "random-effect tables per member; drop "
                + ", ".join(incompatible)
            )
    if args.member is not None and args.fleet_size is None:
        raise SystemExit("--member requires --fleet-size")

    def _version_dir(version=None):
        """Resolve a registry version string (None = newest) to its
        published directory; ``--model-dir`` pins one directory."""
        if args.model_dir:
            return args.model_dir
        versions = scan_versions(args.registry_dir)
        if not versions:
            raise SystemExit(
                f"no published versions under {args.registry_dir}"
            )
        if version is None:
            return versions[-1][1]
        for _, path in versions:
            if os.path.basename(os.path.normpath(path)) == str(version):
                return path
        # the front ends map KeyError to HTTP 409 version_unavailable
        raise KeyError(
            f"version {version!r} is not published under "
            f"{args.registry_dir}"
        )

    registry = None
    heartbeat = None
    beat = None
    mesh = _build_mesh(args.mesh) if args.mesh else None
    if args.member is not None:

        def _load_slice(fleet_size, version=None):
            return load_member_engine(
                _version_dir(version),
                args.member,
                fleet_size,
                max_batch=args.max_batch,
                max_row_nnz=args.max_row_nnz,
                hbm_budget_bytes=(
                    None
                    if args.hbm_budget_mb is None
                    else int(args.hbm_budget_mb * 2**20)
                ),
                re_checkpoints=_parse_re_checkpoints(args.re_checkpoint),
            )

        source = ShardMemberSource(
            _load_slice, member=args.member, fleet_size=args.fleet_size
        )
        # load + warm BEFORE serving: announcing is the readiness barrier
        source.commit(*source.stage(args.fleet_size))
    elif args.router:
        task, link, lookups = fleet_lookups_from_version_dir(_version_dir())
        source = FleetRouter(
            args.announce_dir,
            lookups,
            task=task,
            link=link,
            member_timeout_s=args.member_timeout_s,
            refresh_interval_s=args.router_refresh_s,
            max_batch=args.max_batch,
            sample_every=args.trace_sample_every,
        )
    elif args.model_dir:
        source = ScoringEngine.load(
            args.model_dir,
            max_batch=args.max_batch,
            max_row_nnz=args.max_row_nnz,
            mesh=mesh,
            entity_axis=args.entity_axis,
            re_checkpoints=_parse_re_checkpoints(args.re_checkpoint),
        ).warmup()
    else:
        if args.re_checkpoint:
            raise SystemExit(
                "--re-checkpoint requires --model-dir (registry versions "
                "carry their own tables)"
            )
        registry = ModelRegistry(
            args.registry_dir,
            max_batch=args.max_batch,
            max_row_nnz=args.max_row_nnz,
            poll_interval=args.poll_interval,
            mesh=mesh,
            entity_axis=args.entity_axis,
        )
        registry.start()
        source = registry

    try:
        if args.stdio:
            ignored = [
                flag
                for flag, on in (
                    ("--nearline", args.nearline),
                    ("--frontend", args.frontend != "threading"),
                    ("--batcher", args.batcher),
                )
                if on
            ]
            if ignored:
                raise SystemExit(
                    "--stdio is a bare engine loop with no batcher, front "
                    "end, or nearline path; drop " + ", ".join(ignored)
                )
            return serve_stdio(source, sys.stdin, sys.stdout)
        batcher = args.batcher or (
            "continuous" if args.frontend == "asyncio" else "deadline"
        )
        service = ScoringService(
            source,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            queue_depth=args.queue_depth,
            batcher=batcher,
        )
        if args.nearline:
            publish_dir = args.nearline_publish_dir or args.registry_dir
            engine = source.engine if registry is not None else source
            service.attach_nearline(
                NearlineUpdater(
                    source,
                    id_name=args.nearline,
                    flush_interval_s=args.nearline_flush_s,
                    publish_dir=publish_dir,
                    publish_interval_s=args.nearline_publish_s,
                    index_maps=engine.index_maps if publish_dir else None,
                )
            )
        server_cls = (
            AsyncScoringServer if args.frontend == "asyncio" else ScoringServer
        )
        server = server_cls(service, host=args.host, port=args.port)
        server.start()

        epoch_ref = {"epoch": int(args.epoch)}

        def _owned_ranges(fleet_size, version):
            from photon_ml_tpu.parallel.sharding import member_row_range

            try:
                with open(
                    os.path.join(
                        _version_dir(version), "model-metadata.json"
                    )
                ) as fh:
                    meta = json.load(fh)
                out = {}
                for spec in (meta.get("coordinates") or {}).values():
                    if spec.get("type") != "random_effect":
                        continue
                    lo, hi = member_row_range(
                        int(spec["num_entities"]), args.member, fleet_size
                    )
                    out[spec["id_name"]] = [lo, hi]
                return out
            except (OSError, ValueError, KeyError):
                return {}

        def _announce(fleet_size, version):
            write_announce(
                args.announce_dir,
                {
                    "member": args.member,
                    "fleet_size": int(fleet_size),
                    "epoch": epoch_ref["epoch"],
                    "url": f"http://{args.host}:{server.port}",
                    "version": str(version),
                    "ready": True,
                    "pid": os.getpid(),
                    "owned": _owned_ranges(fleet_size, version),
                },
            )

        if args.member is not None:

            def _on_commit(key, payload):
                fleet_size, version = key
                if payload.get("epoch") is not None:
                    epoch_ref["epoch"] = int(payload["epoch"])
                _announce(fleet_size, version)

            service.on_commit = _on_commit
            _announce(source.fleet_size, source.engine.version)
            if args.heartbeat_dir:
                from photon_ml_tpu.parallel.multihost import HeartbeatWriter

                heartbeat = HeartbeatWriter(
                    args.heartbeat_dir, args.member
                ).start()
            if args.telemetry_out:
                beat = _ServingBeat(args.telemetry_out, args.member).start()

        from photon_ml_tpu.game.checkpoint import GracefulStop

        stop = GracefulStop(hard_exit_code=75).install()
        banner = {
            "host": args.host,
            "port": server.port,
            "frontend": args.frontend,
            "batcher": batcher,
            "model_version": service.health().get("model_version"),
        }
        if args.member is not None:
            banner["member"] = args.member
            banner["fleet_size"] = source.fleet_size
            banner["epoch"] = epoch_ref["epoch"]
        if args.router:
            banner["router"] = True
        print(json.dumps({"serving": banner}), flush=True)
        while not stop():
            time.sleep(0.2)
        logger.info(
            "draining: admission closed (503 + Retry-After), in-flight "
            "batches finishing; exiting %d", stop.hard_exit_code,
        )
        service.drain()
        server.stop()
        # the flight recorder's drain-path dump: the last seconds of
        # request records land atomically next to the telemetry
        # artifacts, so even a drained member leaves its last words
        flight_dir = next(
            (
                os.path.dirname(os.path.abspath(p))
                for p in (args.trace_out, args.telemetry_out)
                if p
            ),
            None,
        )
        if flight_dir is not None:
            from photon_ml_tpu.telemetry import identity, requests

            proc = identity.fleet_process_index()
            if proc is None:
                proc = args.member or 0
            requests.flight_dump(requests.flight_path(flight_dir, proc))
        if args.telemetry_out:
            # the final metrics snapshot: its presence is what marks this
            # member "ok" (not lost) in the fleet report
            telemetry.flush_metrics(args.telemetry_out)
        return stop.hard_exit_code
    finally:
        if beat is not None:
            beat.stop()
        if heartbeat is not None:
            heartbeat.stop()
        if registry is not None:
            registry.stop()
        if args.router:
            source.close()


if __name__ == "__main__":
    raise SystemExit(main())
