"""GAME online scoring server driver.

Long-lived, low-latency counterpart of the batch ``cli score`` driver:

    python -m photon_ml_tpu.cli serve --registry-dir out/registry \\
        --port 8080 --max-batch 64 --queue-depth 256

    python -m photon_ml_tpu.cli serve --model-dir out/model/best \\
        --mesh model=8 --frontend asyncio --batcher continuous \\
        --nearline memberId --nearline-publish-dir out/registry

    python -m photon_ml_tpu.cli serve --model-dir out/model/best --stdio

``--registry-dir`` watches a versioned models directory and hot-swaps to
the newest valid version (see serving/registry.py for the layout);
``--model-dir`` pins one saved model (still requiring its
``feature-indexes/``). ``--mesh model=N`` serves the random-effect
coefficient tables ENTITY-SHARDED over an N-device mesh axis instead of
replicated — the GLMix "tables too big for one chip" deployment;
``--re-checkpoint coord=dir`` restores that coordinate's table from a
sharded streamed-checkpoint manifest straight onto the serving mesh
(``restore_placed``, no host materialization). ``--frontend asyncio``
swaps the thread-per-connection stdlib server for the event-loop front
end; ``--batcher continuous`` swaps the fixed-deadline micro-batcher for
continuous batching (admit rows into the next in-flight bucket as device
capacity frees). ``--nearline <id_name>`` accepts ``POST /v1/update``
feedback events and re-solves just those entities' coefficient rows in
place. ``--stdio`` swaps the HTTP front end for a JSONL stdin/stdout
loop so pipelines and CI can drive the service without sockets.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from photon_ml_tpu.utils import logger, setup_logging


def _build_mesh(raw: str):
    """``--mesh`` flag -> a serving Mesh (or None for off)."""
    from photon_ml_tpu.cli.train import parse_mesh_flag
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.sharding import MODEL_AXIS

    spec = parse_mesh_flag(raw)
    if spec is False:
        return None
    if spec is True:
        import jax

        spec = {MODEL_AXIS: jax.device_count()}
    return make_mesh(spec)


def _parse_re_checkpoints(pairs):
    out = {}
    for pair in pairs or ():
        coord, eq, directory = pair.partition("=")
        if not eq or not coord or not directory:
            raise ValueError(
                f"--re-checkpoint expects 'coord=dir', got {pair!r}"
            )
        out[coord] = directory
    return out or None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli serve", description=__doc__.splitlines()[0]
    )
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir", help="serve one saved GAME model dir")
    src.add_argument(
        "--registry-dir",
        help="watch a versioned models directory and hot-swap to the "
        "newest valid version",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--mesh",
        help="serve entity-sharded over a named mesh: 'model=N' places "
        "random-effect coefficient tables across the N-device model axis "
        "('auto' uses all devices); registry hot swaps re-place every "
        "new version with the same sharding",
    )
    parser.add_argument(
        "--entity-axis",
        help="mesh axis to shard entity rows over (default: the mesh's "
        "model axis)",
    )
    parser.add_argument(
        "--re-checkpoint",
        action="append",
        metavar="COORD=DIR",
        help="restore this coordinate's coefficient table from a sharded "
        "streamed-checkpoint directory straight onto the serving mesh "
        "(repeatable)",
    )
    parser.add_argument(
        "--frontend",
        choices=("threading", "asyncio"),
        default="threading",
        help="HTTP front end: stdlib thread-per-connection or the "
        "single-event-loop server (asyncio defaults --batcher to "
        "continuous)",
    )
    parser.add_argument(
        "--batcher",
        choices=("deadline", "continuous"),
        help="request scheduler: fixed-deadline coalescing (MicroBatcher) "
        "or continuous batching (default: continuous under --frontend "
        "asyncio, deadline otherwise)",
    )
    parser.add_argument(
        "--max-batch", type=int, default=64,
        help="largest padded device batch (compiled buckets are powers of "
        "two up to this)",
    )
    parser.add_argument(
        "--max-delay-ms", type=float, default=5.0,
        help="micro-batching deadline: how long a request may wait for "
        "co-riders (deadline batcher only; continuous ignores it)",
    )
    parser.add_argument(
        "--queue-depth", type=int, default=256,
        help="admission control: pending-row cap before requests are shed "
        "with 503",
    )
    parser.add_argument(
        "--max-row-nnz", type=int, default=128,
        help="per-shard feature cap per request row",
    )
    parser.add_argument(
        "--poll-interval", type=float, default=2.0,
        help="registry watch interval in seconds",
    )
    parser.add_argument(
        "--nearline",
        metavar="ID_NAME",
        help="accept POST /v1/update feedback events and re-solve that "
        "random-effect coordinate's entity rows in place",
    )
    parser.add_argument(
        "--nearline-flush-s", type=float, default=1.0,
        help="nearline flush cadence: buffered events are re-solved and "
        "swapped into the live tables this often",
    )
    parser.add_argument(
        "--nearline-publish-dir",
        help="persist nearline-updated tables as new registry versions "
        "here (defaults to --registry-dir when watching one)",
    )
    parser.add_argument(
        "--nearline-publish-s", type=float, default=30.0,
        help="minimum seconds between nearline version publishes",
    )
    parser.add_argument(
        "--stdio", action="store_true",
        help="serve a JSONL request/response loop on stdin/stdout instead "
        "of HTTP",
    )
    args = parser.parse_args(argv)

    setup_logging()
    from photon_ml_tpu import faults

    # a serving process with an armed fault plan WILL fail requests on
    # purpose — say so at startup, loudly
    faults.warn_if_armed()
    from photon_ml_tpu.serving import (
        AsyncScoringServer,
        ModelRegistry,
        NearlineUpdater,
        ScoringEngine,
        ScoringServer,
        ScoringService,
        serve_stdio,
    )

    registry = None
    mesh = _build_mesh(args.mesh) if args.mesh else None
    if args.model_dir:
        source = ScoringEngine.load(
            args.model_dir,
            max_batch=args.max_batch,
            max_row_nnz=args.max_row_nnz,
            mesh=mesh,
            entity_axis=args.entity_axis,
            re_checkpoints=_parse_re_checkpoints(args.re_checkpoint),
        ).warmup()
    else:
        if args.re_checkpoint:
            raise SystemExit(
                "--re-checkpoint requires --model-dir (registry versions "
                "carry their own tables)"
            )
        registry = ModelRegistry(
            args.registry_dir,
            max_batch=args.max_batch,
            max_row_nnz=args.max_row_nnz,
            poll_interval=args.poll_interval,
            mesh=mesh,
            entity_axis=args.entity_axis,
        )
        registry.start()
        source = registry

    try:
        if args.stdio:
            ignored = [
                flag
                for flag, on in (
                    ("--nearline", args.nearline),
                    ("--frontend", args.frontend != "threading"),
                    ("--batcher", args.batcher),
                )
                if on
            ]
            if ignored:
                raise SystemExit(
                    "--stdio is a bare engine loop with no batcher, front "
                    "end, or nearline path; drop " + ", ".join(ignored)
                )
            return serve_stdio(source, sys.stdin, sys.stdout)
        batcher = args.batcher or (
            "continuous" if args.frontend == "asyncio" else "deadline"
        )
        service = ScoringService(
            source,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            queue_depth=args.queue_depth,
            batcher=batcher,
        )
        if args.nearline:
            publish_dir = args.nearline_publish_dir or args.registry_dir
            engine = source.engine if registry is not None else source
            service.attach_nearline(
                NearlineUpdater(
                    source,
                    id_name=args.nearline,
                    flush_interval_s=args.nearline_flush_s,
                    publish_dir=publish_dir,
                    publish_interval_s=args.nearline_publish_s,
                    index_maps=engine.index_maps if publish_dir else None,
                )
            )
        server_cls = (
            AsyncScoringServer if args.frontend == "asyncio" else ScoringServer
        )
        server = server_cls(service, host=args.host, port=args.port)
        server.start()
        stop = threading.Event()

        def _on_signal(signum, frame):
            logger.info("received signal %d: shutting down", signum)
            stop.set()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
        print(
            json.dumps(
                {
                    "serving": {
                        "host": args.host,
                        "port": server.port,
                        "frontend": args.frontend,
                        "batcher": batcher,
                        "model_version": service.health().get("model_version"),
                    }
                }
            ),
            flush=True,
        )
        stop.wait()
        server.stop()
        return 0
    finally:
        if registry is not None:
            registry.stop()


if __name__ == "__main__":
    raise SystemExit(main())
