"""Offline feature-index build job.

Reference analog: photon-client FeatureIndexingJob.scala:56-170 — a
standalone job scanning training Avro for name+term feature keys and
writing a partitioned PalDB index store, optionally per feature shard, with
intercept injection. Here the store is the mmap-friendly sorted-hash layout
of data/index_map.py:

    python -m photon_ml_tpu.cli index --input train/ --output idx/ \\
        [--shards global:features,userFeatures user:userFeatures] \\
        [--no-intercept]
"""

from __future__ import annotations

import argparse
import json
import os

from photon_ml_tpu.utils import logger, setup_logging, timed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli index", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--input", required=True, nargs="+", help=".avro files or directories"
    )
    parser.add_argument("--output", required=True, help="index store directory")
    parser.add_argument(
        "--shards",
        nargs="*",
        default=[],
        help="shard specs 'name:bag1,bag2' (featureShardId sections map); "
        "default one shard 'features' from the 'features' bag",
    )
    parser.add_argument(
        "--no-intercept",
        action="store_true",
        help="do not inject the intercept key",
    )
    args = parser.parse_args(argv)
    setup_logging()

    from photon_ml_tpu.data.avro import build_index_maps_from_avro

    shards: dict[str, tuple[str, ...]] = {}
    for spec in args.shards:
        name, _, bags = spec.partition(":")
        if not bags:
            raise SystemExit(f"bad shard spec '{spec}' (want name:bag1,bag2)")
        shards[name] = tuple(bags.split(","))
    if not shards:
        shards = {"features": ("features",)}

    summary = {}
    with timed(f"index {len(shards)} shard(s), one scan"):
        maps = build_index_maps_from_avro(
            args.input, shards, add_intercept=not args.no_intercept
        )
    for shard, imap in maps.items():
        out_dir = os.path.join(args.output, shard)
        imap.save(out_dir)
        logger.info("shard '%s': %d features -> %s", shard, len(imap), out_dir)
        summary[shard] = {"num_features": len(imap), "path": out_dir}
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
