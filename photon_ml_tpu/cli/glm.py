"""Legacy single-GLM driver: the staged pipeline
INIT -> PREPROCESSED -> TRAINED -> VALIDATED -> DIAGNOSED.

Reference analog: photon-client Driver.scala:71-732 — each stage asserts
its predecessor completed (assertDriverStage/updateStage, :633-651), the
train stage runs the warm-started lambda sweep via ModelTraining, validate
computes per-lambda metrics and selects the best model, diagnose runs the
photon-diagnostics suite and renders an HTML report, and models are
written in text form (IOUtils.writeModelsInText):

    python -m photon_ml_tpu.cli glm --config glm.json

Config:

    {
      "task": "logistic",
      "input": {"format": "libsvm", "paths": ["a1a"]},
      "validation": {"paths": ["a1a.t"]},     # optional
      "optimizer": {"type": "lbfgs", "regularization": "l2"},
      "lambdas": [100.0, 10.0, 1.0, 0.1],
      "normalization": "standardization",      # optional
      "compute_variances": false,
      "diagnostics": true,
      "validation_mode": "full",               # full | sample | disabled
      "output_dir": "out/"
    }
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import json
import os
from typing import Mapping, Optional

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.cli.train import read_input
from photon_ml_tpu.utils import logger, setup_logging, timed
from photon_ml_tpu.utils.events import (
    EventEmitter,
    OptimizationLogEvent,
    SetupEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)


class DriverStage(enum.IntEnum):
    """Pipeline stages with strict ordering (DriverStage.scala)."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3
    DIAGNOSED = 4


class GLMDriver:
    """Staged legacy GLM pipeline. ``stage_history`` records every stage
    transition (the MockDriver assertion surface in the reference tests)."""

    def __init__(self, config: Mapping, output_dir: Optional[str] = None):
        self.config = dict(config)
        self.output_dir = output_dir or self.config.get("output_dir")
        self.stage = DriverStage.INIT
        self.stage_history: list[DriverStage] = [DriverStage.INIT]
        self.events = EventEmitter()
        self.sweep = None  # list[SweepEntry]
        self.best = None  # (SweepEntry, metric)
        self.metrics: dict[float, dict] = {}
        self._batch = None
        self._val_batch = None
        self._normalization = None
        self._summary = None

    # -- stage protocol (Driver.scala:633-651) ------------------------------

    def _assert_stage(self, expected: DriverStage) -> None:
        if self.stage != expected:
            raise RuntimeError(
                f"driver stage must be {expected.name} but is {self.stage.name}"
            )

    def _update_stage(self, new: DriverStage) -> None:
        self.stage = new
        self.stage_history.append(new)

    # -- stages --------------------------------------------------------------

    def preprocess(self) -> None:
        """Read + validate + summarize + build the normalization context
        (Driver.scala:300-325)."""
        from photon_ml_tpu.data.normalization import (
            NormalizationType,
            build_normalization_context,
        )
        from photon_ml_tpu.data.stats import summarize
        from photon_ml_tpu.data.validators import ValidationMode, validate

        from photon_ml_tpu.data.index_map import INTERCEPT_KEY

        task = self.config["task"]
        in_spec = self.config["input"]
        data, index_maps = read_input(in_spec)
        if len(data.feature_shards) != 1:
            raise ValueError(
                "the legacy GLM driver trains one feature shard; got "
                f"{sorted(data.feature_shards)} (use the GAME train driver "
                "for multi-shard configs)"
            )
        shard = next(iter(data.feature_shards))
        self._batch = data.batch_for(shard)
        # accept the short aliases full/sample/disabled as well as the
        # reference's VALIDATE_FULL-style names
        raw_mode = str(self.config.get("validation_mode", "full")).lower()
        if not raw_mode.startswith("validate_"):
            raw_mode = f"validate_{raw_mode}"
        mode = ValidationMode(raw_mode)
        validate(self._batch, task, mode=mode)
        self._summary = summarize(self._batch)

        # locate the intercept column: explicit config wins; otherwise
        # libsvm's appended last column / the avro index map's intercept key
        add_intercept = bool(in_spec.get("add_intercept", True))
        intercept_index = self.config.get("intercept_index")
        if intercept_index is None and add_intercept:
            if index_maps is not None:  # avro: look up the intercept key
                imap = index_maps[shard]
                idx = imap.get(INTERCEPT_KEY)
                intercept_index = idx if idx >= 0 else None
            else:  # libsvm: intercept is appended as the LAST column
                intercept_index = self._batch.num_features - 1
        self._intercept_index = intercept_index

        ntype = NormalizationType(self.config.get("normalization", "none"))
        if ntype != NormalizationType.NONE:
            self._normalization = build_normalization_context(
                ntype,
                self._summary,
                intercept_index=intercept_index,
            )
        if self.config.get("validation"):
            vspec = {**in_spec, **self.config["validation"]}
            if in_spec.get("format", "avro") == "libsvm":
                # pin the raw feature dimension to training's
                d_raw = self._batch.num_features - (1 if add_intercept else 0)
                vspec["num_features"] = d_raw
            val_data, _ = read_input(vspec, index_maps=index_maps)
            self._val_batch = val_data.batch_for(
                next(iter(val_data.feature_shards))
            )
            if self._val_batch.num_features != self._batch.num_features:
                raise ValueError(
                    f"validation feature dimension "
                    f"{self._val_batch.num_features} != training "
                    f"{self._batch.num_features}"
                )
            validate(self._val_batch, task, mode=mode)

    def train(self) -> None:
        """Warm-started lambda sweep (ModelTraining via training.train_glm;
        Driver.scala:330-348)."""
        from photon_ml_tpu.config import parse_optimizer_config
        from photon_ml_tpu.training import train_glm

        opt = parse_optimizer_config(self.config.get("optimizer"))
        lambdas = [float(x) for x in self.config.get("lambdas", [0.0])]
        self.sweep = train_glm(
            self._batch,
            self.config["task"],
            lambdas,
            opt,
            normalization=self._normalization,
            compute_variances=bool(self.config.get("compute_variances", False)),
        )
        for pos, e in enumerate(self.sweep):
            self.events.send(
                OptimizationLogEvent(
                    iteration=pos,  # position in the sweep
                    coordinate=f"lambda={e.reg_weight}",
                    seconds=0.0,
                    metrics={"solver_iterations": int(e.result.iterations)},
                )
            )

    def validate_models(self) -> None:
        """Per-lambda validation metrics + best-model selection
        (Driver.scala:448-457, computeAndLogModelMetrics + ModelSelection)."""
        from photon_ml_tpu.diagnostics import evaluate
        from photon_ml_tpu.training import select_best_model

        # cache per-model validation margins so best-model selection reuses
        # them instead of re-scoring (evaluate() computes its own means/
        # margins internally for the full metric map)
        score_cache = {}
        for e in self.sweep:
            score_cache[id(e.model)] = e.model.compute_score(self._val_batch)
            self.metrics[e.reg_weight] = evaluate(e.model, self._val_batch)
        self.best = select_best_model(
            self.sweep,
            self._val_batch,
            scorer=lambda m: score_cache[id(m)],
        )
        logger.info(
            "best lambda=%s (metric %.6g)", self.best[0].reg_weight, self.best[1]
        )

    def diagnose(self) -> dict:
        """Diagnostics + HTML/text report (Driver.scala:600-627,
        writeDiagnostics:711-731). Returns report paths."""
        from photon_ml_tpu.config import parse_optimizer_config
        from photon_ml_tpu.diagnostics import (
            Chapter,
            bootstrap_train,
            diagnose_model,
            fitting_diagnostic,
            render_html,
            render_text,
        )
        from photon_ml_tpu.diagnostics.fitting import fitting_report_sections

        model = (self.best or (self.sweep[-1], None))[0].model
        doc = diagnose_model(model, self._batch, summary=self._summary)

        opt = parse_optimizer_config(self.config.get("optimizer"))
        lam = (self.best or (self.sweep[-1], None))[0].reg_weight
        extra = []
        if self.config.get("diagnostic_fitting", True):
            fit_rep = fitting_diagnostic(
                self._batch,
                self.config["task"],
                dataclasses.replace(opt, regularization_weight=lam),
                lambdas=[lam],
                normalization=self._normalization,
            )
            extra.append(Chapter("Fitting curves", fitting_report_sections(fit_rep)))
        if self.config.get("diagnostic_bootstrap", True):
            boot = bootstrap_train(
                self._batch,
                self.config["task"],
                dataclasses.replace(opt, regularization_weight=lam),
                num_samples=int(self.config.get("bootstrap_samples", 8)),
                normalization=self._normalization,
            )
            from photon_ml_tpu.diagnostics import Section, Table

            extra.append(
                Chapter(
                    "Bootstrap confidence intervals",
                    [
                        Section(
                            "Per-coefficient summaries",
                            [
                                Table(
                                    header=["coefficient", "summary"],
                                    rows=[
                                        (j, s.to_summary_string())
                                        for j, s in enumerate(
                                            boot.coefficient_summaries
                                        )
                                    ],
                                )
                            ],
                        )
                    ],
                )
            )
        doc = dataclasses.replace(doc, chapters=list(doc.chapters) + extra)

        paths = {}
        if self.output_dir:
            os.makedirs(self.output_dir, exist_ok=True)
            html_path = os.path.join(self.output_dir, "diagnostic-report.html")
            text_path = os.path.join(self.output_dir, "diagnostic-report.txt")
            with open(html_path, "w") as f:
                f.write(render_html(doc))
            with open(text_path, "w") as f:
                f.write(render_text(doc))
            paths = {"html": html_path, "text": text_path}
        return paths

    def write_models(self) -> Optional[str]:
        """Per-lambda models: npz via the model store plus the text format
        (learned-models-text / IOUtils.writeModelsInText analog: one
        `index<TAB>value[<TAB>variance]` line per nonzero coefficient)."""
        if not self.output_dir:
            return None
        from photon_ml_tpu.data.model_store import save_glm

        text_dir = os.path.join(self.output_dir, "learned-models-text")
        os.makedirs(text_dir, exist_ok=True)
        for e in self.sweep:
            save_glm(
                e.model,
                os.path.join(self.output_dir, "models", f"lambda-{e.reg_weight}"),
            )
            means = np.asarray(e.model.coefficients.means)
            variances = e.model.coefficients.variances
            lines = []
            for j in np.nonzero(means)[0]:
                cols = [str(int(j)), repr(float(means[j]))]
                if variances is not None:
                    cols.append(repr(float(np.asarray(variances)[j])))
                lines.append("\t".join(cols))
            with open(
                os.path.join(text_dir, f"lambda-{e.reg_weight}.txt"), "w"
            ) as f:
                f.write("\n".join(lines) + "\n")
        return text_dir

    # -- pipeline ------------------------------------------------------------

    def run(self) -> dict:
        from photon_ml_tpu.utils.timing import Timer

        t = Timer().start()
        trace_out = self.config.get("trace_out")
        if trace_out:
            telemetry.configure(trace_out=trace_out)
        self.events.send(SetupEvent(config=self.config))

        self._assert_stage(DriverStage.INIT)
        with timed("preprocess"):
            self.preprocess()
        self._update_stage(DriverStage.PREPROCESSED)
        self.events.send(
            TrainingStartEvent(num_rows=int(np.sum(
                np.asarray(self._batch.weights) > 0
            )))
        )

        self._assert_stage(DriverStage.PREPROCESSED)
        with timed("train"):
            self.train()
        self._update_stage(DriverStage.TRAINED)

        if self._val_batch is not None:
            self._assert_stage(DriverStage.TRAINED)
            with timed("validate"):
                self.validate_models()
            self._update_stage(DriverStage.VALIDATED)

        report_paths = {}
        if self.config.get("diagnostics", False):
            self._assert_stage(
                DriverStage.VALIDATED
                if self._val_batch is not None
                else DriverStage.TRAINED
            )
            with timed("diagnose"):
                report_paths = self.diagnose()
            self._update_stage(DriverStage.DIAGNOSED)

        with timed("write models"):
            text_dir = self.write_models()

        self.events.send(
            TrainingFinishEvent(
                best_metric=self.best[1] if self.best else None,
                seconds=t.stop(),
                metrics_snapshot=telemetry.snapshot(),
            )
        )
        telemetry_out = self.config.get("telemetry_out")
        if telemetry_out:
            telemetry.flush_metrics(telemetry_out)
        if trace_out:
            telemetry.export_chrome_trace(
                trace_out, telemetry.perfetto_path(trace_out)
            )
        return {
            "stages": [s.name for s in self.stage_history],
            "lambdas": [e.reg_weight for e in self.sweep],
            "best_lambda": self.best[0].reg_weight if self.best else None,
            "best_metric": self.best[1] if self.best else None,
            "metrics": {
                str(k): {m: float(v) for m, v in mm.items()}
                for k, mm in self.metrics.items()
            },
            "models_text_dir": text_dir,
            "report": report_paths,
        }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli glm", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--config", required=True, help="JSON config path")
    parser.add_argument("--output-dir", help="override config output_dir")
    parser.add_argument(
        "--trace-out",
        help="write telemetry spans to this JSONL file (+ a sibling "
        ".perfetto.json Chrome trace); overrides config trace_out",
    )
    parser.add_argument(
        "--telemetry-out",
        help="append the final metrics snapshot to this JSONL file; "
        "overrides config telemetry_out",
    )
    args = parser.parse_args(argv)

    setup_logging()
    with open(args.config) as f:
        config = json.load(f)
    if args.trace_out:
        config["trace_out"] = args.trace_out
    if args.telemetry_out:
        config["telemetry_out"] = args.telemetry_out
    summary = GLMDriver(config, output_dir=args.output_dir).run()
    print(json.dumps(summary, default=float))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
