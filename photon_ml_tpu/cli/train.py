"""GAME training driver.

Reference analog: photon-client cli/game/training/Driver.scala:58-87 — the
staged run (prepare features -> read train/validation -> stats ->
normalization -> GameEstimator.fit -> select best -> save) becomes one
timed pipeline driven by a JSON config:

    python -m photon_ml_tpu.cli train --config train.json

Config document (coordinates order = updating sequence):

    {
      "task": "logistic",
      "input": {"format": "avro", "paths": ["train/"],
                "feature_shards": {"global": ["features"]},
                "id_columns": ["userId"], "add_intercept": true},
      "validation": {"paths": ["validate/"]},
      "coordinates": {"fixed": {"type": "fixed_effect",
                                "shard_name": "global",
                                "optimizer": {"regularization": "l2",
                                               "regularization_weight": 1.0}}},
      "num_iterations": 1,
      "evaluators": ["auc"],
      "output_dir": "out/model"
    }
"""

from __future__ import annotations

import argparse
import json
from typing import Mapping, Optional

import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.config import parse_game_config
from photon_ml_tpu.game.checkpoint import (
    CheckpointSpec,
    GracefulStop,
    TrainingInterrupted,
)
from photon_ml_tpu.game.dataset import GameDataset, build_game_dataset
from photon_ml_tpu.game.estimator import GameEstimator
from photon_ml_tpu.optim.guard import GuardSpec
from photon_ml_tpu.utils import setup_logging, timed


def read_input(
    spec: Mapping,
    is_response_required: bool = True,
    index_maps: Optional[Mapping] = None,
) -> tuple[GameDataset, Optional[Mapping]]:
    """Materialize a GameDataset from an input spec ({format, paths, ...}).

    Returns (dataset, index_maps). For Avro, ``index_maps`` (per shard) pin
    the feature space — REQUIRED at scoring time so ids match training
    (the reference ships PalDB index maps next to the model for exactly
    this, cli/game/GAMEDriver prepareFeatureMaps); built by scanning when
    absent and returned so the training driver can persist them.
    """
    spec = dict(spec)
    fmt = spec.pop("format", "avro")
    paths = spec.pop("paths")
    dr = spec.pop("date_range", None)
    dr_ago = spec.pop("date_range_days_ago", None)
    if dr or dr_ago:
        if fmt != "avro":
            raise ValueError(
                "date_range expansion is supported for avro daily "
                f"directories only, not format '{fmt}'"
            )
        # daily-directory expansion (IOUtils.getInputPathsWithinDateRange)
        from photon_ml_tpu.data.paths import expand_input_paths

        if isinstance(paths, str):
            paths = [paths]
        paths = expand_input_paths(paths, date_range=dr,
                                   date_range_days_ago=dr_ago)
    if fmt == "avro":
        shards = spec.pop("feature_shards", None)
        shards = {
            k: tuple(v) for k, v in (shards or {"features": ("features",)}).items()
        }
        add_intercept = bool(spec.pop("add_intercept", True))
        ingest = spec.pop("ingest", None)
        if ingest:
            # out-of-core path: the threaded ingest pipeline streams the
            # shard set through a bounded staging ring (parallel block
            # decode, double-buffered upload) and assembles the feature
            # payload DEVICE-side — the host never holds the whole COO.
            # Arrays are bit-identical to the in-core reader's, so the
            # fit matches the in-core fit exactly.
            from photon_ml_tpu.ingest import (
                IngestSpec,
                read_game_dataset_streamed,
            )

            data, index_maps = read_game_dataset_streamed(
                paths,
                feature_shards=shards,
                index_maps=index_maps,
                id_columns=tuple(spec.pop("id_columns", ())),
                add_intercept=add_intercept,
                is_response_required=is_response_required,
                spec=IngestSpec.from_config(ingest),
                return_index_maps=True,
            )
            return data, index_maps
        from photon_ml_tpu.data.avro import read_game_dataset_from_avro

        # ONE scan builds the index maps AND the dataset (a separate
        # index-build pass would decode the whole input twice — at
        # north-star scale that was the pipeline's dominant cost)
        data, index_maps = read_game_dataset_from_avro(
            paths,
            feature_shards=shards,
            index_maps=index_maps,
            id_columns=tuple(spec.pop("id_columns", ())),
            add_intercept=add_intercept,
            is_response_required=is_response_required,
            return_index_maps=True,
        )
        return data, index_maps
    if fmt == "libsvm":
        from photon_ml_tpu.data.libsvm import read_libsvm

        if isinstance(paths, (list, tuple)):
            if len(paths) != 1:
                raise ValueError("libsvm input takes exactly one path")
            paths = paths[0]
        lib = read_libsvm(paths)
        # "num_features" pins the RAW (pre-intercept) feature dimension so a
        # validation/scoring file whose max feature id differs from
        # training's still produces an aligned batch
        batch = lib.to_batch(
            num_features=spec.pop("num_features", None),
            add_intercept=bool(spec.pop("add_intercept", True)),
        )
        labels = np.asarray(lib.labels)
        if spec.pop("binarize_labels", True):
            labels = (labels > 0).astype(np.float64)
        shard = spec.pop("shard_name", "features")
        return (
            build_game_dataset(response=labels, feature_shards={shard: batch}),
            None,
        )
    raise ValueError(f"unknown input format '{fmt}'")


def parse_mesh_flag(raw: str):
    """``--mesh`` flag -> config ``mesh`` value.

    ``batch=N,model=M`` (either axis optional) builds the named GSPMD
    mesh; ``auto``/``on`` is the 1-D all-devices mesh; ``off``/``none``
    disables a config-file mesh."""
    text = raw.strip().lower()
    if text in ("auto", "on", "true"):
        return True
    if text in ("off", "none", "false"):
        return False
    axes: dict[str, int] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, size = part.partition("=")
        if not eq or not name:
            raise ValueError(
                f"--mesh expects 'axis=N[,axis=M]' or 'auto'/'off', got "
                f"{raw!r}"
            )
        try:
            axes[name.strip()] = int(size)
        except ValueError:
            raise ValueError(
                f"--mesh axis '{name.strip()}' needs an integer size, got "
                f"{size!r}"
            ) from None
    if not axes:
        raise ValueError(f"--mesh got no axes in {raw!r}")
    return axes


def _init_distributed_and_mesh(config: Mapping):
    """Join a multi-host fleet and build the training mesh when configured.

    Config keys (both optional):
      "distributed": {"coordinator_address", "num_processes", "process_id"}
        — explicit fleet wiring; omitted fields fall back to PHOTON_ML_*
        env vars, and on TPU pods everything auto-detects
        (SparkContextConfiguration.asYarnClient analog).
      "mesh": true/"auto" for a 1-D mesh over all (global) devices, or
        {"axis": size, ...} for an explicit shape — the GSPMD vocabulary
        is {"batch": N, "model": M} (FE rows shard over `batch`, RE
        coefficient tables over `model`; the --mesh flag spells it
        `batch=N,model=M`).
    """
    from photon_ml_tpu.parallel import multihost

    dist = config.get("distributed")
    if dist is not None:
        env = multihost.DistributedConfig.from_env()
        # flaky gloo/grpc rendezvous retries with backoff
        # (multihost.init_retries counted; FleetInitError after
        # exhaustion) — "init_retries"/"init_backoff_s" config keys
        multihost.initialize(
            multihost.DistributedConfig(
                coordinator_address=dist.get(
                    "coordinator_address", env.coordinator_address
                ),
                num_processes=dist.get("num_processes", env.num_processes),
                process_id=dist.get("process_id", env.process_id),
                auto=bool(dist.get("auto", env.auto)),
                init_retries=int(dist.get("init_retries", env.init_retries)),
                init_backoff_s=float(
                    dist.get("init_backoff_s", env.init_backoff_s)
                ),
            )
        )
    if multihost.is_multiprocess():
        # The estimator pipeline is single-controller: it reads the whole
        # input and device_puts process-local arrays, which is wrong (and
        # rejected by jax) across processes. Multi-host training drives
        # the per-process APIs instead (multihost.process_slice /
        # host_local_array / game.streaming.LocalChunk — see README
        # "Multi-host deployment"), supervised by tools/fleet.py (member
        # liveness, coordinated checkpoints, survivor-elastic relaunch);
        # the CLI stops here rather than train one divergent model per
        # host.
        raise NotImplementedError(
            "the `train` CLI does not span processes yet; write a worker "
            "with the per-process APIs and supervise it with tools/fleet "
            "(README 'Multi-host deployment' / 'Fleet supervision')"
        )
    mesh_spec = config.get("mesh")
    if not mesh_spec and dist is None:
        return None
    from photon_ml_tpu.parallel import make_mesh

    if mesh_spec in (None, True, "auto") or mesh_spec is False:
        # a configured fleet defaults to a 1-D 'data' mesh over all devices
        return None if mesh_spec is False else make_mesh()
    return make_mesh({k: int(v) for k, v in mesh_spec.items()})


def _parse_checkpoint_spec(config: Mapping) -> Optional[CheckpointSpec]:
    """Config key ``"checkpoint": {"dir", "every", "keep_last", "resume"}``
    (the --checkpoint-dir/--checkpoint-every/--resume flags).

    ``resume`` defaults to TRUE: a scheduler restarting a preempted run
    with identical argv must continue it, not wipe it. Set
    ``"resume": false`` explicitly for a fresh fit into the directory
    (which clears existing checkpoints)."""
    import dataclasses

    spec = config.get("checkpoint")
    if not spec:
        return None
    spec = dict(spec)
    if "dir" not in spec:
        raise ValueError("checkpoint config needs a 'dir' key")
    spec["directory"] = spec.pop("dir")
    # defaults come from CheckpointSpec itself — no duplicated literals
    fields = {f.name for f in dataclasses.fields(CheckpointSpec)}
    unknown = set(spec) - fields
    if unknown:
        raise ValueError(
            f"unknown checkpoint config keys: {sorted(unknown)}"
        )
    return CheckpointSpec(**spec)


_WARM_START_KEYS = {
    "dir", "delta_paths", "registry_dir", "base_version", "force",
    "lambda_factors", "lambda_points", "lambda_span", "metric", "policy",
    "quality_gate", "bootstrap_samples",
}


def _parse_warm_start(config: Mapping) -> Optional[dict]:
    """Config key ``"warm_start"`` (the ``--warm-start``/``--delta``
    flags): ``{"dir": <base checkpoint/model dir>, "delta_paths": [...],
    "registry_dir": ..., "lambda_points"/"lambda_span" or an explicit
    "lambda_factors" list, "metric", "policy", "base_version"}``."""
    spec = config.get("warm_start")
    if not spec:
        return None
    if isinstance(spec, str):
        spec = {"dir": spec}
    spec = dict(spec)
    if "dir" not in spec:
        raise ValueError("warm_start config needs a 'dir' key")
    unknown = set(spec) - _WARM_START_KEYS
    if unknown:
        raise ValueError(
            f"unknown warm_start config keys: {sorted(unknown)}"
        )
    if config.get("sweep"):
        raise ValueError(
            "warm_start and sweep are mutually exclusive — the "
            "incremental path runs its own local λ sweep "
            '(warm_start {"lambda_points": N, "lambda_span": S})'
        )
    return spec


def _run_incremental(
    config: Mapping,
    warm: dict,
    estimator: GameEstimator,
    train_data,
    validation_data,
    index_maps,
    output_dir,
    mesh,
    checkpoint_spec,
    guard,
    stop,
) -> dict:
    """The warm-start branch of the train pipeline: load the base,
    scan the delta, run the selective refresh, optionally publish with
    lineage. Returns the freshness summary block."""
    from photon_ml_tpu.incremental import (
        load_warm_start,
        local_lambda_factors,
        publish_incremental,
        scan_delta,
    )

    with timed("warm-start restore"):
        ws = load_warm_start(warm["dir"], mesh=mesh)
    if ws.model is None:
        from photon_ml_tpu.incremental import WarmStartError

        raise WarmStartError(
            f"{warm['dir']} holds a streamed coefficient-table "
            "checkpoint, not a full GAME model — the train CLI "
            "warm-starts coordinate descent; streamed tables warm-start "
            "StreamingRandomEffectTrainer via the API "
            "(incremental.load_warm_start + "
            "ShardedCoefficientTable.from_coefficients)"
        )
    delta_scan = None
    delta_paths = list(warm.get("delta_paths") or ())
    if delta_paths:
        base_vocabs = {}
        for sub in ws.model.models.values():
            id_name = getattr(sub, "id_name", None)
            vocab = getattr(sub, "vocab", None)
            if id_name is not None and vocab is not None:
                base_vocabs[id_name] = vocab
        if base_vocabs:
            with timed("delta scan"):
                # the delta IS re-decoded here (it was already read as
                # the combined stream's suffix) — only its id columns
                # are needed, and at the 5%-of-base scale a delta is by
                # premise, the second decode is bounded by that fraction
                delta_spec = {**config["input"], "paths": delta_paths}
                delta_spec.pop("ingest", None)  # scan is host-side
                # delta paths are explicit shards, never daily dirs
                delta_spec.pop("date_range", None)
                delta_spec.pop("date_range_days_ago", None)
                delta_data, _ = read_input(
                    delta_spec, index_maps=index_maps
                )
                delta_scan = scan_delta(
                    delta_data, base_vocabs, paths=delta_paths
                )
    if delta_scan is not None and warm.get("registry_dir"):
        # a delta whose digest the newest published version already
        # trained on is a typed refusal (StaleDeltaError) — re-running a
        # stuck cron on unchanged shards must not publish no-op versions
        from photon_ml_tpu.incremental import check_delta_freshness

        check_delta_freshness(
            warm["registry_dir"],
            delta_scan.digest,
            force=bool(warm.get("force")),
        )
    factors = warm.get("lambda_factors")
    if factors is None and warm.get("lambda_points"):
        factors = local_lambda_factors(
            points=int(warm["lambda_points"]),
            span=float(warm.get("lambda_span", 4.0)),
        )
    gate_enabled = bool(warm.get("quality_gate", True))
    bootstrap_samples = int(warm.get("bootstrap_samples", 32))
    publishing = bool(warm.get("registry_dir"))
    with timed("incremental fit"):
        result = estimator.fit_incremental(
            train_data,
            ws,
            delta=delta_scan,
            validation_data=validation_data,
            output_dir=output_dir,
            mesh=mesh,
            lambda_factors=factors,
            metric=warm.get("metric"),
            policy=warm.get("policy", "best"),
            guard=guard,
            checkpoint_spec=checkpoint_spec,
            should_stop=stop if checkpoint_spec is not None else None,
            bootstrap_samples=bootstrap_samples if publishing else 0,
        )
    gate_refusal = None
    quality = None
    if publishing:
        if not index_maps:
            raise ValueError(
                "publishing an incremental model needs index maps (avro "
                "input builds them; libsvm input cannot publish)"
            )
        from photon_ml_tpu.quality import (
            QualityGateRefused,
            game_quality_stats,
        )

        with timed("quality stats"):
            # candidate error bars on the strongest available eval set;
            # the champion comparison happens inside publish_version
            eval_data = (
                validation_data
                if validation_data is not None
                else train_data
            )
            quality = game_quality_stats(
                result.model, eval_data, num_samples=bootstrap_samples
            ).to_json()
            if result.bootstrap is not None:
                quality["bootstrap"] = result.bootstrap
        with timed("registry publish"):
            try:
                result.published_version = publish_incremental(
                    warm["registry_dir"],
                    result.model,
                    index_maps,
                    result.lineage,
                    delta=result.delta,
                    base_version=warm.get("base_version"),
                    selection=result.selection,
                    quality=quality,
                    gate_override=not gate_enabled,
                )
            except QualityGateRefused as exc:
                # a quarantined candidate is a RESULT, not a crash: the
                # refresh reports the decision and exits cleanly with
                # the champion still serving
                gate_refusal = {
                    **exc.decision.to_json(),
                    "quarantine_path": exc.quarantine_path,
                }
    freshness = {
        "base": result.lineage.to_json(),
        "lanes_solved": result.lanes_solved,
        "lanes_skipped": result.lanes_skipped,
        "bucket_solves": result.bucket_solves,
        "buckets_skipped": result.buckets_skipped,
        "new_entities": result.new_entities,
        "time_to_fresh_s": round(result.seconds, 3),
        "best_metric": result.best_metric,
    }
    if result.delta is not None:
        freshness["delta"] = result.delta.to_json()
    if result.selection is not None:
        freshness["selection"] = result.selection.to_json()
    if result.published_version:
        freshness["published_version"] = result.published_version
    if quality is not None:
        freshness["quality"] = quality
    if gate_refusal is not None:
        freshness["quality_gate"] = gate_refusal
    return freshness


def _persist_feature_artifacts(output_dir, index_maps, train_data) -> None:
    """Persist the feature space next to the saved models (final/ and
    best/ feature-indexes — scoring must reproduce training-time feature
    ids, the prepareFeatureMaps/PalDB analog) plus the per-shard feature
    statistics (writeBasicStatistics analog). Shared by the plain fit
    and the incremental warm-start branch so a refreshed model dir
    carries exactly the artifacts a trained one does."""
    import os

    with timed("save index maps"):
        for shard, imap in index_maps.items():
            for sub in ("final", "best"):
                imap.save(
                    os.path.join(output_dir, sub, "feature-indexes", shard)
                )
    from photon_ml_tpu.data.avro import write_feature_summary
    from photon_ml_tpu.data.stats import summarize

    with timed("save feature summaries"):
        stats_dir = os.path.join(output_dir, "feature-stats")
        os.makedirs(stats_dir, exist_ok=True)
        for shard, imap in index_maps.items():
            write_feature_summary(
                os.path.join(stats_dir, f"{shard}.avro"),
                summarize(train_data.batch_for(shard)),
                imap,
            )


def _parse_guard_spec(config: Mapping) -> Optional[GuardSpec]:
    """Config key ``"guard"``: true (default — divergence recovery on),
    false to disable, or an object overriding GuardSpec fields (defaults
    come from GuardSpec itself)."""
    import dataclasses

    spec = config.get("guard", True)
    if spec is False:
        return None
    if spec is True:
        return GuardSpec()
    spec = dict(spec)
    unknown = set(spec) - {f.name for f in dataclasses.fields(GuardSpec)}
    if unknown:
        raise ValueError(f"unknown guard config keys: {sorted(unknown)}")
    return GuardSpec(**spec)


def _parse_heartbeat(config: Mapping, telemetry_out: Optional[str]):
    """Config key ``"heartbeat"``: true (default — a progress line every
    ~30 s once a fit runs longer than that), false to disable, or
    ``{"every": seconds, "out": jsonl_path}``. The JSONL sink defaults to
    ``telemetry_out`` so heartbeat lines land next to the metrics
    snapshot and the run report picks them up."""
    spec = config.get("heartbeat", True)
    # False / null / 0 all disable ({} means enabled with defaults)
    if spec is None or spec is False or spec == 0:
        return None
    if isinstance(spec, (int, float)) and not isinstance(spec, bool):
        spec = {"every": float(spec)}  # bare number = interval seconds
    from photon_ml_tpu.telemetry.progress import DEFAULT_INTERVAL_S, Heartbeat

    every = DEFAULT_INTERVAL_S
    out = telemetry_out
    if spec is not True:
        spec = dict(spec)
        unknown = set(spec) - {"every", "out"}
        if unknown:
            raise ValueError(
                f"unknown heartbeat config keys: {sorted(unknown)}"
            )
        every = float(spec.get("every", every))
        out = spec.get("out", out)
        if every <= 0:
            return None
    return Heartbeat(interval=every, jsonl_path=out)


def _maybe_write_report(
    config: Mapping,
    summary: dict,
    trace_out: Optional[str],
    telemetry_out: Optional[str],
) -> None:
    """Config key ``report_out`` (the ``--report-out`` flag): render the
    run report (markdown + a sibling ``.json`` compare baseline) from this
    run's sinks — or the live in-process telemetry when no sinks were
    configured — and record both paths in the summary."""
    report_out = config.get("report_out")
    if not report_out:
        return
    # same per-member suffixing as the trace/telemetry sinks: N fleet
    # members pointed at one --report-out must not last-writer-win
    report_out = telemetry.member_artifact_path(report_out)
    from photon_ml_tpu.telemetry.report import RunReport

    ckpt_dir = (config.get("checkpoint") or {}).get("dir")
    if trace_out or telemetry_out:
        report = RunReport.load(
            trace=trace_out, telemetry=telemetry_out, checkpoint_dir=ckpt_dir
        )
    else:
        report = RunReport.from_live(checkpoint_dir=ckpt_dir)
    with open(report_out, "w", encoding="utf-8") as fh:
        fh.write(report.to_markdown())
    json_path = (
        report_out[: -len(".md")] + ".json"
        if report_out.endswith(".md")
        else report_out + ".json"
    )
    report.save_json(json_path)
    summary["report"] = report_out
    summary["report_json"] = json_path


def run(config: Mapping, output_dir: Optional[str] = None) -> dict:
    """Execute the training pipeline; returns a JSON-safe summary.

    Config keys ``trace_out`` (span JSONL; a sibling ``.perfetto.json``
    Chrome trace is written at the end) and ``telemetry_out`` (metrics
    snapshot JSONL) — the ``--trace-out`` / ``--telemetry-out`` flags.
    ``heartbeat`` (on by default) emits a progress line every ~30 s during
    the fit; ``report_out`` renders the run report when training ends.

    Fault tolerance: the ``checkpoint`` config object persists coordinate-
    descent state per step and resumes from it; a SIGTERM/SIGINT during the
    fit finishes the current step, writes a final checkpoint, and exits
    with ``"interrupted": true`` in the summary (graceful preemption). The
    ``guard`` object (on by default) retries diverging solves with
    escalating L2 damping and rolls back solves that stay divergent."""
    # an armed PHOTON_FAULT_PLAN must be LOUD: this run will fail on
    # purpose (chaos harness subprocesses arm themselves this way)
    faults.warn_if_armed()
    game_config = parse_game_config(config)
    output_dir = output_dir or config.get("output_dir")
    checkpoint_spec = _parse_checkpoint_spec(config)
    guard = _parse_guard_spec(config)
    warm = _parse_warm_start(config)
    if config.get("sweep"):
        # the vmapped sweep path has no checkpoint/resume or mesh support
        # yet; accepting the keys and silently not honoring them is worse
        # than refusing (a "checkpointed" sweep would also swallow the
        # scheduler's SIGTERM via GracefulStop and then save NOTHING).
        # Guard config is inert in sweep mode (on-by-default, so it
        # cannot be an explicit request) — divergent lanes surface
        # through per-config convergence reasons instead.
        if checkpoint_spec is not None:
            raise ValueError(
                "checkpointing is not supported with a sweep yet — drop "
                'the "checkpoint" config (sweeps are one batched solve '
                "per coordinate, not a resumable step sequence)"
            )
        if config.get("mesh"):
            raise ValueError(
                "mesh training is not supported with a GAME sweep yet — "
                'drop the "mesh" config / --mesh flag (plain-GLM sweeps '
                "can shard the config axis via sweep.sweep_glm(mesh=...))"
            )
    stop = GracefulStop()
    if checkpoint_spec is not None:
        # without a checkpoint there is nothing durable to write on SIGTERM;
        # default signal handling (die immediately) is then the right call
        stop.install()
    mesh = _init_distributed_and_mesh(config)

    # explicit --trace-out/--telemetry-out paths get the SAME per-member
    # suffixing the PHOTON_*_OUT env path applies (telemetry.identity):
    # under a fleet each member writes trace.proc-<i>.jsonl instead of
    # last-writer-winning one file; single-process paths pass through
    # untouched. Resolved AFTER _init_distributed_and_mesh so the
    # multi-process-jax identity mode sees the initialized process index
    # (PHOTON_PROC_ID needs no jax and works either way); no spans are
    # lost — the first traced phase is the data read below.
    trace_out = config.get("trace_out")
    if trace_out:
        trace_out = telemetry.member_artifact_path(trace_out)
        telemetry.configure(trace_out=trace_out)
    telemetry_out = config.get("telemetry_out")
    if telemetry_out:
        telemetry_out = telemetry.member_artifact_path(telemetry_out)
    xprof_cfg = config.get("xprof")
    if xprof_cfg:
        # arm a jax.profiler capture window around the Kth dispatch (the
        # steady state AFTER compiles) — telemetry.profile refuses on the
        # CPU backend unless forced, so a CPU smoke run just logs a note
        if isinstance(xprof_cfg, str):
            xprof_cfg = {"dir": xprof_cfg}
        xprof_kwargs = {}
        if xprof_cfg.get("arm_at") is not None:
            xprof_kwargs["arm_at"] = int(xprof_cfg["arm_at"])
        if xprof_cfg.get("capture") is not None:
            xprof_kwargs["capture"] = int(xprof_cfg["capture"])
        telemetry.profile.configure_xprof(
            telemetry.member_artifact_path(str(xprof_cfg["dir"])),
            **xprof_kwargs,
        )

    input_spec = dict(config["input"])
    if warm and warm.get("delta_paths"):
        # the combined stream: yesterday's shards ∪ today's delta. The
        # deterministic planner keeps yesterday's chunk ids/offsets
        # stable under the appended files (the resume contract).
        paths = input_spec.get("paths")
        if isinstance(paths, str):
            paths = [paths]
        dr = input_spec.pop("date_range", None)
        dr_ago = input_spec.pop("date_range_days_ago", None)
        if dr or dr_ago:
            # expand the BASE daily directories here, before appending:
            # delta files are explicit shards, not daily dirs — expanding
            # the combined list would silently drop them
            from photon_ml_tpu.data.paths import expand_input_paths

            paths = expand_input_paths(
                list(paths), date_range=dr, date_range_days_ago=dr_ago
            )
        input_spec["paths"] = list(paths) + list(warm["delta_paths"])
    with timed("read training data"):
        train_data, index_maps = read_input(input_spec)
    validation_data = None
    if config.get("validation"):
        with timed("read validation data"):
            vspec = {**config["input"], **config["validation"]}
            # validation shares the TRAINING feature space
            validation_data, _ = read_input(vspec, index_maps=index_maps)

    estimator = GameEstimator(game_config)
    if config.get("event_listeners"):
        # dotted-path listener specs, import-registered at driver startup
        # (the --event-listeners class loading of Driver.scala:110-118)
        from photon_ml_tpu.utils.events import load_listeners

        for listener in load_listeners(config["event_listeners"]):
            estimator.events.register(listener)
    heartbeat = _parse_heartbeat(config, telemetry_out)
    try:
        if heartbeat is not None:
            heartbeat.start()
        if config.get("sweep"):
            # multi-λ sweep + best-model selection INSTEAD of a single
            # fit: the winner lands under <output_dir>/best (and in the
            # sweep registry_dir, if configured) — cli/sweep.py
            from photon_ml_tpu.cli.sweep import run_sweep_fit

            with timed("sweep"):
                sweep_summary = run_sweep_fit(
                    estimator,
                    config["sweep"],
                    train_data,
                    validation_data,
                    index_maps,
                    output_dir,
                )
            summary = {
                "sweep": sweep_summary,
                "best_metric": sweep_summary["selected_metric"],
                "output_dir": output_dir,
                "num_rows": train_data.num_rows,
            }
            if output_dir is not None and index_maps is not None:
                import os

                with timed("save index maps"):
                    for shard, imap in index_maps.items():
                        imap.save(
                            os.path.join(
                                output_dir, "best", "feature-indexes", shard
                            )
                        )
            if telemetry_out:
                summary["telemetry"] = telemetry.flush_metrics(telemetry_out)
            if trace_out:
                telemetry.export_chrome_trace(
                    trace_out, telemetry.perfetto_path(trace_out)
                )
            _maybe_write_report(config, summary, trace_out, telemetry_out)
            return summary
        if warm:
            # incremental warm-start refresh INSTEAD of a full fit:
            # selective RE re-solve over the combined stream, lineage
            # recorded end to end (cli/train._run_incremental)
            freshness = _run_incremental(
                config, warm, estimator, train_data, validation_data,
                index_maps, output_dir, mesh, checkpoint_spec, guard,
                stop,
            )
            summary = {
                "freshness": freshness,
                "best_metric": freshness.get("best_metric"),
                "output_dir": output_dir,
                "num_rows": train_data.num_rows,
            }
            if output_dir is not None and index_maps is not None:
                _persist_feature_artifacts(
                    output_dir, index_maps, train_data
                )
            if telemetry_out:
                summary["telemetry"] = telemetry.flush_metrics(
                    telemetry_out
                )
            if trace_out:
                telemetry.export_chrome_trace(
                    trace_out, telemetry.perfetto_path(trace_out)
                )
            _maybe_write_report(config, summary, trace_out, telemetry_out)
            return summary
        with timed("fit"):
            result = estimator.fit(
                train_data,
                validation_data=validation_data,
                output_dir=output_dir,
                mesh=mesh,
                checkpoint_spec=checkpoint_spec,
                guard=guard,
                should_stop=stop if checkpoint_spec is not None else None,
            )
    except TrainingInterrupted as e:
        # graceful preemption: the final checkpoint is on disk; report and
        # stop instead of crashing (a restart with the same argv resumes)
        summary = {
            "interrupted": True,
            "interrupted_at_step": e.step,
            "checkpoint": e.checkpoint_path,
            "output_dir": output_dir,
            "num_rows": train_data.num_rows,
        }
        if telemetry_out:
            summary["telemetry"] = telemetry.flush_metrics(telemetry_out)
        if trace_out:
            telemetry.export_chrome_trace(
                trace_out, telemetry.perfetto_path(trace_out)
            )
        _maybe_write_report(config, summary, trace_out, telemetry_out)
        return summary
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        # close any still-open xprof capture window (idempotent): a fit
        # shorter than the arm threshold, or one interrupted mid-window,
        # must not leave the jax profiler tracing into a dead directory
        telemetry.profile.stop_xprof()

    if output_dir is not None and index_maps is not None:
        _persist_feature_artifacts(output_dir, index_maps, train_data)

    summary = {
        "output_dir": output_dir,
        "best_metric": result.best_metric,
        "num_rows": train_data.num_rows,
        "history": [
            {k: v for k, v in entry.items()}
            for entry in result.history
        ],
    }
    if telemetry_out:
        summary["telemetry"] = telemetry.flush_metrics(telemetry_out)
    if trace_out:
        # one Chrome/Perfetto trace next to the span JSONL, ready to open
        telemetry.export_chrome_trace(
            trace_out, telemetry.perfetto_path(trace_out)
        )
    _maybe_write_report(config, summary, trace_out, telemetry_out)
    return summary


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli train", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--config", required=True, help="JSON config path")
    parser.add_argument("--output-dir", help="override config output_dir")
    parser.add_argument(
        "--trace-out",
        help="write telemetry spans to this JSONL file (+ a sibling "
        ".perfetto.json Chrome trace); overrides config trace_out",
    )
    parser.add_argument(
        "--telemetry-out",
        help="append the final metrics snapshot to this JSONL file; "
        "overrides config telemetry_out",
    )
    parser.add_argument(
        "--report-out",
        help="write the run report (markdown; + a sibling .json compare "
        "baseline) here when training ends — the `cli report` rendering "
        "of this run's trace/telemetry/checkpoints (config report_out)",
    )
    parser.add_argument(
        "--xprof-dir",
        metavar="DIR",
        help="capture a jax.profiler (xprof) trace into this directory, "
        "armed around the Kth instrumented-jit dispatch (see "
        "--xprof-arm) so compiles are excluded; refused on the CPU "
        "backend (config key xprof.dir)",
    )
    parser.add_argument(
        "--xprof-arm",
        type=int,
        metavar="K",
        help="dispatch count at which the --xprof-dir capture window "
        "opens (default 20 — past warmup/compile; config xprof.arm_at)",
    )
    parser.add_argument(
        "--heartbeat-every",
        type=float,
        help="seconds between live progress heartbeat lines (default 30, "
        "so only fits longer than ~30 s emit any; 0 disables; config key "
        "heartbeat)",
    )
    parser.add_argument(
        "--mesh",
        help="train over a named device mesh: 'batch=N,model=M' shards "
        "FE rows over the batch axis and RE coefficient tables over the "
        "model axis via GSPMD (either axis may be omitted); 'auto' uses a "
        "1-D mesh over all devices; 'off' disables a config mesh "
        "(overrides config mesh)",
    )
    parser.add_argument(
        "--sweep",
        action="append",
        help="train a multi-λ sweep instead of a single fit: grid tokens "
        "like 'lambda=1e-4:1e2:log16' or 'lambda.perUser=0.1,1,10' "
        "(repeatable; needs a validation input; config key sweep.grid)",
    )
    parser.add_argument(
        "--sweep-metric",
        help="validation metric the sweep selects on (default: the "
        "task's ModelSelection metric; config sweep.metric)",
    )
    parser.add_argument(
        "--sweep-policy",
        choices=("best", "parsimonious"),
        help="sweep selection policy (config sweep.policy)",
    )
    parser.add_argument(
        "--sweep-registry-dir",
        help="publish the sweep winner here via publish_version for live "
        "ModelRegistry hot-swap (config sweep.registry_dir)",
    )
    parser.add_argument(
        "--warm-start",
        metavar="DIR",
        help="incremental retrain: warm-start every coordinate from this "
        "base artifact (a --checkpoint-dir step checkpoint, a streamed "
        "chunk checkpoint, or a saved model dir) instead of fitting from "
        "scratch; with --delta, only the touched random-effect lanes "
        "re-solve (config key warm_start.dir)",
    )
    parser.add_argument(
        "--delta",
        action="append",
        metavar="PATH",
        help="delta shard(s) appended to the input paths (repeatable); "
        "their interned entity-id columns drive the touched-lane mask — "
        "requires --warm-start (config warm_start.delta_paths)",
    )
    parser.add_argument(
        "--refresh-registry-dir",
        metavar="DIR",
        help="publish the refreshed model here via publish_version with "
        "the lineage record (base checkpoint, delta digest) in metadata "
        "(config warm_start.registry_dir)",
    )
    parser.add_argument(
        "--lambda-points",
        type=int,
        help="run a local descending-λ sweep of this many lanes around "
        "the incumbent regularization during an incremental retrain, "
        "selected by sweep.select policies (needs a validation input; "
        "config warm_start.lambda_points)",
    )
    parser.add_argument(
        "--ingest-workers",
        type=int,
        help="read Avro input through the out-of-core ingest pipeline "
        "with this many parallel block-decode workers (0 = one per host "
        "core); enables config input.ingest with defaults when absent",
    )
    parser.add_argument(
        "--prefetch-depth",
        type=int,
        help="how many device-ready chunks the ingest pipeline keeps "
        "ahead of the solve (bounded double-buffer depth; config "
        "input.ingest.prefetch_depth)",
    )
    parser.add_argument(
        "--checkpoint-dir",
        help="persist coordinate-descent state here after each "
        "(iteration, coordinate) step; SIGTERM/SIGINT then writes a final "
        "checkpoint before exiting (overrides config checkpoint.dir)",
    )
    parser.add_argument(
        "--checkpoint-every",
        type=int,
        help="save every N steps (default 1; overrides checkpoint.every)",
    )
    parser.add_argument(
        "--resume",
        action="store_true",
        help="resume from the newest valid checkpoint in --checkpoint-dir, "
        "skipping completed steps (this is already the default when a "
        "checkpoint dir is configured — a restarted job continues; set "
        'config checkpoint {"resume": false} for a fresh fit that clears '
        "the directory)",
    )
    args = parser.parse_args(argv)

    setup_logging()
    with open(args.config) as f:
        config = json.load(f)
    if args.mesh:
        config["mesh"] = parse_mesh_flag(args.mesh)
    if (
        args.sweep or args.sweep_metric or args.sweep_policy
        or args.sweep_registry_dir
    ):
        from photon_ml_tpu.cli.sweep import merge_sweep_flags

        sweep_cfg = merge_sweep_flags(
            config,
            grid=args.sweep,
            metric=args.sweep_metric,
            policy=args.sweep_policy,
            registry_dir=args.sweep_registry_dir,
        )
        if not sweep_cfg or not sweep_cfg.get("grid"):
            parser.error(
                "--sweep-metric/--sweep-policy/--sweep-registry-dir need a "
                "grid: pass --sweep lambda=... (or config sweep.grid)"
            )
        config["sweep"] = sweep_cfg
    if (
        args.warm_start or args.delta or args.refresh_registry_dir
        or args.lambda_points is not None
    ):
        ws = dict(config.get("warm_start") or {})
        if args.warm_start:
            ws["dir"] = args.warm_start
        if args.delta:
            ws["delta_paths"] = list(ws.get("delta_paths") or ()) + list(
                args.delta
            )
        if args.refresh_registry_dir:
            ws["registry_dir"] = args.refresh_registry_dir
        if args.lambda_points is not None:
            ws["lambda_points"] = args.lambda_points
        if "dir" not in ws:
            parser.error(
                "--delta/--refresh-registry-dir/--lambda-points need "
                "--warm-start (or a config warm_start.dir)"
            )
        config["warm_start"] = ws
    if args.ingest_workers is not None or args.prefetch_depth is not None:
        inp = dict(config.get("input") or {})
        ing = inp.get("ingest")
        ing = dict(ing) if isinstance(ing, dict) else {}
        if args.ingest_workers is not None:
            ing["workers"] = args.ingest_workers
        if args.prefetch_depth is not None:
            ing["prefetch_depth"] = args.prefetch_depth
        inp["ingest"] = ing
        config["input"] = inp
    if args.trace_out:
        config["trace_out"] = args.trace_out
    if args.telemetry_out:
        config["telemetry_out"] = args.telemetry_out
    if args.report_out:
        config["report_out"] = args.report_out
    if args.xprof_dir or args.xprof_arm is not None:
        xp = config.get("xprof")
        xp = dict(xp) if isinstance(xp, dict) else (
            {"dir": xp} if xp else {}
        )
        if args.xprof_dir:
            xp["dir"] = args.xprof_dir
        if args.xprof_arm is not None:
            xp["arm_at"] = args.xprof_arm
        if "dir" not in xp:
            parser.error(
                "--xprof-arm needs --xprof-dir (or a config xprof.dir)"
            )
        config["xprof"] = xp
    if args.heartbeat_every is not None:
        if args.heartbeat_every <= 0:
            config["heartbeat"] = False
        else:
            hb = config.get("heartbeat")
            hb = dict(hb) if isinstance(hb, dict) else {}
            hb["every"] = args.heartbeat_every
            config["heartbeat"] = hb
    if args.checkpoint_dir or args.checkpoint_every is not None or args.resume:
        ckpt = dict(config.get("checkpoint") or {})
        if args.checkpoint_dir:
            ckpt["dir"] = args.checkpoint_dir
        if args.checkpoint_every is not None:
            # invalid values (e.g. 0) reach CheckpointSpec validation
            ckpt["every"] = args.checkpoint_every
        if args.resume:
            ckpt["resume"] = True
        if "dir" not in ckpt:
            parser.error("--checkpoint-every/--resume need --checkpoint-dir "
                         "(or a config checkpoint.dir)")
        config["checkpoint"] = ckpt
    summary = run(config, output_dir=args.output_dir)
    print(json.dumps(summary, default=float))
    # a preempted run is incomplete: exit non-zero so schedulers restart it
    return 75 if summary.get("interrupted") else 0


if __name__ == "__main__":
    raise SystemExit(main())
