"""Freshness-conductor driver: ``cli pipeline`` — the supervised daemon
that tails a delta directory and keeps the serving registry fresh.

One long-running process unifying the three freshness tiers::

    python -m photon_ml_tpu.cli pipeline --config train.json \
        --base ckpt/ --delta-dir deltas/ --registry-dir registry/ \
        --workdir pipeline-work/ --interval-s 30 \
        --escalate-touched-fraction 0.5 --escalate-after-cycles 24 \
        --status-port 8080

Each cycle: ``delta_digest`` detects new/changed shards, ``scan_delta``
finds the touched entities, the masked re-solve refreshes only their
lanes, ``publish_incremental`` lands a lineage-linked registry version
(carrying the nearline-vs-delta reconciliation decision), and the live
``ModelRegistry`` hot-swaps it. Touched-fraction or cycle-count
thresholds escalate to a full retrain into a fresh base generation under
the workdir. Event→served staleness p99 is the run's headline gauge.

SIGTERM/SIGINT finish the in-flight cycle, then exit 75 (the scheduler
restart convention); a restarted daemon re-seeds its digest cursor from
the newest published lineage and continues. ``--status-file`` /
``--status-port`` expose the ``/statusz`` fleet-status document with
per-cycle pipeline facts under ``members["0"].pipeline``.
"""

from __future__ import annotations

import argparse
import json
import signal

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.utils import setup_logging


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli pipeline",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("--config", required=True,
                        help="training JSON config path")
    parser.add_argument(
        "--base", "--warm-start", dest="base", required=True, metavar="DIR",
        help="warm-start base artifact (step checkpoint or saved model "
        "dir); escalations re-base onto new generations under --workdir",
    )
    parser.add_argument(
        "--delta-dir", required=True, metavar="DIR",
        help="directory tailed for delta shards (see --delta-glob)",
    )
    parser.add_argument(
        "--registry-dir", required=True, metavar="DIR",
        help="serving registry: each cycle publishes the next version "
        "here and hot-swaps the live engine",
    )
    parser.add_argument(
        "--workdir", required=True, metavar="DIR",
        help="daemon scratch: escalation base generations and the "
        "fleet-status heartbeat directory live here",
    )
    parser.add_argument(
        "--cycles", type=int, default=0, metavar="N",
        help="stop after N cycles (default 0 = run until SIGTERM)",
    )
    parser.add_argument(
        "--interval-s", type=float, default=5.0,
        help="seconds between delta polls (default 5)",
    )
    parser.add_argument(
        "--delta-glob", default="*.avro",
        help="shard pattern tailed inside --delta-dir (default *.avro)",
    )
    parser.add_argument(
        "--escalate-touched-fraction", type=float, default=0.5,
        help="escalate to a full retrain when a delta touches at least "
        "this fraction of any coordinate's entities (default 0.5; >=1 "
        "disables)",
    )
    parser.add_argument(
        "--escalate-after-cycles", type=int, default=0,
        help="escalate to a full retrain after this many incremental "
        "cycles since the last full one (default 0 = never by count)",
    )
    parser.add_argument(
        "--no-quality-gate", action="store_true",
        help="bypass the champion/challenger publish gate: candidate "
        "quality stats are still computed and recorded (decision "
        "'bypassed'), but a regression beyond the champion's bootstrap "
        "CI no longer quarantines the version",
    )
    parser.add_argument(
        "--bootstrap-samples", type=int, default=32,
        help="bootstrap resamples behind the published error bars "
        "(AUC CI + masked-lane coefficient CIs); default 32, 0 disables",
    )
    parser.add_argument(
        "--no-serve", action="store_true",
        help="publish without hot-swapping a live ModelRegistry (staleness "
        "then measures event->published)",
    )
    parser.add_argument(
        "--status-file", metavar="PATH",
        help="write the fleet-status JSON document here each cycle",
    )
    parser.add_argument(
        "--status-port", type=int, metavar="PORT",
        help="serve the live status document over HTTP /statusz "
        "(0 = ephemeral port)",
    )
    parser.add_argument(
        "--telemetry-out",
        help="append the final metrics snapshot to this JSONL file",
    )
    parser.add_argument(
        "--report-out",
        help="write the run report (with its Pipeline section) here when "
        "the daemon stops",
    )
    args = parser.parse_args(argv)

    setup_logging()
    # an armed PHOTON_FAULT_PLAN must be LOUD: this run will fail on
    # purpose (the chaos harness arms its subprocesses this way)
    faults.warn_if_armed()
    with open(args.config) as f:
        config = json.load(f)
    # the conductor owns checkpointing (escalation generations under the
    # workdir); an inherited train-config checkpoint dir would alias the
    # warm-start base — same hazard cli refresh drops it for
    config.pop("checkpoint", None)

    from photon_ml_tpu.pipeline import FreshnessPipeline, PipelineSpec

    pipe = FreshnessPipeline(PipelineSpec(
        config=config,
        delta_dir=args.delta_dir,
        base_dir=args.base,
        registry_dir=args.registry_dir,
        workdir=args.workdir,
        interval_s=args.interval_s,
        max_cycles=args.cycles,
        delta_glob=args.delta_glob,
        escalate_touched_fraction=args.escalate_touched_fraction,
        escalate_after_cycles=args.escalate_after_cycles,
        serve=not args.no_serve,
        status_file=args.status_file,
        status_port=args.status_port,
        quality_gate=not args.no_quality_gate,
        bootstrap_samples=args.bootstrap_samples,
    ))

    def _on_signal(signum, frame):
        pipe.request_stop()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    summary = pipe.run()
    if args.telemetry_out:
        summary["telemetry"] = telemetry.flush_metrics(args.telemetry_out)
    if args.report_out:
        from photon_ml_tpu.telemetry.report import RunReport

        report = RunReport.from_live()
        with open(args.report_out, "w", encoding="utf-8") as fh:
            fh.write(report.to_markdown())
        json_path = (
            args.report_out[: -len(".md")] + ".json"
            if args.report_out.endswith(".md")
            else args.report_out + ".json"
        )
        report.save_json(json_path)
        summary["report"] = args.report_out
        summary["report_json"] = json_path
    print(json.dumps(summary, default=float))
    # an interrupted daemon is incomplete: exit 75 so schedulers restart
    return 75 if summary.get("interrupted") else 0


if __name__ == "__main__":
    raise SystemExit(main())
