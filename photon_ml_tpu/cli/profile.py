"""Profiler-capture driver: wrap any CLI run in ``jax.profiler.trace``.

    python -m photon_ml_tpu.cli profile --profile-dir prof/ -- \
        train --config train.json --trace-out run.trace.jsonl

Everything after ``--`` is a normal CLI invocation (train, score, glm,
serve, report, ...). The wrapped run executes inside a profiler capture:
``--profile-dir`` receives the xplane/TensorBoard artifacts (open with
TensorBoard's profile plugin or xprof), and every telemetry span is
mirrored as a ``jax.profiler.TraceAnnotation`` so our span tree
(``fit > cd_iteration > coordinate:<name>``) lines up with the XLA
executable timeline — the "which executable ran inside which phase"
question BENCH_r05 could not answer.

Degrades gracefully: a backend that cannot start the profiler logs a
warning and runs the wrapped command unprofiled (exit code is the wrapped
command's either way); ``--no-annotations`` disables the span mirror for
overhead-sensitive captures.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

EXIT_USAGE = 2


def main(argv: Optional[list] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # split at the first bare "--": left = profile flags, right = the
    # wrapped CLI invocation
    if "--" in argv:
        split = argv.index("--")
        own, wrapped = argv[:split], argv[split + 1:]
    else:
        own, wrapped = argv, []
    parser = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli profile",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument(
        "--profile-dir",
        required=True,
        help="directory for the xplane/TensorBoard profiler capture",
    )
    parser.add_argument(
        "--no-annotations",
        action="store_true",
        help="do not mirror telemetry spans as profiler annotations",
    )
    args = parser.parse_args(own)
    if not wrapped:
        parser.error(
            "nothing to profile: pass the wrapped command after `--`, "
            "e.g. `profile --profile-dir prof/ -- train --config t.json`"
        )

    import jax

    from photon_ml_tpu.cli.__main__ import main as cli_main
    from photon_ml_tpu.telemetry import trace

    if not args.no_annotations:
        trace.set_annotation_factory(jax.profiler.TraceAnnotation)
    started = False
    try:
        jax.profiler.start_trace(args.profile_dir)
        started = True
    except Exception as e:  # noqa: BLE001 — capture is best-effort
        print(
            f"warning: profiler capture unavailable ({e}); running "
            "unprofiled",
            file=sys.stderr,
        )
    try:
        rc = cli_main(wrapped)
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                print(
                    f"profiler capture written to {args.profile_dir} "
                    "(open with TensorBoard's profile plugin)",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001
                print(
                    f"warning: profiler capture failed to finalize: {e}",
                    file=sys.stderr,
                )
        if not args.no_annotations:
            trace.set_annotation_factory(None)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
