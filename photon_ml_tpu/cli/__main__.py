"""CLI dispatcher: python -m photon_ml_tpu.cli {train|score|serve} ...

Reference analog: the photon-client spark-submit mains
(cli/game/training/Driver.scala:327, cli/game/scoring/Driver.scala:255)."""

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m photon_ml_tpu.cli {train|refresh|pipeline|sweep|score|serve|glm|index|report|profile} [options]")
        print("  train --config <json> [--output-dir <dir>] [--sweep lambda=...]   GAME training")
        print("  refresh --config <json> --warm-start <dir> [--delta <avro>...]  incremental warm-start retrain")
        print("  pipeline --config <json> --base <dir> --delta-dir <dir> --registry-dir <dir>  supervised freshness daemon")
        print("  sweep --config <json> --sweep lambda=...     multi-λ sweep + best-model selection")
        print("  score --model-dir <dir> --config <json> [--output <avro>]")
        print("  serve --registry-dir <dir> | --model-dir <dir>  online scoring server")
        print("  glm   --config <json> [--output-dir <dir>]   staged legacy GLM")
        print("  index --input <avro...> --output <dir>       feature index build")
        print("  report --trace <jsonl> [--telemetry <jsonl>] [--compare <json>]")
        print("  profile --profile-dir <dir> -- <command...>  profiler capture around any run")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "train":
        from photon_ml_tpu.cli.train import main as train_main

        return train_main(rest)
    if cmd == "refresh":
        from photon_ml_tpu.cli.refresh import main as refresh_main

        return refresh_main(rest)
    if cmd == "pipeline":
        from photon_ml_tpu.cli.pipeline import main as pipeline_main

        return pipeline_main(rest)
    if cmd == "sweep":
        from photon_ml_tpu.cli.sweep import main as sweep_main

        return sweep_main(rest)
    if cmd == "score":
        from photon_ml_tpu.cli.score import main as score_main

        return score_main(rest)
    if cmd == "serve":
        from photon_ml_tpu.cli.serve import main as serve_main

        return serve_main(rest)
    if cmd == "glm":
        from photon_ml_tpu.cli.glm import main as glm_main

        return glm_main(rest)
    if cmd == "index":
        from photon_ml_tpu.cli.index import main as index_main

        return index_main(rest)
    if cmd == "report":
        from photon_ml_tpu.cli.report import main as report_main

        return report_main(rest)
    if cmd == "profile":
        from photon_ml_tpu.cli.profile import main as profile_main

        return profile_main(rest)
    print(
        f"unknown command '{cmd}' (expected train|refresh|pipeline|sweep|score|serve|glm|index|report|profile)",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
