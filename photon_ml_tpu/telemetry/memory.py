"""HBM accounting: device memory stats as metrics, per-phase peak gauges,
table-size estimates, and a pre-flight headroom check.

The north-star fits hold multi-GB device state (HBM-resident coefficient
tables, tiled design matrices) for hours; today the first memory signal is
an XLA OOM that kills the run. This module turns ``device.memory_stats()``
— populated on TPU/GPU backends, absent on CPU — into:

- :func:`hbm_stats` / :func:`record_device_memory`: raw per-device
  bytes-in-use/limit, published as ``memory.*`` gauges;
- :func:`record_phase_memory`: per-phase ``memory.phase.<name>.bytes_in_use``
  gauges plus a max-tracked ``memory.phase.<name>.peak_bytes`` (the HBM
  profile of ``fit > cd_iteration > coordinate:<name>`` in the run report);
- :func:`estimate_table_bytes` / :func:`estimate_batch_bytes`: predicted
  residency of a coefficient table / a pytree batch before it is uploaded;
- :func:`check_headroom`: warn (log + ``memory.headroom_warnings`` counter)
  BEFORE a predicted allocation exceeds free HBM, instead of OOMing a
  coordinate mid-fit.

Backends without memory stats (the CPU test mesh) degrade gracefully:
every probe returns None and the headroom check passes as "unknown".
Tests inject deterministic stats via :func:`set_stats_provider`.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Mapping, Optional, Sequence

from photon_ml_tpu.telemetry import metrics

__all__ = [
    "hbm_stats",
    "set_stats_provider",
    "record_device_memory",
    "record_device_watermarks",
    "device_spread_bytes",
    "record_phase_memory",
    "estimate_table_bytes",
    "estimate_batch_bytes",
    "check_headroom",
    "reset",
]

logger = logging.getLogger("photon_ml_tpu.telemetry.memory")

#: Fraction of the device's byte limit treated as usable by the headroom
#: check — XLA needs workspace beyond the caller's own arrays.
DEFAULT_SAFETY_FRACTION = 0.92

# test/injection hook: zero-arg callable returning a memory_stats()-shaped
# mapping (or None); overrides the real device probe when set
_stats_provider: Optional[Callable[[], Optional[Mapping[str, Any]]]] = None


def set_stats_provider(
    provider: Optional[Callable[[], Optional[Mapping[str, Any]]]]
) -> None:
    """Override the device probe (deterministic tests / simulations).

    ``None`` restores the real ``device.memory_stats()`` probe."""
    global _stats_provider
    _stats_provider = provider


def hbm_stats(device=None) -> Optional[dict[str, int]]:
    """``{"bytes_in_use", "bytes_limit", ...}`` for ``device`` (default:
    the first device), or None when the backend publishes no memory stats
    (CPU) — callers must treat None as "unknown", not "zero"."""
    if _stats_provider is not None and device is None:
        raw = _stats_provider()
        return dict(raw) if raw else None
    try:
        import jax

        if device is None:
            device = jax.devices()[0]
    except Exception:  # noqa: BLE001 — accounting must never fail a caller
        return None
    probe = getattr(device, "memory_stats", None)
    if probe is None:
        return None
    try:
        raw = probe()
    except Exception:  # noqa: BLE001 — some backends raise NotImplemented
        return None
    return dict(raw) if raw else None


def record_device_memory(devices: Optional[Sequence] = None) -> dict[str, int]:
    """Publish ``memory.device.<id>.bytes_in_use`` / ``.bytes_limit``
    gauges for every device that exposes stats; returns the total in-use
    bytes per device id (empty on statless backends)."""
    if devices is None:
        try:
            import jax

            devices = jax.devices()
        except Exception:  # noqa: BLE001
            return {}
    out: dict[str, int] = {}
    for d in devices:
        stats = hbm_stats(d)
        if not stats:
            continue
        did = getattr(d, "id", len(out))
        in_use = int(stats.get("bytes_in_use", 0))
        metrics.gauge(f"memory.device.{did}.bytes_in_use").set(in_use)
        if "bytes_limit" in stats:
            metrics.gauge(f"memory.device.{did}.bytes_limit").set(
                int(stats["bytes_limit"])
            )
        out[str(did)] = in_use
    return out


def record_device_watermarks(
    devices: Optional[Sequence] = None, phase: Optional[str] = None
) -> dict[str, int]:
    """Sample per-device HBM in-use and max-track high-watermark gauges.

    The executable profiler calls this on its sampling cadence, so the
    peaks are LIVE — they catch the transient allocation spike mid-solve
    that the end-of-phase ``record_phase_memory`` probe sleeps through.
    Gauges: ``memory.device.<id>.peak_bytes`` (per-run high-watermark)
    and, when ``phase`` is given, ``memory.phase.<phase>.device.<id>
    .peak_bytes``. Returns the sampled in-use bytes per device id (empty
    on statless backends — absence stays unknown, never zero)."""
    per_device = record_device_memory(devices)
    for did, in_use in per_device.items():
        peak = metrics.gauge(f"memory.device.{did}.peak_bytes")
        if peak.value is None or in_use > peak.value:
            peak.set(in_use)
        if phase:
            phase_peak = metrics.gauge(
                f"memory.phase.{phase}.device.{did}.peak_bytes"
            )
            if phase_peak.value is None or in_use > phase_peak.value:
                phase_peak.set(in_use)
    return per_device


def device_spread_bytes() -> Optional[int]:
    """Per-device HBM in-use spread (max - min bytes across all devices
    that expose stats), or None with fewer than two reporting devices.

    ``make_mesh`` publishes the per-device ``memory.device.<id>.*`` gauges
    at mesh build; this refreshes them from the live probe, falls back to
    the already-published gauges (statless probes, offline tests), and
    reduces to the ONE number that makes shard imbalance visible (a
    balanced entity sharding keeps it near zero). Also published as the
    ``memory.device_spread_bytes`` gauge so run reports loaded from a
    metrics JSONL can render it."""
    per_device = record_device_memory()
    if len(per_device) < 2:
        prefix, suffix = "memory.device.", ".bytes_in_use"
        per_device = {
            name[len(prefix):-len(suffix)]: value
            for name, value in metrics.snapshot()["gauges"].items()
            if name.startswith(prefix) and name.endswith(suffix)
            and value is not None
        }
    if len(per_device) < 2:
        return None
    spread = max(per_device.values()) - min(per_device.values())
    metrics.gauge("memory.device_spread_bytes").set(spread)
    return int(spread)


def record_phase_memory(phase: str, device=None) -> Optional[int]:
    """Sample HBM in-use under ``phase`` and max-track its peak gauge.

    Gauges: ``memory.phase.<phase>.bytes_in_use`` (last sample) and
    ``memory.phase.<phase>.peak_bytes`` (max over the run). Returns the
    sampled bytes, or None when the backend has no stats."""
    stats = hbm_stats(device)
    if not stats or "bytes_in_use" not in stats:
        return None
    in_use = int(stats["bytes_in_use"])
    metrics.gauge(f"memory.phase.{phase}.bytes_in_use").set(in_use)
    peak = metrics.gauge(f"memory.phase.{phase}.peak_bytes")
    if peak.value is None or in_use > peak.value:
        peak.set(in_use)
    metrics.gauge("memory.bytes_in_use").set(in_use)
    if "bytes_limit" in stats:
        metrics.gauge("memory.bytes_limit").set(int(stats["bytes_limit"]))
    return in_use


def estimate_table_bytes(
    num_entities: int, dim: int, itemsize: int = 4
) -> int:
    """Predicted HBM residency of an [num_entities, dim] coefficient
    table (the ShardedCoefficientTable / RE-bucket model envelope)."""
    return int(num_entities) * int(dim) * int(itemsize)


def estimate_batch_bytes(batch: Any) -> int:
    """Predicted device residency of a pytree batch: the sum of its array
    leaves' ``nbytes`` (host numpy leaves report what the upload will
    cost; device leaves report what is already resident)."""
    try:
        import jax

        leaves = jax.tree.leaves(batch)
    except Exception:  # noqa: BLE001 — accounting only
        leaves = [batch]
    return int(sum(getattr(x, "nbytes", 0) for x in leaves))


def check_headroom(
    predicted_bytes: int,
    label: str = "",
    device=None,
    safety_fraction: float = DEFAULT_SAFETY_FRACTION,
) -> Optional[bool]:
    """Will ``predicted_bytes`` more fit in free HBM?

    Returns True (fits), False (predicted to exceed — a warning is logged
    and ``memory.headroom_warnings`` incremented BEFORE the OOM would
    happen), or None (backend has no stats; nothing to check). Publishes
    ``memory.free_bytes`` either way stats exist.
    """
    stats = hbm_stats(device)
    if not stats or "bytes_limit" not in stats:
        return None
    in_use = int(stats.get("bytes_in_use", 0))
    limit = int(stats["bytes_limit"])
    free = int(limit * safety_fraction) - in_use
    metrics.gauge("memory.free_bytes").set(max(free, 0))
    if predicted_bytes <= free:
        return True
    metrics.counter("memory.headroom_warnings").inc()
    logger.warning(
        "HBM headroom: %s predicts %.2f GB but only %.2f GB free "
        "(%.2f/%.2f GB in use; safety %.0f%%) — expect an OOM or spill",
        label or "allocation",
        predicted_bytes / 2**30,
        max(free, 0) / 2**30,
        in_use / 2**30,
        limit / 2**30,
        safety_fraction * 100,
    )
    return False


def reset() -> None:
    """Restore defaults (test isolation): drop any injected stats
    provider. Gauges/counters live in the metrics registry and are cleared
    by ``metrics.reset()``."""
    set_stats_provider(None)
