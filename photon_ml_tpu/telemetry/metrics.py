"""Process-global metrics registry: counters, gauges, histograms.

The reference ships solve telemetry through PhotonOptimizationLogEvent
listeners and per-phase Timed logs; nothing aggregates across a run. This
registry is the aggregation point: any layer increments a named counter
(``metrics.counter("device_fetches").inc()``), sets a gauge, or feeds a
histogram, and ``snapshot()`` returns one JSON-safe dict for the finish
event, the bench JSON, and the ``--telemetry-out`` flush.

Thread-safe (one registry lock; metric mutation is a few ns under it) and
allocation-light so hot paths can afford it. Histograms keep a bounded
uniform reservoir for percentiles plus exact count/sum/min/max.

Metric names use dotted lowercase (``events.OptimizationLogEvent`` counts
keep the event class name verbatim).
"""

from __future__ import annotations

import datetime
import json
import threading
from typing import Any, Optional

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "counter",
    "peek_counter",
    "gauge",
    "histogram",
    "snapshot",
    "register_snapshot_provider",
    "flush_jsonl",
    "reset",
]

_PERCENTILES = (5, 25, 50, 75, 95, 99)


class Counter:
    """Monotonically increasing count (int or float increments)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.value: float = 0
        self._lock = lock

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.value: Optional[float] = None
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming distribution: exact count/sum/min/max plus a bounded
    uniform reservoir (deterministic LCG, no global RNG state) for
    percentiles."""

    __slots__ = (
        "name", "count", "total", "min", "max", "_sample", "_cap",
        "_lcg", "_lock",
    )

    def __init__(self, name: str, lock: threading.Lock, cap: int = 4096):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._sample: list[float] = []
        self._cap = cap
        self._lcg = 0x9E3779B9
        self._lock = lock

    def _observe_locked(self, v: float) -> None:
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if len(self._sample) < self._cap:
            self._sample.append(v)
        else:
            # Vitter reservoir sampling with a private LCG stream
            self._lcg = (self._lcg * 1103515245 + 12345) & 0x7FFFFFFF
            j = self._lcg % self.count
            if j < self._cap:
                self._sample[j] = v

    def observe(self, v: float) -> None:
        with self._lock:
            self._observe_locked(float(v))

    def observe_many(self, values) -> None:
        """Vectorized bulk observe: per-entity tracker vectors arrive here
        once per coordinate update, so the per-element Python loop (and the
        registry lock hold) must not scale with entity count."""
        import numpy as np

        arr = np.asarray(
            values if hasattr(values, "__len__") else list(values), dtype=float
        ).ravel()
        if arr.size == 0:
            return
        if arr.size < 64:  # small batches: the scalar path is cheaper
            with self._lock:
                for v in arr:
                    self._observe_locked(float(v))
            return
        with self._lock:
            prior = self.count
            self.count += int(arr.size)
            self.total += float(arr.sum())
            mn, mx = float(arr.min()), float(arr.max())
            self.min = mn if self.min is None else min(self.min, mn)
            self.max = mx if self.max is None else max(self.max, mx)
            room = self._cap - len(self._sample)
            if room > 0:
                take = arr[:room]
                self._sample.extend(take.tolist())
                prior += int(take.size)
                arr = arr[room:]
            if arr.size:
                # batch reservoir: element with global index g replaces slot
                # j ~ U[0, g) when j < cap (later duplicates win, matching
                # the sequential algorithm); seeded from the LCG state so
                # the stream stays deterministic
                rng = np.random.default_rng(self._lcg)
                g = np.arange(prior + 1, prior + arr.size + 1)
                j = (rng.random(arr.size) * g).astype(np.int64)
                hit = j < self._cap
                if hit.any():
                    sample = np.asarray(self._sample)
                    sample[j[hit]] = arr[hit]
                    self._sample = sample.tolist()
                self._lcg = int(rng.integers(1, 2**31))

    def summary(self) -> dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0}
            out = {
                "count": self.count,
                "sum": self.total,
                "mean": self.total / self.count,
                "min": self.min,
                "max": self.max,
            }
            sample = sorted(self._sample)
            n = len(sample)
            for p in _PERCENTILES:
                idx = min(n - 1, max(0, round(p / 100 * (n - 1))))
                out[f"p{p}"] = sample[idx]
            return out


class MetricsRegistry:
    """Named metric store; get-or-create accessors, one snapshot dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._providers: dict[str, Any] = {}

    def register_snapshot_provider(self, name: str, fn) -> None:
        """Attach a named section to every :meth:`snapshot`: ``fn()``
        must return a JSON-safe value, published under ``name`` beside
        ``counters``/``gauges``/``histograms``. Layers with structured
        state the scalar registries cannot carry (the quality layer's
        per-version drift sketches) ride the same snapshot/flush/report
        surface this way instead of growing unbounded per-version gauge
        names. Providers survive :meth:`reset` (they are wiring, not
        run state) and a provider that raises is skipped — a broken
        section must never take ``/metricsz`` down."""
        reserved = ("counters", "gauges", "histograms")
        if name in reserved:
            raise ValueError(f"snapshot section name {name!r} is reserved")
        with self._lock:
            self._providers[name] = fn

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, self._lock)
            return c

    def peek_counter(self, name: str) -> Optional[float]:
        """A counter's value WITHOUT registering it: monitors (the
        heartbeat) must not force absent counters into the snapshot as
        zeros — downstream consumers read absence as "unknown"."""
        with self._lock:
            c = self._counters.get(name)
            return None if c is None else c.value

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, self._lock)
            return g

    def peek_gauge(self, name: str) -> Optional[float]:
        """A gauge's value WITHOUT registering it (same "absence stays
        unknown" contract as :meth:`peek_counter`)."""
        with self._lock:
            g = self._gauges.get(name)
            return None if g is None else g.value

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, self._lock)
            return h

    def snapshot(self) -> dict[str, Any]:
        """JSON-safe state of every metric: ``{"counters": {name: value},
        "gauges": {name: value}, "histograms": {name: summary}}``."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            providers = dict(self._providers)
        out: dict[str, Any] = {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {
                n: h.summary() for n, h in sorted(histograms.items())
            },
        }
        for name, fn in sorted(providers.items()):
            try:
                section = fn()
            except Exception:  # noqa: BLE001 — observability, never control
                continue
            if section is not None:
                out[name] = section
        return out

    def flush_jsonl(self, path: str) -> dict[str, Any]:
        """Append one ``{"type": "metrics", ...}`` line to ``path`` and
        return the snapshot that was written. In a fleet the line carries
        ``process_index``/``hostname`` so the aggregate report
        (telemetry.fleet_report) can attribute it without trusting the
        file name alone."""
        from photon_ml_tpu.telemetry import identity

        snap = self.snapshot()
        line = {
            "type": "metrics",
            "wall_time": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "snapshot": snap,
        }
        proc = identity.fleet_process_index()
        if proc is not None:
            line["process_index"] = proc
            line["hostname"] = identity.hostname()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(line, default=str) + "\n")
        return snap

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: Process-global registry; module-level helpers delegate to it.
REGISTRY = MetricsRegistry()

counter = REGISTRY.counter
peek_counter = REGISTRY.peek_counter
gauge = REGISTRY.gauge
peek_gauge = REGISTRY.peek_gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot
register_snapshot_provider = REGISTRY.register_snapshot_provider
flush_jsonl = REGISTRY.flush_jsonl
reset = REGISTRY.reset
