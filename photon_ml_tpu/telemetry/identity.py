"""Fleet process identity for telemetry artifacts.

Every telemetry primitive used to be process-global AND process-blind: a
2-process gloo fit pointed both members' ``PHOTON_TRACE_OUT`` at the same
file and the last writer won. This module is the one place the telemetry
layer learns *which fleet member it is*, so that

- artifact paths can be suffixed per member
  (``trace.jsonl`` -> ``trace.proc-0.jsonl``, :func:`member_artifact_path`);
- trace headers / metric snapshots / heartbeat lines can carry
  ``process_index``/``hostname`` fields the fleet aggregator
  (:mod:`photon_ml_tpu.telemetry.fleet_report`) attributes rows by.

Identity resolution, in priority order:

1. ``PHOTON_PROC_ID`` (and optional ``PHOTON_PROC_COUNT``) — set by the
   fleet supervisor (tools/fleet.py) for each worker BEFORE launch, so
   identity exists before (and without) jax ever importing;
2. ``jax.process_index()`` — but only when jax is ALREADY imported and
   multi-process: telemetry configuration must never be the thing that
   initializes a backend;
3. none: single-process runs keep unsuffixed paths and unchanged formats.

Kept dependency-free (os/sys/socket only) so both ``trace`` and
``metrics`` can import it without cycles.
"""

from __future__ import annotations

import os
import socket
import sys
from typing import Optional

__all__ = [
    "ENV_PROC_ID",
    "ENV_PROC_COUNT",
    "fleet_process_index",
    "fleet_process_count",
    "hostname",
    "member_artifact_path",
]

ENV_PROC_ID = "PHOTON_PROC_ID"
ENV_PROC_COUNT = "PHOTON_PROC_COUNT"


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        value = int(raw)
    except ValueError:
        return None  # malformed env must not fail telemetry setup
    return value if value >= 0 else None


def fleet_process_index() -> Optional[int]:
    """This process's fleet member index, or ``None`` outside a fleet.

    ``PHOTON_PROC_ID`` wins (the supervisor's assignment — present before
    jax exists); otherwise an already-imported multi-process jax is
    consulted. Never imports jax itself.
    """
    env = _env_int(ENV_PROC_ID)
    if env is not None:
        return env
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        if jax.process_count() > 1:
            return int(jax.process_index())
    except Exception:  # noqa: BLE001 — identity must never fail telemetry
        return None
    return None


def fleet_process_count() -> Optional[int]:
    """The fleet size this member believes in, or ``None`` when unknown
    (same resolution order as :func:`fleet_process_index`)."""
    env = _env_int(ENV_PROC_COUNT)
    if env is not None:
        return env
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        count = int(jax.process_count())
    except Exception:  # noqa: BLE001
        return None
    return count if count > 1 else None


def hostname() -> str:
    try:
        return socket.gethostname()
    except OSError:
        return "unknown"


def member_artifact_path(path: str, proc: Optional[int] = None) -> str:
    """Suffix an artifact path per fleet member: ``trace.jsonl`` ->
    ``trace.proc-0.jsonl`` (suffix inserted before the final extension;
    extensionless paths append ``.proc-0``).

    ``proc`` defaults to :func:`fleet_process_index`; outside a fleet the
    path is returned UNCHANGED, so single-process callers keep their
    exact artifact names. Idempotent: an already-suffixed path (the
    supervisor may pre-suffix) is left alone.
    """
    if proc is None:
        proc = fleet_process_index()
    if proc is None:
        return path
    base, ext = os.path.splitext(path)
    if base.endswith(f".proc-{proc}"):
        return path
    return f"{base}.proc-{proc}{ext}"
